"""Built-in L4 Connect proxy: mTLS termination + intention enforcement.

The reference ships a managed sidecar proxy (connect/proxy/listener.go)
and a Connect-native SDK (connect/service.go) so a mesh works with no
Envoy at all: the public listener terminates TLS with the service's
CA-issued leaf, REQUIRES a client certificate chaining to the mesh
roots, reads the peer's spiffe:// URI SAN, asks the intention graph
whether that source may reach this destination, and only then pipes
bytes to the local application.  Upstream listeners do the reverse:
accept plaintext from the local app, dial the target's public listener
with our leaf, and verify the server presented the EXPECTED service
identity (not just any valid mesh cert) before forwarding.

This module is that data plane.  Certificates come from callables so a
CA rotation picks up new leaves/roots on the next connection without
restarting listeners (the reference's proxy watches leaf/root updates
the same way).
"""

from __future__ import annotations

import os
import socket
import ssl
import tempfile
import threading
from typing import Callable, List, Optional, Tuple

# lazy crypto (same gate as connect/ca.py / tlsutil.py): the module
# must import without the 'cryptography' package — only SPIFFE peer
# verification on a live mTLS splice needs the real parser
try:  # pragma: no cover - import guard
    from cryptography import x509
    HAVE_CRYPTO = True
except ImportError:  # pragma: no cover
    x509 = None
    HAVE_CRYPTO = False

from consul_tpu.connect import intentions as imod
from consul_tpu.utils.net import shutdown_and_close
from consul_tpu.servicemgr import expose_paths_by_port

_COPY_CHUNK = 65536


def _pipe(a: socket.socket, b: socket.socket) -> None:
    """Bidirectional byte pump; returns when either side closes."""

    def one_way(src, dst):
        try:
            while True:
                chunk = src.recv(_COPY_CHUNK)
                if not chunk:
                    break
                dst.sendall(chunk)
        except OSError:
            pass
        finally:
            # half-close so the peer's read loop ends too
            for s, how in ((dst, socket.SHUT_WR), (src, socket.SHUT_RD)):
                try:
                    s.shutdown(how)
                except OSError:
                    pass

    t = threading.Thread(target=one_way, args=(a, b), daemon=True)
    t.start()
    one_way(b, a)
    t.join(timeout=5.0)


def peer_spiffe_uri(tls_sock: ssl.SSLSocket) -> Optional[str]:
    """The spiffe:// URI SAN from the peer's (already chain-verified)
    certificate."""
    if not HAVE_CRYPTO:
        raise RuntimeError(
            "peer_spiffe_uri requires the 'cryptography' package "
            "(X.509 SAN parsing)")
    der = tls_sock.getpeercert(binary_form=True)
    if not der:
        return None
    cert = x509.load_der_x509_certificate(der)
    try:
        sans = cert.extensions.get_extension_for_class(
            x509.SubjectAlternativeName).value
    except x509.ExtensionNotFound:
        return None
    for uri in sans.get_values_for_type(x509.UniformResourceIdentifier):
        if uri.startswith("spiffe://"):
            return uri
    return None


class _CertFiles:
    """python-ssl needs cert/key as FILES; cache them per-material so
    each rotation writes once, not per connection."""

    def __init__(self):
        self._dir = tempfile.mkdtemp(prefix="connect-proxy-")
        self._cached: Tuple[str, str] = ("", "")
        self._paths = (os.path.join(self._dir, "cert.pem"),
                       os.path.join(self._dir, "key.pem"))
        self._lock = threading.Lock()

    def paths(self, cert_pem: str, key_pem: str) -> Tuple[str, str]:
        with self._lock:
            if (cert_pem, key_pem) != self._cached:
                cpath, kpath = self._paths
                fd = os.open(kpath, os.O_CREAT | os.O_WRONLY
                             | os.O_TRUNC, 0o600)
                with os.fdopen(fd, "w") as f:
                    f.write(key_pem)
                with open(cpath, "w") as f:
                    f.write(cert_pem)
                self._cached = (cert_pem, key_pem)
            return self._paths


class TlsMaterial:
    """SSL contexts rebuilt when the leaf/roots change (rotation-safe).

    `leaf_fn() -> {"CertPEM","PrivateKeyPEM",...}`,
    `roots_fn() -> [{"RootCert",...}]` — the same shapes CAManager and
    the proxycfg snapshot carry."""

    def __init__(self, leaf_fn: Callable[[], dict],
                 roots_fn: Callable[[], List[dict]]):
        self.leaf_fn = leaf_fn
        self.roots_fn = roots_fn
        self._files = _CertFiles()
        self._lock = threading.Lock()
        self._cache = {}        # (kind, material-key) -> context

    def _material(self):
        leaf = self.leaf_fn()
        roots = "".join(r["RootCert"] for r in self.roots_fn())
        return leaf, roots

    def _context(self, kind: str) -> ssl.SSLContext:
        leaf, roots = self._material()
        material = (leaf["CertPEM"], roots)
        with self._lock:
            hit = self._cache.get(kind)
            if hit is not None and hit[0] == material:
                return hit[1]
            cpath, kpath = self._files.paths(leaf["CertPEM"],
                                             leaf["PrivateKeyPEM"])
            if kind == "server":
                ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
                ctx.verify_mode = ssl.CERT_REQUIRED
            else:
                ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
                # identity is the URI SAN, checked explicitly against
                # the expected SPIFFE id — hostname rules don't apply
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_REQUIRED
            ctx.load_cert_chain(cpath, kpath)
            ctx.load_verify_locations(cadata=roots)
            # per-kind slot: server/client contexts coexist; a rotation
            # replaces only the rebuilt kind's stale entry
            self._cache[kind] = (material, ctx)
            return ctx

    def server_context(self) -> ssl.SSLContext:
        return self._context("server")

    def client_context(self) -> ssl.SSLContext:
        return self._context("client")


class _Listener:
    """Shared accept-loop scaffolding: bind, per-connection serve
    threads, clean shutdown.  Subclasses implement _serve(conn)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._lsock = socket.socket()
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((host, port))
        self._lsock.listen(64)
        self.port = self._lsock.getsockname()[1]
        self._running = False
        self._threads: List[threading.Thread] = []

    def start(self) -> None:
        self._running = True
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        self._running = False
        shutdown_and_close(self._lsock)

    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _ = self._lsock.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)
            self._threads = [x for x in self._threads if x.is_alive()]

    def _serve(self, conn: socket.socket) -> None:  # pragma: no cover
        raise NotImplementedError


class PublicListener(_Listener):
    """Inbound side (connect/proxy/listener.go NewPublicListener):
    mTLS-terminate, authorize the peer SPIFFE id against intentions,
    pipe to the local app."""

    def __init__(self, tls: TlsMaterial,
                 authorize: Callable[[str], Tuple[bool, str]],
                 app_addr: Tuple[str, int],
                 host: str = "127.0.0.1", port: int = 0):
        super().__init__(host, port)
        self.tls = tls
        self.authorize = authorize
        self.app_addr = app_addr
        # observability: how many conns each decision saw
        self.stats = {"allowed": 0, "denied": 0, "tls_failed": 0}

    def _serve(self, conn: socket.socket) -> None:
        try:
            try:
                tls_conn = self.tls.server_context().wrap_socket(
                    conn, server_side=True)
            except (ssl.SSLError, OSError):
                # no/bad client cert: refused before any app byte
                self.stats["tls_failed"] += 1
                conn.close()
                return
            uri = peer_spiffe_uri(tls_conn)
            if uri is None:
                # a mesh-root-signed cert with NO spiffe:// URI SAN is
                # unidentifiable — reject outright rather than letting
                # default-allow intentions admit source "" (the
                # reference's connect authz errors on such certs)
                self.stats["denied"] += 1
                tls_conn.close()
                return
            ok, _reason = self.authorize(uri)
            if not ok:
                self.stats["denied"] += 1
                tls_conn.close()
                return
            self.stats["allowed"] += 1
            try:
                app = socket.create_connection(self.app_addr,
                                               timeout=10)
            except OSError:
                tls_conn.close()
                return
            _pipe(tls_conn, app)
            tls_conn.close()
            app.close()
        except Exception:
            try:
                conn.close()
            except OSError:
                pass


def _read_http_head(conn: socket.socket, cap: int,
                    on_bad=None) -> Optional[tuple]:
    """Accumulate one HTTP request head off `conn` up to `cap` bytes.
    Returns (head, body_start) or None after answering 431/closing —
    shared by every plaintext-HTTP listener so framing limits cannot
    diverge between them.  `on_bad` is called once when the cap trips
    (stats hook)."""
    buf = b""
    while b"\r\n\r\n" not in buf:
        if len(buf) > cap:
            if on_bad is not None:
                on_bad()
            _http_respond(conn, 431, "Request Header Too Large")
            conn.close()
            return None
        try:
            chunk = conn.recv(4096)
        except OSError:
            conn.close()
            return None
        if not chunk:
            conn.close()
            return None
        buf += chunk
    head, _, body_start = buf.partition(b"\r\n\r\n")
    return head, body_start


def _http_respond(conn, code: int, reason: str) -> None:
    body = f"{code} {reason}\n".encode()
    try:
        conn.sendall(
            f"HTTP/1.1 {code} {reason}\r\n"
            f"content-length: {len(body)}\r\n"
            f"connection: close\r\n\r\n".encode() + body)
    except OSError:
        pass


class ExposeListener(_Listener):
    """Expose-path listener: PLAINTEXT HTTP on its own port, no mTLS,
    no intention RBAC — the escape hatch that lets non-mesh callers
    (HTTP health checks, metrics scrapers) reach specific paths of a
    Connect-only app (Expose.Paths,
    agent/structs/connect_proxy_config.go:198,551; the xDS shape is
    the exposed_path_* listener in xds.listeners).

    Only requests whose path EXACTLY matches an exposed path forward
    to 127.0.0.1:local_path_port; everything else gets 404 before any
    app byte."""

    def __init__(self, paths: dict, host: str = "127.0.0.1",
                 port: int = 0):
        super().__init__(host, port)
        # path -> local_path_port for THIS listener port
        self.paths = dict(paths)
        self.stats = {"allowed": 0, "denied": 0}

    _HEAD_CAP = 64 * 1024

    def _serve(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(10)
            got = _read_http_head(conn, self._HEAD_CAP)
            if got is None:
                return
            head, body = got
            parsed = HttpUpstreamListener._parse_head(head)
            if parsed is None:
                _http_respond(conn, 400, "Bad Request")
                conn.close()
                return
            _method, path, _qs, _headers, _query, _proto = parsed
            lpp = self.paths.get(path)
            if lpp is None:
                self.stats["denied"] += 1
                _http_respond(conn, 404, "Not Found")
                conn.close()
                return
            self.stats["allowed"] += 1
            try:
                app = socket.create_connection(("127.0.0.1", lpp),
                                               timeout=10)
            except OSError:
                _http_respond(conn, 502, "Bad Gateway")
                conn.close()
                return
            app.sendall(head + b"\r\n\r\n" + body)
            _pipe(conn, app)
            conn.close()
            app.close()
        except Exception:
            try:
                conn.close()
            except OSError:
                pass


class UpstreamListener(_Listener):
    """Outbound side (proxy upstream listener): local plaintext in,
    mTLS to the target's public listener out, server identity pinned
    to the expected SPIFFE id."""

    def __init__(self, tls: TlsMaterial, expect_uri: str,
                 resolve: Callable[[], Optional[Tuple[str, int]]],
                 host: str = "127.0.0.1", port: int = 0):
        super().__init__(host, port)
        self.tls = tls
        self.expect_uri = expect_uri
        self.resolve = resolve
        self.stats = {"connected": 0, "identity_mismatch": 0,
                      "no_endpoint": 0}

    def _serve(self, conn: socket.socket) -> None:
        try:
            target = self.resolve()
            if target is None:
                self.stats["no_endpoint"] += 1
                conn.close()
                return
            try:
                raw = socket.create_connection(target, timeout=10)
                tls_conn = self.tls.client_context().wrap_socket(raw)
            except (ssl.SSLError, OSError):
                conn.close()
                return
            # the chain verified against mesh roots; now pin the
            # IDENTITY: any valid mesh cert is not enough, it must be
            # the service we meant to reach (connect/tls.go verify).
            # A tuple/set means any of several pinned identities (a
            # tcp chain with cross-service failover pins every leg,
            # the way the reference adds failover SANs)
            uri = peer_spiffe_uri(tls_conn)
            allowed = self.expect_uri if isinstance(
                self.expect_uri, (tuple, set, frozenset, list)) \
                else (self.expect_uri,)
            if uri not in allowed:
                self.stats["identity_mismatch"] += 1
                tls_conn.close()
                conn.close()
                return
            self.stats["connected"] += 1
            _pipe(conn, tls_conn)
            tls_conn.close()
            conn.close()
        except Exception:
            try:
                conn.close()
            except OSError:
                pass


class HttpUpstreamListener(_Listener):
    """L7 outbound side: parse the local app's HTTP/1.1 request head,
    select a route from the upstream's compiled discovery chain
    (connect/l7.py route table — the same table the xDS layer emits as
    RDS), pick a cluster by weight, dial the chosen TARGET over mTLS
    pinned to that service's identity, and relay.

    This is what makes splitters/routers move real traffic with the
    built-in proxy: a 90/10 service-splitter measurably splits
    connections 90/10, a header-match router steers to the matched leg.
    One request per connection (Connection: close semantics), matching
    the managed-proxy simplicity bar rather than Envoy's connection
    pooling."""

    _HEAD_CAP = 65536

    def __init__(self, tls: TlsMaterial,
                 table_fn: Callable[[], List[dict]],
                 resolve_target: Callable[[str],
                                          Optional[Tuple[str, int]]],
                 expect_uri: Callable[[str], str],
                 host: str = "127.0.0.1", port: int = 0,
                 rng=None,
                 resolve_groups: Optional[Callable[
                     [str], List[List[Tuple[str, int]]]]] = None):
        super().__init__(host, port)
        self.tls = tls
        self.table_fn = table_fn
        self.resolve_target = resolve_target
        # priority-ordered endpoint GROUPS (primary, failover...) for
        # hash-based sticky selection within each priority level
        self.resolve_groups = resolve_groups
        self.expect_uri = expect_uri
        import random
        self._rng = rng if rng is not None else random.Random()
        self._rng_lock = threading.Lock()
        self.stats = {"routed": 0, "no_route": 0, "no_endpoint": 0,
                      "identity_mismatch": 0, "bad_request": 0}
        # per-target connection counts: the observable the split test
        # asserts on
        self.target_counts: dict = {}

    def _roll(self) -> float:
        with self._rng_lock:
            return self._rng.random()

    @staticmethod
    def _parse_head(head: bytes):
        # connect/l7.py parse_http_head: repeated field lines combine
        # per RFC 7230 §3.2.2 so a split Connection header can't dodge
        # the hop-by-hop strip; parsing lives next to the route table
        # it feeds (and unit-tests without the TLS stack)
        from consul_tpu.connect import l7
        return l7.parse_http_head(head)

    _respond = staticmethod(_http_respond)

    def _serve(self, conn: socket.socket) -> None:
        from consul_tpu.connect import l7
        try:
            conn.settimeout(10)

            def _on_bad():
                self.stats["bad_request"] += 1

            got = _read_http_head(conn, self._HEAD_CAP, on_bad=_on_bad)
            if got is None:
                return
            head, body_start = got
            parsed = self._parse_head(head)
            if parsed is None:
                self.stats["bad_request"] += 1
                self._respond(conn, 400, "Bad Request")
                conn.close()
                return
            method, path, qs, headers, query, proto = parsed
            route = l7.select_route(self.table_fn(), method, path,
                                    headers, query)
            if route is None or not route["clusters"]:
                self.stats["no_route"] += 1
                self._respond(conn, 404, "No Route")
                conn.close()
                return
            target = l7.pick_cluster(route["clusters"], self._roll())
            out_path = path
            pr = route.get("prefix_rewrite")
            if pr and route["match"].get("PathPrefix"):
                out_path = pr + path[len(route["match"]["PathPrefix"]):]
            elif pr and route["match"].get("PathExact"):
                out_path = pr
            # sticky hashing (ring_hash/maglev): the same hash-policy
            # key always orders the same endpoint first — the builtin
            # proxy honoring what the emitted RDS asks of a real Envoy
            try:
                peer_ip = conn.getpeername()[0]
            except OSError:
                peer_ip = ""
            key = l7.hash_key(route.get("lb"), method, path, headers,
                              query, peer_ip)
            tls_conn = self._dial(target, route, key)
            if tls_conn is None:
                self._respond(conn, 503, "No Healthy Upstream")
                conn.close()
                return
            self.stats["routed"] += 1
            self.target_counts[target] = \
                self.target_counts.get(target, 0) + 1
            full = out_path + ("?" + qs if qs else "")
            first, _, rest_head = head.decode("latin-1").partition("\r\n")
            # hop-by-hop stripping (l7.strip_hop_headers): Connection
            # itself plus everything its token list nominates, plus
            # keep-alive.  Then force close: this relay is one-
            # request-per-connection, and a keep-alive upstream would
            # hold it open until the idle timeout.
            kept = l7.strip_hop_headers(rest_head.split("\r\n"),
                                        headers.get("connection", ""))
            kept.append("connection: close")
            new_head = (f"{method} {full} {proto}\r\n"
                        + "\r\n".join(kept)).encode("latin-1")
            try:
                tls_conn.sendall(new_head + b"\r\n\r\n" + body_start)
            except OSError:
                tls_conn.close()
                conn.close()
                return
            _pipe(conn, tls_conn)
            tls_conn.close()
            conn.close()
        except Exception:
            try:
                conn.close()
            except OSError:
                pass

    def _dial(self, target: str, route: dict, key=None):
        """mTLS to the picked target with identity pinning; retries
        connect failures when the route's retry policy asks
        (routes.go RetryPolicy connect-failure).  A sticky-hash `key`
        orders candidates within each priority group via rendezvous
        hashing (connect/l7.py pick_endpoint)."""
        from consul_tpu.connect import l7
        attempts = 1 + int((route.get("retry") or {}).get(
            "num_retries", 0) or 0)
        for _ in range(attempts):
            if self.resolve_groups is not None:
                candidates = [ep for group in
                              self.resolve_groups(target)
                              for ep in l7.pick_endpoint(group, key)]
            else:
                ep = self.resolve_target(target)
                candidates = [ep] if ep is not None else []
            if not candidates:
                self.stats["no_endpoint"] += 1
                continue
            allowed = self.expect_uri(target)   # constant per call
            if isinstance(allowed, str):
                allowed = (allowed,)
            for ep in candidates:
                try:
                    raw = socket.create_connection(ep, timeout=10)
                    tls_conn = self.tls.client_context().wrap_socket(
                        raw)
                except (ssl.SSLError, OSError):
                    self.stats["no_endpoint"] += 1
                    continue
                uri = peer_spiffe_uri(tls_conn)
                if uri not in allowed:
                    self.stats["identity_mismatch"] += 1
                    tls_conn.close()
                    continue
                return tls_conn
        return None


class ApiProxy:
    """Standalone data plane driven purely by the agent HTTP API — the
    `consul connect proxy` process shape (command/connect/proxy): runs
    in its own process, fetches the leaf + roots from the agent,
    authorizes inbound peers via /v1/agent/connect/authorize, and
    resolves upstreams via /v1/health/connect.  Leaf/root fetches are
    cached briefly so the per-connection path doesn't hammer the
    agent."""

    def __init__(self, client, service: str,
                 listen: Tuple[str, int] = ("127.0.0.1", 0),
                 local_app_port: int = 0,
                 upstreams: Optional[List[Tuple[str, int]]] = None,
                 cache_seconds: float = 30.0):
        self.client = client
        self.service = service
        self._cache_s = cache_seconds
        self._cached = {}       # kind -> (expires, value)
        self._cache_lock = threading.Lock()

        def cached(kind, fetch):
            import time as _t
            with self._cache_lock:
                hit = self._cached.get(kind)
                if hit is not None and _t.time() < hit[0]:
                    return hit[1]
            val = fetch()
            with self._cache_lock:
                self._cached[kind] = (_t.time() + self._cache_s, val)
            return val

        self.tls = TlsMaterial(
            lambda: cached("leaf",
                           lambda: client.connect_ca_leaf(service)),
            lambda: cached("roots",
                           lambda: client.connect_ca_roots()["Roots"]))

        def authorize(uri: str) -> Tuple[bool, str]:
            out = client.connect_authorize(service, uri)
            return bool(out.get("Authorized")), out.get("Reason", "")

        self.public = PublicListener(
            self.tls, authorize,
            app_addr=("127.0.0.1", local_app_port),
            host=listen[0], port=listen[1])
        self.upstreams: List[UpstreamListener] = []
        if upstreams:
            # expected identities come from the trust domain + dc, not
            # from signing leaves for services we don't own
            td = client.connect_ca_roots().get("TrustDomain", "consul")
            dc = client.agent_self()["Config"].get("Datacenter", "dc1")
        for name, bind_port in upstreams or []:
            def resolve(name=name):
                rows = cached(f"eps:{name}",
                              lambda: self.client.health_connect(name))
                for r in rows:
                    if any(c.get("Status") == "critical"
                           for c in r.get("Checks", [])):
                        continue
                    s = r["Service"]
                    return (s.get("Address")
                            or r.get("Node", {}).get("Address")
                            or "127.0.0.1", s.get("Port", 0))
                return None

            expect = (f"spiffe://{td}/ns/default/dc/{dc}/svc/{name}")
            self.upstreams.append(UpstreamListener(
                self.tls, expect, resolve, port=bind_port))

    def start(self) -> None:
        self.public.start()
        for u in self.upstreams:
            u.start()

    def stop(self) -> None:
        self.public.stop()
        for u in self.upstreams:
            u.stop()


class SidecarProxy:
    """One service's sidecar: public listener + one upstream listener
    per configured upstream, driven by the agent's proxycfg snapshot
    (the managed-proxy shape, connect/proxy/proxy.go)."""

    def __init__(self, agent, proxy_id: str,
                 host: str = "127.0.0.1"):
        state = agent.api.proxycfg.watch(proxy_id)
        if state is None:
            raise ValueError(f"unknown proxy service id {proxy_id!r}")
        self._state = state
        snap = state.fetch(0, timeout=5.0)
        self.service = snap.service
        manager = agent.api.proxycfg

        def leaf_fn():
            return manager.get_leaf(self.service)

        def roots_fn():
            return manager.ca.roots()

        self.tls = TlsMaterial(leaf_fn, roots_fn)

        def authorize(uri: str) -> Tuple[bool, str]:
            source = imod.spiffe_service(uri) or ""
            fresh = self._state.fetch(0, timeout=0.0)
            return imod.authorize(
                fresh.intentions if fresh else [], source,
                self.service,
                fresh.default_allow if fresh else True)

        self.public = PublicListener(
            self.tls, authorize,
            app_addr=(host, snap.local_port or 0),
            host=host,
            port=snap.port or 0)
        # expose paths: one plaintext listener per distinct
        # listener_port, each serving the exact paths bound to it
        # (grouping/admission shared with the xDS view)
        self.exposed: List[ExposeListener] = []
        for lport, paths in sorted(expose_paths_by_port(
                getattr(snap, "expose", None)).items()):
            self.exposed.append(ExposeListener(paths, host=host,
                                               port=lport))
        self.upstreams: List[_Listener] = []
        ca = manager.ca
        from consul_tpu import discoverychain as dchain
        from consul_tpu.connect import l7
        for up in snap.upstreams:
            name = up.get("destination_name", "")
            bind_host = up.get("local_bind_address", host) or host
            bind_port = up.get("local_bind_port", 0)
            chain = snap.chains.get(name)
            l7_chain = (chain is not None
                        and not dchain.is_default_chain(chain)
                        and chain.get("Protocol") in
                        ("http", "http2", "grpc"))
            if l7_chain:
                # L7 mode: the route table from the LIVE snapshot (a
                # config-entry change re-routes the next request), one
                # mTLS dial per request pinned to the picked target

                def table_fn(name=name):
                    fresh = self._state.fetch(0, timeout=0.0)
                    ch = (fresh.chains.get(name) if fresh else None)
                    return l7.route_table(ch) if ch else []

                def _failover_tids(fresh, tid, name):
                    """Primary + failover target ids in priority order
                    (the Python analogue of the priority>0 EDS groups
                    xds.endpoints emits for the same chain)."""
                    tids = [tid]
                    ch = fresh.chains.get(name) if fresh else None
                    if ch is not None:
                        for node in ch["Nodes"].values():
                            if node.get("Type") == "resolver" and \
                                    node.get("Target") == tid:
                                tids += (node.get("Failover") or {}) \
                                    .get("Targets", [])
                                break
                    return tids, ch

                def resolve_groups(tid, name=name):
                    # priority-ordered endpoint groups (primary, then
                    # failover legs) for sticky-hash selection
                    fresh = self._state.fetch(0, timeout=0.0)
                    if fresh is None:
                        return []
                    tids, _ = _failover_tids(fresh, tid, name)
                    groups = []
                    for t in tids:
                        eps = fresh.chain_endpoints.get(t, [])
                        if eps:
                            groups.append([
                                (e["address"] or host, e["port"])
                                for e in eps])
                    return groups

                def expect_uri(tid, name=name):
                    # every identity the resolver can legitimately land
                    # on: the primary target's service plus failover
                    # legs (the reference adds failover SANs the same
                    # way, clusters.go failover-target SAN handling)
                    fresh = self._state.fetch(0, timeout=0.0)
                    tids, ch = _failover_tids(fresh, tid, name)
                    svcs = []
                    for t in tids:
                        svc = (ch["Targets"].get(t, {}).get("Service")
                               if ch else None) or t.split(".", 1)[0]
                        if svc not in svcs:
                            svcs.append(svc)
                    return tuple(ca.active.spiffe_id(s) for s in svcs)

                def resolve_target(tid, _groups=resolve_groups):
                    # single-endpoint form DERIVED from the groups so
                    # the two can never drift
                    for group in _groups(tid):
                        if group:
                            return group[0]
                    return None

                self.upstreams.append(HttpUpstreamListener(
                    self.tls, table_fn, resolve_target, expect_uri,
                    host=bind_host, port=bind_port,
                    resolve_groups=resolve_groups))
                continue

            # L4 mode: single expected identity; a non-default TCP
            # chain still honors redirects/failover by resolving the
            # chain's start target
            if chain is not None and not dchain.is_default_chain(chain):
                start = l7._resolve_to_resolver(chain,
                                                chain["StartNode"])
                tids = [start["Target"]] if start and \
                    start.get("Target") else []
                tids += (start.get("Failover") or {}).get("Targets", []) \
                    if start else []
                svc_names = [chain["Targets"][t]["Service"]
                             for t in tids] or [name]

                def resolve(tids=tuple(tids), name=name):
                    fresh = self._state.fetch(0, timeout=0.0)
                    if fresh is None:
                        return None
                    for tid in tids:     # priority order w/ failover
                        eps = fresh.chain_endpoints.get(tid, [])
                        if eps:
                            return (eps[0]["address"] or host,
                                    eps[0]["port"])
                    return None
            else:
                svc_names = [name]

                def resolve(name=name):
                    # endpoints are the destination's sidecar public
                    # listeners (health connect rows via proxycfg)
                    fresh = self._state.fetch(0, timeout=0.0)
                    eps = (fresh.upstream_endpoints.get(name, [])
                           if fresh else [])
                    if eps:
                        return (eps[0]["address"] or host,
                                eps[0]["port"])
                    return None

            expect = ca.active.spiffe_id(svc_names[0]) \
                if len(svc_names) == 1 else tuple(
                    ca.active.spiffe_id(s) for s in svc_names)
            self.upstreams.append(UpstreamListener(
                self.tls, expect, resolve,
                host=bind_host, port=bind_port))

    def start(self) -> None:
        self.public.start()
        for e in self.exposed:
            e.start()
        for u in self.upstreams:
            u.start()

    def stop(self) -> None:
        self.public.stop()
        for e in self.exposed:
            e.stop()
        for u in self.upstreams:
            u.stop()
