"""ctypes bridge to the native prefix index (native/prefix_index.cpp).

The runtime around the TPU compute path is native where the reference's
is: go-memdb's radix tree is the state store's ordered-index engine, and
this module loads its C++ counterpart — building it on first use with
the toolchain baked into the image — with a pure-Python fallback so the
framework degrades gracefully where no compiler exists.

`PrefixIndex` is the shared surface: set/delete/get plus prefix_max
(per-prefix watch indexes), prefix_count, and sorted prefix_keys.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Iterator, List, Optional

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_SRC = os.path.join(_NATIVE_DIR, "prefix_index.cpp")
_SO = os.path.join(_NATIVE_DIR, "libprefix_index.so")

_lib = None
_lib_lock = threading.Lock()
_build_failed = False


def _load() -> Optional[ctypes.CDLL]:
    """Build (once) + load the shared object; None when unavailable."""
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lib_lock:
        if _lib is not None or _build_failed:
            return _lib
        try:
            if (not os.path.exists(_SO)
                    or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                     "-o", _SO + ".tmp", _SRC],
                    check=True, capture_output=True, timeout=120)
                from consul_tpu import storage
                storage.OS.replace(_SO + ".tmp", _SO)
            lib = ctypes.CDLL(_SO)
        except (OSError, subprocess.SubprocessError):
            _build_failed = True
            return None
        lib.pfx_new.restype = ctypes.c_void_p
        lib.pfx_free.argtypes = [ctypes.c_void_p]
        lib.pfx_set.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_int64]
        lib.pfx_del.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.pfx_del.restype = ctypes.c_int
        lib.pfx_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_int64]
        lib.pfx_get.restype = ctypes.c_int64
        lib.pfx_len.argtypes = [ctypes.c_void_p]
        lib.pfx_len.restype = ctypes.c_int64
        lib.pfx_prefix_max.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_int64]
        lib.pfx_prefix_max.restype = ctypes.c_int64
        lib.pfx_prefix_count.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.pfx_prefix_count.restype = ctypes.c_int64
        lib.pfx_prefix_keys.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_int64, ctypes.c_int64]
        lib.pfx_prefix_keys.restype = ctypes.c_int64
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


_bg_started = False


def _ensure_building() -> None:
    """Kick the build on a background thread: the first caller must not
    pay (or hold locks across) a g++ compile — callers use the Python
    fallback until the library is ready."""
    global _bg_started
    if _bg_started or _lib is not None or _build_failed:
        return
    _bg_started = True
    threading.Thread(target=_load, daemon=True).start()


class _NativePrefixIndex:
    def __init__(self):
        self._lib = _load()
        self._h = self._lib.pfx_new()

    def __del__(self):
        lib, h = getattr(self, "_lib", None), getattr(self, "_h", None)
        if lib is not None and h:
            lib.pfx_free(h)
            self._h = None

    def set(self, key: str, value: int) -> None:
        self._lib.pfx_set(self._h, key.encode(), value)

    def delete(self, key: str) -> bool:
        return bool(self._lib.pfx_del(self._h, key.encode()))

    def get(self, key: str, default: int = 0) -> int:
        return self._lib.pfx_get(self._h, key.encode(), default)

    def __len__(self) -> int:
        return self._lib.pfx_len(self._h)

    def prefix_max(self, prefix: str, default: int = 0) -> int:
        return self._lib.pfx_prefix_max(self._h, prefix.encode(), default)

    def prefix_count(self, prefix: str) -> int:
        return self._lib.pfx_prefix_count(self._h, prefix.encode())

    def prefix_keys(self, prefix: str, limit: int = 1 << 31) -> List[str]:
        cap = 4096
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.pfx_prefix_keys(self._h, prefix.encode(), buf,
                                          cap, limit)
            if n >= 0:
                raw = buf.raw
                out, pos = [], 0
                for _ in range(n):
                    end = raw.index(b"\x00", pos)
                    out.append(raw[pos:end].decode())
                    pos = end + 1
                return out
            cap *= 4


class _PyPrefixIndex:
    """Pure-Python fallback (no compiler in the environment)."""

    def __init__(self):
        self._d = {}

    def set(self, key: str, value: int) -> None:
        self._d[key] = value

    def delete(self, key: str) -> bool:
        return self._d.pop(key, None) is not None

    def get(self, key: str, default: int = 0) -> int:
        return self._d.get(key, default)

    def __len__(self) -> int:
        return len(self._d)

    def prefix_max(self, prefix: str, default: int = 0) -> int:
        best, any_ = default, False
        for k, v in self._d.items():
            if k.startswith(prefix) and (not any_ or v > best):
                best, any_ = v, True
        return best

    def prefix_count(self, prefix: str) -> int:
        return sum(1 for k in self._d if k.startswith(prefix))

    def prefix_keys(self, prefix: str, limit: int = 1 << 31) -> List[str]:
        return sorted(k for k in self._d
                      if k.startswith(prefix))[:limit]


def PrefixIndex():
    """Factory: native when ALREADY built/loaded, Python otherwise (the
    background build upgrades future instances; existing ones keep
    working — both impls share one semantics)."""
    if _lib is not None:
        return _NativePrefixIndex()
    try:
        fresh = os.path.exists(_SO) and \
            os.path.getmtime(_SO) >= os.path.getmtime(_SRC)
    except OSError:
        fresh = False
    if fresh:
        # cheap load path: an up-to-date library exists, no compile
        return _NativePrefixIndex() if native_available() \
            else _PyPrefixIndex()
    _ensure_building()
    return _PyPrefixIndex()
