"""Gateway wiring: ingress / terminating / mesh gateway catalog views.

The reference derives a GatewayServices mapping from the
`ingress-gateway` and `terminating-gateway` config entries
(agent/consul/state/config_entry.go gateway-services table,
catalog_endpoint.go GatewayServices) and feeds it to proxycfg's
per-kind snapshot assembly (agent/proxycfg/state.go).  This module
derives the same mapping on demand from the config-entry store — the
store stays schema-light, the mapping is pure function of entries.

Config entry shapes (lower-cased keys, matching config_entry_set):

  ingress-gateway:     {"listeners": [{"port": 8080, "protocol": "http",
                         "services": [{"name": "web", "hosts": [...]}]}]}
  terminating-gateway: {"services": [{"name": "legacy", "ca_file": ...,
                         "sni": ...}]}

A `{"name": "*"}` service entry is the wildcard: the gateway exposes
every service (structs.WildcardSpecifier).
"""

from __future__ import annotations

from typing import List

WILDCARD = "*"

GATEWAY_KINDS = ("mesh-gateway", "ingress-gateway",
                 "terminating-gateway")


def gateway_services(store, gateway_name: str) -> List[dict]:
    """All services bound to `gateway_name`, in the
    /v1/catalog/gateway-services/<gw> row shape."""
    out: List[dict] = []
    ent = store.config_entry_get("ingress-gateway", gateway_name)
    if ent is not None:
        for lst in ent.get("listeners") or []:
            for s in lst.get("services") or []:
                out.append({
                    "Gateway": gateway_name,
                    "Service": s.get("name", ""),
                    "GatewayKind": "ingress-gateway",
                    "Port": lst.get("port", 0),
                    "Protocol": lst.get("protocol", "tcp"),
                    "Hosts": s.get("hosts") or [],
                })
    ent = store.config_entry_get("terminating-gateway", gateway_name)
    if ent is not None:
        for s in ent.get("services") or []:
            out.append({
                "Gateway": gateway_name,
                "Service": s.get("name", ""),
                "GatewayKind": "terminating-gateway",
                "CAFile": s.get("ca_file", ""),
                "CertFile": s.get("cert_file", ""),
                "KeyFile": s.get("key_file", ""),
                "SNI": s.get("sni", ""),
            })
    return out


def _bound_services(store, row_filter) -> List[dict]:
    """Scan every gateway config entry; keep rows row_filter accepts."""
    rows = []
    for ent in store.config_entry_list("ingress-gateway") + \
            store.config_entry_list("terminating-gateway"):
        for row in gateway_services(store, ent["name"]):
            if row_filter(row):
                rows.append(row)
    return rows


def ingress_gateways_for(store, service: str) -> List[dict]:
    """Ingress gateways exposing `service` (state ServiceGateways used
    by /v1/health/ingress/<svc>).  Wildcard listeners match any."""
    return _bound_services(
        store, lambda r: r["GatewayKind"] == "ingress-gateway"
        and r["Service"] in (service, WILDCARD))


def resolve_wildcard(store, rows: List[dict]) -> List[dict]:
    """Expand `*` rows into one row per registered service name,
    excluding connect proxies and other gateways (the reference's
    wildcard expansion skips Kind != typical).

    Explicit bindings win over wildcard expansion, and duplicates are
    dropped — a service bound both ways must yield ONE row (one SNI
    filter chain; Envoy rejects duplicate filter-chain matches)."""
    out: List[dict] = []
    seen = set()

    def key(r, svc):
        return (r["Gateway"], r["GatewayKind"], svc, r.get("Port", 0))

    # explicit rows first: they carry per-service settings (sni,
    # ca_file) the wildcard defaults would otherwise mask
    for row in rows:
        if row["Service"] == WILDCARD:
            continue
        if key(row, row["Service"]) not in seen:
            seen.add(key(row, row["Service"]))
            out.append(row)
    kind_map = None
    for row in rows:
        if row["Service"] != WILDCARD:
            continue
        if kind_map is None:
            kind_map = store.service_kind_map()   # one pass, lazily
        for name, kinds in sorted(kind_map.items()):
            if kinds - {""}:
                continue  # proxies/gateways are not exposable targets
            if key(row, name) in seen:
                continue
            seen.add(key(row, name))
            out.append(dict(row, Service=name))
    return out
