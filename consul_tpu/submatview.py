"""Materialized views over the event stream — the streaming read path.

The reference's submatview (materializer.go:47 Materializer, store.go
Store) maintains client-side views fed by the gRPC event stream so a
blocked `/v1/health/service/<name>?index=` is answered from materialized
state — no query re-execution per wakeup, wakeups only on RELEVANT
events.  Here the view subscribes to the store's EventPublisher on one
(topic, key): snapshot once, then follow events; a SnapshotRequired
reset re-snapshots (stream/publisher.py).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from consul_tpu import locks
from consul_tpu.stream.publisher import SnapshotRequired


class Materializer:
    """One live view: snapshot + follow (materializer.go:47).

    `snapshot_fn() -> (value, index)` reads current state from the
    store; events on (topic, key) trigger re-materialization.  Events in
    this framework carry (topic, key, index) — re-materialization re-runs
    the snapshot function, which reads only the keyed slice (cheap), so
    payload-carrying events are not required for correctness."""

    def __init__(self, publisher, topic: str, key: Optional[str],
                 snapshot_fn: Callable[[], Tuple[Any, int]]):
        self.publisher = publisher
        self.topic = topic
        self.key = key
        self.snapshot_fn = snapshot_fn
        self._cond = locks.make_condition(name="submatview.view")
        self._value: Any = None       # guarded-by: _cond
        self._index = 0               # guarded-by: _cond
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self.resets = 0               # SnapshotRequired re-snapshots
        self._inflight = 0            # guarded-by: _cond — parked
        #                               fetch()ers (sweep guard)
        locks.register_guards(self, locks.lock_of(self._cond),
                              "_value", "_index", "_inflight")

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        self._running = True
        self._materialize()
        self._thread = threading.Thread(target=self._follow, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        if self._sub is not None:
            self._sub.close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    _sub = None

    def _materialize(self) -> None:
        # subscribe FIRST (tail-only — no replay needed since the
        # snapshot below reads current state), so no event between
        # snapshot and subscribe can be missed and eviction history is
        # irrelevant
        self._sub = self.publisher.subscribe(self.topic, self.key,
                                             since_index=None)
        value, index = self.snapshot_fn()
        with self._cond:
            self._value, self._index = value, index
            self._cond.notify_all()

    def _follow(self) -> None:
        import time as _time

        from consul_tpu import telemetry
        while self._running:
            try:
                events = self._sub.events(timeout=1.0)
            except SnapshotRequired:
                if not self._running:
                    return
                self.resets += 1
                telemetry.incr_counter(("stream", "view_resets"),
                                       labels={"topic": self.topic})
                self._materialize()
                continue
            if not events:
                continue
            top = max(e.index for e in events)
            t0 = _time.perf_counter()
            value, index = self.snapshot_fn()
            # consul.stream.materialize: re-materialization cost per
            # relevant event batch — the per-wakeup work the streaming
            # read path saves the query layer (materializer.go role)
            telemetry.measure_since(("stream", "materialize"), t0,
                                    labels={"topic": self.topic})
            # view freshness is a wakeup in the commit-to-visibility
            # pipeline: the materialized state now reflects `top`
            # (the publisher shares its store's table)
            vt = getattr(self.publisher, "visibility", None)
            if vt is not None:
                vt.stage("wakeup", top)
            with self._cond:
                self._value = value
                self._index = max(index, top, self._index)
                self._cond.notify_all()

    # -------------------------------------------------------------- serving

    def fetch(self, min_index: int = 0,
              timeout: float = 300.0) -> Tuple[Any, int]:
        """Blocking read from the view: parks until index > min_index
        (the submatview Store.Get contract)."""
        deadline = time.time() + timeout
        with self._cond:
            self._inflight += 1
            try:
                while self._index <= min_index:
                    remaining = deadline - time.time()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
                return self._value, self._index
            finally:
                self._inflight -= 1


class _ViewEntry:
    """One shared view slot: the Materializer once ready, the
    single-flight gate concurrent requesters park on, and the
    last-access stamp the idle sweep judges."""

    __slots__ = ("view", "last", "ready", "error")

    def __init__(self, now: float):
        self.view: Optional[Materializer] = None
        self.last = now
        self.ready = threading.Event()
        self.error: Optional[BaseException] = None


class ViewStore:
    """SHARED cross-client materialized-view cache keyed on
    (topic, key, view_key) with single-flight materialization
    (submatview/store.go Store).

    Promotion contract (ISSUE 12 tentpole): N concurrent clients
    polling the same service share ONE Materializer and ONE publisher
    subscription — the first requester materializes, every concurrent
    requester for the same key parks on the entry's gate instead of
    re-materializing (single-flight), and requesters for OTHER keys
    never wait behind it (the registry lock is held only for dict
    ops, never across a snapshot).  `consul.cache.hit/miss{type}`
    counts the sharing ratio per topic; idle views reap on TTL unless
    a blocking reader has them pinned (`_inflight`, the reference's
    refcounting)."""

    # single-flight wait bound: a wedged creator must surface as an
    # error to its waiters, not park them forever
    MATERIALIZE_TIMEOUT = 30.0

    def __init__(self, publisher, idle_ttl: float = 120.0):
        self.publisher = publisher
        self.idle_ttl = idle_ttl
        # the shared view registry; held for dict ops ONLY, never
        # across a snapshot/materialization  # guarded-by: _lock
        self._views: Dict[Tuple[str, str, str], _ViewEntry] = {}
        self._lock = locks.make_lock("submatview.registry")
        locks.register_guards(self, self._lock, "_views")

    _closed = False

    def get(self, topic: str, key: str,
            snapshot_fn: Callable[[], Tuple[Any, int]],
            view_key: str = "") -> Materializer:
        """`key` scopes the event subscription (service name); `view_key`
        distinguishes views sharing a subscription but differing in
        request shape (tag/passing filters) — the reference keys views by
        the full request hash (submatview/store.go)."""
        from consul_tpu import telemetry
        vkey = (topic, key or "", view_key)
        now = time.time()
        creator = False
        doomed: list = []
        with self._lock:
            if self._closed:
                raise RuntimeError("view store closed")
            # idle sweep on EVERY access, else a stable working set never
            # expires its idle neighbors; views with parked blocking
            # readers are pinned (the reference refcounts views), and
            # the stop()s run OUTSIDE this lock so reaping a dead view
            # never stalls live requesters
            for k, e in list(self._views.items()):
                if k != vkey and e.ready.is_set() and e.view is not None \
                        and now - e.last > self.idle_ttl \
                        and e.view._inflight == 0:
                    doomed.append(e.view)
                    del self._views[k]
            ent = self._views.get(vkey)
            if ent is not None:
                ent.last = now
            else:
                ent = _ViewEntry(now)
                self._views[vkey] = ent
                creator = True
        for v in doomed:
            v.stop()
        telemetry.incr_counter(("cache", "miss" if creator else "hit"),
                               labels={"type": f"view:{topic}"})
        if creator:
            m = Materializer(self.publisher, topic, key, snapshot_fn)
            try:
                m.start()
            except BaseException as e:
                # a failed materialization must release its waiters AND
                # vacate the slot so the next requester retries fresh
                with self._lock:
                    ent.error = e
                    if self._views.get(vkey) is ent:
                        del self._views[vkey]
                ent.ready.set()
                raise
            with self._lock:
                ent.view = m
                ent.last = time.time()
            ent.ready.set()
            return m
        # single-flight: park on the creator's gate, never re-snapshot
        if not ent.ready.wait(self.MATERIALIZE_TIMEOUT):
            raise RuntimeError(
                f"view {vkey} materialization timed out")
        if ent.view is None:
            raise RuntimeError(
                f"view {vkey} creation failed: {ent.error}")
        return ent.view

    def stats(self) -> dict:
        """Live registry shape (tests + /v1/agent/profile debugging)."""
        with self._lock:
            return {
                "views": len(self._views),
                "inflight": sum(e.view._inflight
                                for e in self._views.values()
                                if e.view is not None),
            }

    def close(self) -> None:
        with self._lock:
            self._closed = True
            views = [e.view for e in self._views.values()
                     if e.view is not None]
            self._views.clear()
        for m in views:
            m.stop()
