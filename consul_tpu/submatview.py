"""Materialized views over the event stream — the streaming read path.

The reference's submatview (materializer.go:47 Materializer, store.go
Store) maintains client-side views fed by the gRPC event stream so a
blocked `/v1/health/service/<name>?index=` is answered from materialized
state — no query re-execution per wakeup, wakeups only on RELEVANT
events.  Here the view subscribes to the store's EventPublisher on one
(topic, key): snapshot once, then follow events; a SnapshotRequired
reset re-snapshots (stream/publisher.py).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from consul_tpu.stream.publisher import SnapshotRequired


class Materializer:
    """One live view: snapshot + follow (materializer.go:47).

    `snapshot_fn() -> (value, index)` reads current state from the
    store; events on (topic, key) trigger re-materialization.  Events in
    this framework carry (topic, key, index) — re-materialization re-runs
    the snapshot function, which reads only the keyed slice (cheap), so
    payload-carrying events are not required for correctness."""

    def __init__(self, publisher, topic: str, key: Optional[str],
                 snapshot_fn: Callable[[], Tuple[Any, int]]):
        self.publisher = publisher
        self.topic = topic
        self.key = key
        self.snapshot_fn = snapshot_fn
        self._cond = threading.Condition()
        self._value: Any = None
        self._index = 0
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self.resets = 0               # SnapshotRequired re-snapshots
        self._inflight = 0            # parked fetch()ers (sweep guard)

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        self._running = True
        self._materialize()
        self._thread = threading.Thread(target=self._follow, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        if self._sub is not None:
            self._sub.close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    _sub = None

    def _materialize(self) -> None:
        # subscribe FIRST (tail-only — no replay needed since the
        # snapshot below reads current state), so no event between
        # snapshot and subscribe can be missed and eviction history is
        # irrelevant
        self._sub = self.publisher.subscribe(self.topic, self.key,
                                             since_index=None)
        value, index = self.snapshot_fn()
        with self._cond:
            self._value, self._index = value, index
            self._cond.notify_all()

    def _follow(self) -> None:
        import time as _time

        from consul_tpu import telemetry
        while self._running:
            try:
                events = self._sub.events(timeout=1.0)
            except SnapshotRequired:
                if not self._running:
                    return
                self.resets += 1
                telemetry.incr_counter(("stream", "view_resets"),
                                       labels={"topic": self.topic})
                self._materialize()
                continue
            if not events:
                continue
            top = max(e.index for e in events)
            t0 = _time.perf_counter()
            value, index = self.snapshot_fn()
            # consul.stream.materialize: re-materialization cost per
            # relevant event batch — the per-wakeup work the streaming
            # read path saves the query layer (materializer.go role)
            telemetry.measure_since(("stream", "materialize"), t0,
                                    labels={"topic": self.topic})
            # view freshness is a wakeup in the commit-to-visibility
            # pipeline: the materialized state now reflects `top`
            # (the publisher shares its store's table)
            vt = getattr(self.publisher, "visibility", None)
            if vt is not None:
                vt.stage("wakeup", top)
            with self._cond:
                self._value = value
                self._index = max(index, top, self._index)
                self._cond.notify_all()

    # -------------------------------------------------------------- serving

    def fetch(self, min_index: int = 0,
              timeout: float = 300.0) -> Tuple[Any, int]:
        """Blocking read from the view: parks until index > min_index
        (the submatview Store.Get contract)."""
        deadline = time.time() + timeout
        with self._cond:
            self._inflight += 1
            try:
                while self._index <= min_index:
                    remaining = deadline - time.time()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
                return self._value, self._index
            finally:
                self._inflight -= 1


class ViewStore:
    """Shared view registry with idle expiry (submatview/store.go)."""

    def __init__(self, publisher, idle_ttl: float = 120.0):
        self.publisher = publisher
        self.idle_ttl = idle_ttl
        self._views: Dict[Tuple[str, str], Tuple[Materializer, float]] = {}
        self._lock = threading.Lock()

    _closed = False

    def get(self, topic: str, key: str,
            snapshot_fn: Callable[[], Tuple[Any, int]],
            view_key: str = "") -> Materializer:
        """`key` scopes the event subscription (service name); `view_key`
        distinguishes views sharing a subscription but differing in
        request shape (tag/passing filters) — the reference keys views by
        the full request hash (submatview/store.go)."""
        vkey = (topic, key or "", view_key)
        now = time.time()
        with self._lock:
            if self._closed:
                raise RuntimeError("view store closed")
            # idle sweep on EVERY access, else a stable working set never
            # expires its idle neighbors; views with parked blocking
            # readers are pinned (the reference refcounts views)
            for k, (view, last) in list(self._views.items()):
                if k != vkey and now - last > self.idle_ttl \
                        and view._inflight == 0:
                    view.stop()
                    del self._views[k]
            hit = self._views.get(vkey)
            if hit is not None:
                self._views[vkey] = (hit[0], now)
                return hit[0]
            m = Materializer(self.publisher, topic, key, snapshot_fn)
            m.start()
            self._views[vkey] = (m, now)
            return m

    def close(self) -> None:
        with self._lock:
            self._closed = True
            for m, _ in self._views.values():
                m.stop()
            self._views.clear()
