"""tlsutil: the central TLS Configurator for RPC/HTTP.

The reference funnels every TLS decision through one Configurator
(tlsutil/config.go:177): incoming/outgoing contexts for RPC, HTTP, and
gRPC, verify_incoming / verify_outgoing / verify_server_hostname knobs,
and live CA updates for auto-TLS.  Same shape here over the stdlib `ssl`
module, with certificates minted by the Connect CA machinery
(connect/ca.py) when none are supplied — the auto-encrypt path
(agent/consul/auto_encrypt_endpoint.go) signs agent certs from the same
root so the whole fleet chains to one CA.

Server identities carry the reference's DNS SAN convention
(`server.<dc>.<domain>`) so verify_server_hostname can pin outgoing
connections to real servers.
"""

from __future__ import annotations

import datetime
import os
import ssl
import tempfile
from typing import Optional, Tuple

# lazy crypto (same gate as connect/ca.py): importing this module must
# work without the 'cryptography' package so test collection and
# transitive importers (connect proxy wiring) degrade to a clean skip
# instead of a collection error; only actually minting certificates
# requires the real dependency
try:  # pragma: no cover - import guard
    from cryptography import x509
    HAVE_CRYPTO = True
except ImportError:  # pragma: no cover
    x509 = None
    HAVE_CRYPTO = False


def _write_tmp(data: str) -> str:
    fd, path = tempfile.mkstemp(suffix=".pem")
    with os.fdopen(fd, "w") as f:
        f.write(data)
    return path


class Configurator:
    def __init__(self, dc: str = "dc1", domain: str = "consul",
                 verify_incoming: bool = True,
                 verify_outgoing: bool = True,
                 verify_server_hostname: bool = False,
                 ca_cert_pem: Optional[str] = None,
                 ca_key_pem: Optional[str] = None):
        if not HAVE_CRYPTO:
            raise RuntimeError(
                "tlsutil.Configurator requires the 'cryptography' "
                "package (certificate minting rides its X.509 "
                "builder)")
        from consul_tpu.connect.ca import BuiltinCA
        self.dc = dc
        self.domain = domain
        self.verify_incoming = verify_incoming
        self.verify_outgoing = verify_outgoing
        self.verify_server_hostname = verify_server_hostname
        # the TLS CA: supplied or self-generated (auto-TLS)
        self._ca = BuiltinCA(f"{dc}.{domain}", dc=dc,
                             key_pem=ca_key_pem, cert_pem=ca_cert_pem)

    # ----------------------------------------------------------------- CA

    @property
    def ca_pem(self) -> str:
        return self._ca.cert_pem

    @property
    def ca_key_pem(self) -> str:
        return self._ca.key_pem

    def sign_cert(self, name: str,
                  server: bool = False) -> Tuple[str, str]:
        """(cert_pem, key_pem) for a node/agent; server certs carry the
        `server.<dc>.<domain>` SAN (auto_encrypt_endpoint.go Sign).
        Rides BuiltinCA.sign — one X.509 builder for the whole tree."""
        sans = [x509.DNSName(name), x509.DNSName("localhost")]
        if server:
            sans.append(x509.DNSName(f"server.{self.dc}.{self.domain}"))
        return self._ca.sign(name, sans, datetime.timedelta(days=365))

    # ------------------------------------------------------------ contexts

    def incoming_context(self, cert_pem: str,
                         key_pem: str) -> ssl.SSLContext:
        """Server side: presents `cert`, requires client certs when
        verify_incoming (IncomingRPCConfig)."""
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        cert_f, key_f = _write_tmp(cert_pem), _write_tmp(key_pem)
        ca_f = _write_tmp(self.ca_pem)
        try:
            ctx.load_cert_chain(cert_f, key_f)
            ctx.load_verify_locations(ca_f)
        finally:
            for f in (cert_f, key_f, ca_f):
                os.unlink(f)
        ctx.verify_mode = ssl.CERT_REQUIRED if self.verify_incoming \
            else ssl.CERT_NONE
        return ctx

    def bootstrap_context(self, cert_pem: str,
                          key_pem: str) -> ssl.SSLContext:
        """Server side for the INSECURE bootstrap listener: presents our
        cert, never requires a client cert — the auto-encrypt endpoint
        must be reachable by agents that have no cert yet (the
        reference's insecure RPC server, server.go:240-247)."""
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        cert_f, key_f = _write_tmp(cert_pem), _write_tmp(key_pem)
        try:
            ctx.load_cert_chain(cert_f, key_f)
        finally:
            os.unlink(cert_f)
            os.unlink(key_f)
        ctx.verify_mode = ssl.CERT_NONE
        return ctx

    def outgoing_context(self, cert_pem: Optional[str] = None,
                         key_pem: Optional[str] = None) -> ssl.SSLContext:
        """Client side: verifies the server against our CA; presents a
        client cert when given (OutgoingRPCConfig).  Hostname pinning to
        server.<dc>.<domain> when verify_server_hostname."""
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ca_f = _write_tmp(self.ca_pem)
        try:
            ctx.load_verify_locations(ca_f)
        finally:
            os.unlink(ca_f)
        if cert_pem and key_pem:
            cert_f, key_f = _write_tmp(cert_pem), _write_tmp(key_pem)
            try:
                ctx.load_cert_chain(cert_f, key_f)
            finally:
                os.unlink(cert_f)
                os.unlink(key_f)
        if self.verify_outgoing:
            ctx.check_hostname = self.verify_server_hostname
            ctx.verify_mode = ssl.CERT_REQUIRED
        else:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        return ctx

    def server_sni(self) -> str:
        return f"server.{self.dc}.{self.domain}"
