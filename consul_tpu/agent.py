"""Agent: wires the oracle, state store, and HTTP API into one lifecycle.

The reference's agent (agent/agent.go:354 New / :446 Start) assembles
config, the server core, local state, checks, and the HTTP/DNS servers.
Here the assembly is: GossipOracle (device-resident membership +
coordinates + events) + StateStore (host catalog/KV/sessions) + ApiServer
(/v1 surface), plus a reconciler that mirrors the leader's serf→catalog
loop (agent/consul/leader.go:1187 reconcileMember): members the gossip
layer declares failed get their `serfHealth` check flipped critical and,
on reap, deregistered.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from consul_tpu.api.http import ApiServer
from consul_tpu.catalog.store import StateStore
from consul_tpu.config import GossipConfig, SimConfig
from consul_tpu.oracle import GossipOracle


class Agent:
    def __init__(self, gossip: Optional[GossipConfig] = None,
                 sim: Optional[SimConfig] = None,
                 node_name: str = "node0", http_port: int = 0,
                 dc: str = "dc1", acl_enabled: bool = False,
                 acl_default_policy: str = "allow",
                 acl_down_policy: str = "extend-cache",
                 dns_port: int = 0):
        from consul_tpu.acl import ACLResolver
        from consul_tpu.ae import StateSyncer
        from consul_tpu.checks import CheckManager
        from consul_tpu.local import LocalState
        self.oracle = GossipOracle(gossip, sim)
        self.store = StateStore()
        self.node_name = node_name
        self.acl = ACLResolver(self.store, enabled=acl_enabled,
                               default_policy=acl_default_policy,
                               down_policy=acl_down_policy)
        # local state + AE: /v1/agent writes land here; the syncer pushes
        # to the catalog (reference split: agent/local + agent/ae vs
        # agent/consul catalog)
        self.local = LocalState(node_name,
                                on_change=lambda: self.syncer.trigger())
        self.checks = CheckManager(self._check_notify)
        self.syncer = StateSyncer(
            self.local, self.store, interval=60.0,
            cluster_size=lambda: self.oracle.n_nodes)
        self.api = ApiServer(self.store, self.oracle, node_name=node_name,
                             port=http_port, dc=dc, acl_resolver=self.acl,
                             local=self.local, checks=self.checks)
        # DNS frontend on its own ephemeral (or fixed) port; rides the
        # same store/oracle (agent/agent.go:601 listenAndServeDNS)
        from consul_tpu.dns import DNSServer
        # DNS runs under the agent's (anonymous/default) token so
        # acl_enabled + default deny is enforced on DNS lookups too
        def _dns_query_exec(name):
            """<name>.query.<domain> → prepared-query execute, adapted to
            DNS's health-row shape (dns.py _query).  Runs under the same
            anonymous-token authorizer as direct DNS service lookups — a
            prepared query must not leak a service the token can't read."""
            res = self.api.query_executor.execute(name)
            if res is None:
                return None
            if not self.acl.resolve(None).service_read(res["Service"]):
                return None
            return [{"service": s} for s in res["Nodes"]]

        self.dns = DNSServer(self.store, self.oracle, node_name=node_name,
                             port=dns_port,
                             authz=lambda: self.acl.resolve(None),
                             query_executor=_dns_query_exec)
        self._reconcile_thread: Optional[threading.Thread] = None
        self._running = False

    def _check_notify(self, check_id: str, status: str, output: str) -> None:
        """Check-runner callback → local state → AE push (the reference's
        CheckNotifier wiring, agent/checks/check.go → local.UpdateCheck)."""
        if self.local.update_check(check_id, status, output):
            try:
                self.local.sync_changes(self.store)
            except Exception:
                pass  # syncer retries on its own cadence

    # ------------------------------------------------------------- lifecycle

    def start(self, tick_seconds: float = 0.0,
              reconcile_interval: float = 0.5) -> None:
        self.store.register_node(self.node_name, "127.0.0.1")
        self.store.register_check(self.node_name, "serfHealth",
                                  "Serf Health Status", status="passing")
        self.syncer.start()
        self.oracle.start(tick_seconds)
        self.api.start()
        self.dns.start()
        self._running = True

        def reconcile_loop():
            while self._running:
                try:
                    self.reconcile()
                except Exception:
                    pass
                self.store.expire_sessions()
                time.sleep(reconcile_interval)

        self._reconcile_thread = threading.Thread(target=reconcile_loop,
                                                  daemon=True)
        self._reconcile_thread.start()

    def stop(self) -> None:
        self._running = False
        self.checks.stop_all()
        self.syncer.stop()
        self.oracle.stop()
        self.api.stop()
        self.dns.stop()
        if self._reconcile_thread:
            self._reconcile_thread.join(timeout=5.0)

    # ------------------------------------------------------------- reconcile

    def reconcile(self) -> None:
        """serf→catalog reconciliation (leader.go:1234 handleAliveMember /
        :1332 handleFailedMember / :1390 handleReapMember)."""
        catalog_nodes = {n["node"] for n in self.store.nodes()}
        for m in self.oracle.members():
            name = m["name"]
            if name not in catalog_nodes:
                continue  # only reconcile catalog-registered members
            if m["status"] == "failed":
                checks = {c["check_id"]: c
                          for c in self.store.node_checks(name)}
                sh = checks.get("serfHealth")
                if sh is None or sh["status"] != "critical":
                    self.store.register_check(
                        name, "serfHealth", "Serf Health Status",
                        status="critical",
                        output="Agent not live or unreachable")
            elif m["status"] == "left":
                self.store.deregister_node(name)
            else:
                checks = {c["check_id"]: c
                          for c in self.store.node_checks(name)}
                sh = checks.get("serfHealth")
                if sh is not None and sh["status"] != "passing":
                    self.store.register_check(
                        name, "serfHealth", "Serf Health Status",
                        status="passing", output="Agent alive and reachable")

    @property
    def http_address(self) -> str:
        return self.api.address
