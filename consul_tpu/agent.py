"""Agent: wires the oracle, state store, and HTTP API into one lifecycle.

The reference's agent (agent/agent.go:354 New / :446 Start) assembles
config, the server core, local state, checks, and the HTTP/DNS servers.
Here the assembly is: GossipOracle (device-resident membership +
coordinates + events) + StateStore (host catalog/KV/sessions) + ApiServer
(/v1 surface), plus a reconciler that mirrors the leader's serf→catalog
loop (agent/consul/leader.go:1187 reconcileMember): members the gossip
layer declares failed get their `serfHealth` check flipped critical and,
on reap, deregistered.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from consul_tpu.api.http import ApiServer
from consul_tpu.catalog.store import StateStore
from consul_tpu.config import GossipConfig, SimConfig
from consul_tpu.oracle import GossipOracle


class Agent:
    @classmethod
    def from_config(cls, rc=None, config_files=(), config_dirs=(),
                    **flags) -> "Agent":
        """Build an agent from the multi-source config pipeline
        (consul_tpu/runtime_config.py; reference agent/config/builder.go
        → RuntimeConfig → agent.New).  The sources are remembered so
        `reload()` / PUT /v1/agent/reload can re-read them."""
        from consul_tpu import runtime_config as rcfg
        if rc is None:
            rc = rcfg.load(files=list(config_files), dirs=list(config_dirs),
                           **flags)
        wan = bool(flags.pop("wan_defaults", False))
        a = cls(gossip=rc.gossip_config(wan=wan), sim=rc.sim_config(),
                node_name=rc.node_name, http_port=rc.http_port,
                dc=rc.datacenter, acl_enabled=rc.acl_enabled,
                acl_default_policy=rc.acl_default_policy,
                acl_down_policy=rc.acl_down_policy, dns_port=rc.dns_port,
                grpc_port=rc.grpc_port if rc.grpc_port >= 0 else None,
                data_dir=rc.data_dir or None,
                enable_remote_exec=rc.enable_remote_exec,
                segments=rc.segment_pools())
        a.runtime_config = rc
        a.api.wan_fed_via_gateways = \
            rc.connect_mesh_gateway_wan_federation
        a.api.enable_debug = rc.enable_debug
        a.api.kv_max_value_size = rc.kv_max_value_size
        a.api.txn_max_ops = rc.txn_max_ops
        if rc.encrypt and hasattr(a.oracle, "keyring_install"):
            # `encrypt` preloads the gossip keyring (agent/keyring.go)
            a.oracle.keyring_install(rc.encrypt)
        a._config_sources = (tuple(config_files), tuple(config_dirs),
                             dict(flags))
        a._apply_reloadable(rc)
        if config_files or config_dirs:
            # only re-readable sources make reload meaningful; a literal
            # rc would "reload" back to pure defaults
            a.api.reload_fn = a.reload
        return a

    def _apply_reloadable(self, rc) -> None:
        """Apply the reloadable subset: DNS options + static service/check
        definitions (the reference's ReloadConfig surface).  Definitions
        removed from the config are deregistered; runtime check state is
        preserved across reloads (snapshotCheckState parity)."""
        self.dns.only_passing = rc.dns_only_passing
        self.dns.node_ttl = rc.dns_node_ttl
        self.dns.service_ttl = rc.dns_service_ttl
        self.dns.domain = rc.dns_domain.rstrip(".").lower()
        from consul_tpu.dns import parse_recursor
        # build-then-assign: concurrent queries must never observe a
        # half-populated recursor list mid-reload
        self.dns.recursors = [parse_recursor(r) for r in rc.recursors]
        self.dns.recursor_timeout = rc.dns_recursor_timeout
        # ui_config.metrics_proxy is reloadable (the reference stores
        # it in an atomic.Value for exactly this — ui_endpoint.go:591)
        import json as _json
        self.api.ui_metrics_proxy = _json.loads(
            rc.ui_metrics_proxy_json) if rc.ui_metrics_proxy_json \
            else {}
        new_sids, new_cids = set(), set()
        for svc in rc.services:
            name = svc.get("Name") or svc.get("name")
            sid = svc.get("ID") or svc.get("id") or name
            new_sids.add(sid)
            self.local.add_service(
                sid, name, port=svc.get("Port") or svc.get("port") or 0,
                tags=svc.get("Tags") or svc.get("tags") or [],
                meta=svc.get("Meta") or svc.get("meta") or {})
        existing_checks = self.local.checks()
        for chk in rc.checks:
            name = chk.get("Name") or chk.get("name")
            cid = chk.get("CheckID") or chk.get("id") or name
            new_cids.add(cid)
            if cid in existing_checks:
                continue  # keep runtime status across reloads
            self.local.add_check(
                cid, name or cid,
                status=chk.get("Status") or chk.get("status") or "critical")
        # deregister config-origin definitions dropped from the sources
        for sid in getattr(self, "_config_service_ids", set()) - new_sids:
            self.local.remove_service(sid)
        for cid in getattr(self, "_config_check_ids", set()) - new_cids:
            self.local.remove_check(cid)
        self._config_service_ids = new_sids
        self._config_check_ids = new_cids

    def reload(self):
        """Re-read config sources and apply reloadable fields; returns
        {"reloaded": [...], "restart_required": [...]} (SIGHUP path,
        reference server.go:1395 / Agent.ReloadConfig)."""
        from consul_tpu import runtime_config as rcfg
        files, dirs, flags = getattr(
            self, "_config_sources", ((), (), {}))
        new_rc = rcfg.load(files=list(files), dirs=list(dirs), **flags)
        old_rc = getattr(self, "runtime_config", new_rc)
        reload_keys, restart_keys = rcfg.diff_reloadable(old_rc, new_rc)
        self._apply_reloadable(new_rc)
        self.runtime_config = new_rc
        if reload_keys:
            try:
                self.local.sync_changes(self.store)
            except Exception:
                pass
        return {"reloaded": reload_keys, "restart_required": restart_keys}

    def __init__(self, gossip: Optional[GossipConfig] = None,
                 sim: Optional[SimConfig] = None,
                 node_name: str = "node0", http_port: int = 0,
                 dc: str = "dc1", acl_enabled: bool = False,
                 acl_default_policy: str = "allow",
                 acl_down_policy: str = "extend-cache",
                 dns_port: int = 0, data_dir: Optional[str] = None,
                 enable_remote_exec: bool = False, segments=None,
                 grpc_port: Optional[int] = None):
        self.data_dir = data_dir
        from consul_tpu.acl import ACLResolver
        from consul_tpu.ae import StateSyncer
        from consul_tpu.checks import CheckManager
        from consul_tpu.local import LocalState
        if segments:
            # multi-segment LAN: one device pool per segment, this
            # agent (server-shaped) bridges all of them (SURVEY §2.2;
            # segment_oss.go).  `segments` maps name -> (GossipConfig,
            # SimConfig); "" is the default segment.
            from consul_tpu.segments import SegmentedOracle
            self.oracle = SegmentedOracle(segments)
        else:
            self.oracle = GossipOracle(gossip, sim)
        self.store = StateStore()
        self.node_name = node_name
        self.acl = ACLResolver(self.store, enabled=acl_enabled,
                               default_policy=acl_default_policy,
                               down_policy=acl_down_policy, dc=dc)
        # local state + AE: /v1/agent writes land here; the syncer pushes
        # to the catalog (reference split: agent/local + agent/ae vs
        # agent/consul catalog)
        def _on_local_change():
            self.syncer.trigger()
            self._persist_local()

        self.local = LocalState(node_name, on_change=_on_local_change)
        self.checks = CheckManager(self._check_notify)
        self.syncer = StateSyncer(
            self.local, self.store, interval=60.0,
            cluster_size=lambda: self.oracle.n_nodes)
        self.api = ApiServer(self.store, self.oracle, node_name=node_name,
                             port=http_port, dc=dc, acl_resolver=self.acl,
                             local=self.local, checks=self.checks)
        if data_dir:
            # persistent agent-token slots (agent/token persistence)
            from consul_tpu.token_store import TokenStore
            self.api.tokens = TokenStore(data_dir=data_dir)
        # DNS frontend on its own ephemeral (or fixed) port; rides the
        # same store/oracle (agent/agent.go:601 listenAndServeDNS)
        from consul_tpu.dns import DNSServer
        # DNS runs under the agent's (anonymous/default) token so
        # acl_enabled + default deny is enforced on DNS lookups too
        def _dns_query_exec(name):
            """<name>.query.<domain> → prepared-query execute, adapted to
            DNS's health-row shape (dns.py _query).  Runs under the same
            anonymous-token authorizer as direct DNS service lookups — a
            prepared query must not leak a service the token can't read."""
            res = self.api.query_executor.execute(name)
            if res is None:
                return None
            if not self.acl.resolve(None).service_read(res["Service"]):
                return None
            return [{"service": s} for s in res["Nodes"]]

        # DNS runs under the agent's default-token slot (falls back to
        # anonymous when unset) — a runtime token update via
        # /v1/agent/token/default takes effect on the next query
        self.dns = DNSServer(self.store, self.oracle, node_name=node_name,
                             port=dns_port,
                             authz=lambda: self.acl.resolve(
                                 self.api.tokens.user_token() or None),
                             query_executor=_dns_query_exec)
        from consul_tpu.remote_exec import RemoteExecutor
        self.remote_exec = RemoteExecutor(self.store, self.oracle,
                                          node_name,
                                          enabled=enable_remote_exec)
        # gRPC ADS control plane (ports.grpc; agent/xds/server.go:186):
        # None disables; 0 binds an ephemeral port.  Tokens arrive as
        # x-consul-token metadata and must grant service:write on the
        # proxied service, like the HTTP xDS route.
        self.xds_grpc = None
        if grpc_port is not None:
            from consul_tpu.xds_grpc import XdsGrpcServer

            def _sub_authz(token, topic, key):
                a = self.acl.resolve(token or None)
                if topic == "health" or topic == "services":
                    return a.service_read(key or "")
                if topic == "kv":
                    return a.key_read(key or "")
                if topic == "intentions":
                    return a.intention_read(key or "*")
                if topic == "nodes":
                    return a.node_read(key or "")
                return a.operator_read()

            self.xds_grpc = XdsGrpcServer(
                self.api.proxycfg, port=grpc_port,
                authorize=lambda token, svc: self.acl.resolve(
                    token or None).service_write(svc),
                subscribe_authorize=_sub_authz)
            self.api.grpc_port = self.xds_grpc.port
        self._reconcile_thread: Optional[threading.Thread] = None
        self._running = False

    def _check_notify(self, check_id: str, status: str, output: str) -> None:
        """Check-runner callback → local state → AE push (the reference's
        CheckNotifier wiring, agent/checks/check.go → local.UpdateCheck)."""
        if self.local.update_check(check_id, status, output):
            try:
                self.local.sync_changes(self.store)
            except Exception:
                pass  # syncer retries on its own cadence

    # ------------------------------------------------------------- lifecycle

    # ----------------------------------------------------- local persistence
    # service/check definitions survive restarts via data_dir files, the
    # reference's persisted services/checks reload (agent/agent.go:533-541)

    _persist_lock = None
    _restoring = False

    def _persist_local(self) -> None:
        if not self.data_dir or self._restoring:
            return
        import json
        import os

        from consul_tpu import storage
        if self._persist_lock is None:
            self._persist_lock = threading.Lock()
        with self._persist_lock:
            os.makedirs(self.data_dir, exist_ok=True)
            state = {"services": self.local.services(),
                     "checks": self.local.checks(),
                     "check_definitions": dict(self.checks.definitions)}
            try:
                # unique tmp per writer + atomic replace (the storage
                # seam): concurrent registrations must not interleave
                storage.atomic_replace(
                    os.path.join(self.data_dir, "local_state.json"),
                    json.dumps(state).encode())
            except OSError:
                pass    # best-effort persistence, like the reference

    def _restore_local(self) -> None:
        if not self.data_dir:
            return
        import json
        import os
        path = os.path.join(self.data_dir, "local_state.json")
        if not os.path.exists(path):
            return
        try:
            with open(path) as f:
                state = json.load(f)
        except (OSError, ValueError):
            return  # corrupt persistence must not block startup
        # suppress per-entry rewrites while restoring (a crash mid-restore
        # must not truncate the on-disk state to a partial set)
        self._restoring = True
        try:
            for sid, svc in state.get("services", {}).items():
                self.local.add_service(sid, svc["name"],
                                       port=svc.get("port", 0),
                                       tags=svc.get("tags") or [],
                                       meta=svc.get("meta") or {},
                                       address=svc.get("address", ""))
            for cid, chk in state.get("checks", {}).items():
                self.local.add_check(cid, chk.get("name", cid),
                                     status=chk.get("status", "critical"),
                                     service_id=chk.get("service_id", ""),
                                     output=chk.get("output", ""))
            # re-arm runners from persisted definitions — a restored TTL/
            # HTTP check must keep EXECUTING, not freeze at its last
            # status (agent/agent.go:533 re-arms CheckTypes)
            for cid, defn in state.get("check_definitions", {}).items():
                runner = self.checks.from_definition(cid, defn)
                if runner is not None:
                    self.checks.add(runner)
        finally:
            self._restoring = False

    def start(self, tick_seconds: float = 0.0,
              reconcile_interval: float = 0.5) -> None:
        self._restore_local()
        self.store.register_node(self.node_name, "127.0.0.1")
        self.store.register_check(self.node_name, "serfHealth",
                                  "Serf Health Status", status="passing")
        self.syncer.start()
        self.remote_exec.start()
        self.oracle.start(tick_seconds)
        self.api.start()
        self.dns.start()
        if self.xds_grpc is not None:
            self.xds_grpc.start()
        # usage gauges (agent/consul/usagemetrics wired server.go:568)
        from consul_tpu.usagemetrics import UsageReporter
        self.usage = UsageReporter(self.store)
        self.usage.start()
        self._running = True
        # warm the members/down-mask computation in THIS thread before the
        # reconcile thread exists: its first evaluation is an XLA compile
        # (~tens of seconds on a tunneled TPU), and a daemon thread stuck
        # mid-compile at interpreter exit aborts the TPU runtime
        try:
            self.oracle.members(limit=1)
        except Exception:
            pass

        def reconcile_loop():
            while self._running:
                try:
                    self.reconcile()
                except Exception:
                    pass
                self.store.expire_sessions()
                time.sleep(reconcile_interval)

        self._reconcile_thread = threading.Thread(target=reconcile_loop,
                                                  daemon=True)
        self._reconcile_thread.start()
        from consul_tpu import flight
        flight.emit("agent.started", labels={"node": self.node_name})

    def stop(self) -> None:
        from consul_tpu import flight
        flight.emit("agent.stopped", labels={"node": self.node_name})
        self._running = False
        if getattr(self, "usage", None) is not None:
            self.usage.stop()
        if self.xds_grpc is not None:
            # before proxycfg close: live ADS streams hold ProxyStates
            self.xds_grpc.stop()
        self.remote_exec.stop()
        self.checks.stop_all()
        self.syncer.stop()
        self.oracle.stop()
        self.api.stop()
        # after the HTTP listener: a late ?cached request must not
        # recreate views post-close
        if self.api.view_store is not None:
            self.api.view_store.close()
        self.api.agent_cache.close()
        if self.api._proxycfg is not None:
            self.api._proxycfg.close()
        self.dns.stop()
        if self._reconcile_thread:
            # compile-scale headroom: exiting while the thread is inside
            # an XLA compile tears down libtpu mid-call and aborts
            self._reconcile_thread.join(timeout=60.0)

    # ------------------------------------------------------------- reconcile

    def reconcile(self) -> None:
        """serf→catalog reconciliation (leader.go:1234 handleAliveMember /
        :1332 handleFailedMember / :1390 handleReapMember).

        Standalone-agent shape only: when the backing store is a raft
        Server with an attached oracle, the LEADER runs reconciliation
        (server.py _reconcile_members) and this no-ops — two concurrent
        reconcilers with different reap semantics must not race."""
        if getattr(self.store, "_oracle", None) is not None:
            return
        catalog_nodes = {n["node"] for n in self.store.nodes()}
        for m in self.oracle.members():
            name = m["name"]
            if name not in catalog_nodes:
                continue  # only reconcile catalog-registered members
            if m["status"] == "failed":
                checks = {c["check_id"]: c
                          for c in self.store.node_checks(name)}
                sh = checks.get("serfHealth")
                if sh is None or sh["status"] != "critical":
                    self.store.register_check(
                        name, "serfHealth", "Serf Health Status",
                        status="critical",
                        output="Agent not live or unreachable")
            elif m["status"] == "left":
                self.store.deregister_node(name)
            else:
                checks = {c["check_id"]: c
                          for c in self.store.node_checks(name)}
                sh = checks.get("serfHealth")
                if sh is not None and sh["status"] != "passing":
                    self.store.register_check(
                        name, "serfHealth", "Serf Health Status",
                        status="passing", output="Agent alive and reachable")

    def join_wan(self, router) -> None:
        """Join a multi-DC federation through a WanRouter (the agent's
        JoinWAN analogue, reference agent/consul/server.go:1100)."""
        self.api.attach_router(router)

    @property
    def http_address(self) -> str:
        return self.api.address
