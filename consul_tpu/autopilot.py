"""Autopilot: server-health tracking + dead-server cleanup.

The reference wires hashicorp/raft-autopilot (agent/consul/autopilot.go:67)
to watch server health (stats_fetcher.go) and, when a server stays
unhealthy past the stabilization window AND removing it cannot break
quorum (failure tolerance > 0), automatically remove it from the raft
configuration.  Same policy here, driven from the leader's tick: follower
liveness comes from per-peer append-ack timestamps (raft.last_ack), and
removal rides the replicated membership-change entry
(consensus/raft.py remove_peer).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional


@dataclasses.dataclass
class AutopilotConfig:
    """Operator-tunable knobs (operator_autopilot_endpoint.go shapes)."""

    cleanup_dead_servers: bool = True
    last_contact_threshold: float = 0.2     # seconds without an ack = unhealthy
    server_stabilization_time: float = 1.0  # unhealthy this long → removable


class Autopilot:
    def __init__(self, server, config: Optional[AutopilotConfig] = None):
        self.server = server
        self.config = config or AutopilotConfig()
        self._unhealthy_since: Dict[str, float] = {}
        self._last_healthy: Dict[str, bool] = {}
        self.removed: List[str] = []

    # --------------------------------------------------------------- health

    def server_health(self, now: float) -> List[dict]:
        """Per-server health view (/v1/operator/autopilot/health shape).
        Meaningful on the leader (followers lack ack state)."""
        raft = self.server.raft
        out = [{"ID": self.server.node_id, "Healthy": True,
                "Leader": raft.is_leader(), "LastContact": 0.0,
                "Voter": True}]
        for p in raft.peers:
            ack = raft.last_ack.get(p)
            last = (now - ack) if ack is not None else float("inf")
            out.append({
                "ID": p, "Leader": False, "Voter": True,
                "LastContact": round(last, 4) if last != float("inf")
                else -1.0,
                "Healthy": last <= self.config.last_contact_threshold,
            })
        return out

    def failure_tolerance(self, now: float) -> int:
        """How many more servers can fail before quorum loss."""
        healthy = sum(1 for h in self.server_health(now) if h["Healthy"])
        total = len(self.server.raft.peers) + 1
        quorum = total // 2 + 1
        return max(0, healthy - quorum)

    # -------------------------------------------------------------- cleanup

    def run(self, now: float) -> None:
        """One autopilot pass — call from the leader's tick
        (the reference's promoter loop)."""
        from consul_tpu import flight
        raft = self.server.raft
        if not raft.is_leader():
            return
        health = {h["ID"]: h for h in self.server_health(now)}
        # journal health TRANSITIONS (not steady state) BEFORE the
        # cleanup gate: turning dead-server cleanup off must not blind
        # the observability feed — ts is the caller's clock, virtual
        # under the test cluster, so timelines stay deterministic
        for sid, h in health.items():
            prev = self._last_healthy.get(sid)
            if prev is not None and prev != h["Healthy"]:
                flight.emit("autopilot.health.changed",
                            labels={"server": sid,
                                    "healthy": h["Healthy"]},
                            severity="info" if h["Healthy"] else "warn",
                            ts=now)
            self._last_healthy[sid] = h["Healthy"]
        if not self.config.cleanup_dead_servers:
            return
        for peer in list(raft.peers):
            h = health.get(peer)
            if h is None or h["Healthy"]:
                self._unhealthy_since.pop(peer, None)
                continue
            since = self._unhealthy_since.setdefault(peer, now)
            if now - since < self.config.server_stabilization_time:
                continue
            # only remove when the remaining cluster keeps quorum of the
            # CURRENT configuration (dead-server cleanup guard)
            if self.failure_tolerance(now) < 1:
                continue
            try:
                raft.remove_peer(peer)
                self.removed.append(peer)
                self._unhealthy_since.pop(peer, None)
                flight.emit("autopilot.server.removed",
                            labels={"server": peer}, ts=now)
            except Exception:
                pass  # not leader anymore / racing change — retry next tick
