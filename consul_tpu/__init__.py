"""consul_tpu — a TPU-native framework with HashiCorp Consul's capabilities.

Instead of porting Consul's goroutine-per-node Go design (reference at
/root/reference), the core is a synchronous-parallel cluster simulator/oracle:
the full membership, suspicion-timer, rumor-dissemination and RTT-coordinate
state lives in device arrays and advances one gossip tick at a time inside a
single jitted `step` function.  Host-side Python provides the Consul-shaped
control plane (catalog, KV, health, HTTP API, CLI) around it.

Layout (mirrors SURVEY.md §7 build plan):
  models/    — simulation models: SWIM membership, Serf events, Vivaldi, AE
  ops/       — tensor ops / Pallas kernels shared by the models
  parallel/  — device mesh + sharding helpers (node-axis SPMD)
  catalog/   — host-side state store (catalog/KV/sessions/health)
  api/       — HTTP API (Consul /v1 shape)
  utils/     — PRNG, clocks, metrics
"""

from consul_tpu.config import GossipConfig, SimConfig
from consul_tpu.models import swim

__version__ = "0.1.0"

__all__ = ["GossipConfig", "SimConfig", "swim", "__version__"]
