"""Commit-to-visibility tracing: how long until a committed write is SEEN.

PR 1 instrumented every layer in isolation (raft commitTime, http
latency) and PR 8 journals what happened — but nothing measured the
question the north star turns on: *a write commits; when does a parked
watcher actually observe it?*  This module threads one correlation
through the whole pipeline:

    raft FSM apply          -> StateStore._bump stamps (index, ts,
                               trace id of the proposing request)
    stream publish          -> EventPublisher fan-out stamps publish_ts
    watch wakeup            -> a parked blocking query that a write woke
                               samples apply->wakeup
    HTTP flush              -> the response write samples apply->flush

producing `consul.kv.visibility{stage}` latency histograms (each stage
measured FROM the apply — the per-stage p50/p99 curve the SLO probe in
tools/visibility_probe.py sweeps against watcher count), per-stage
trace spans sharing the WRITER's trace id (so `/v1/agent/traces
?trace_id=` shows one correlated write->delivery story), and a
`kv.visibility.stall` flight event when a stage blows its budget.

Design constraints, deliberate:

  * **Nothing emits under the store lock.**  `note_apply`/`note_publish`
    run inside `StateStore._apply_bump_effects` (store lock held) and
    are PURE table writes — one dict insert under this module's own
    lock, no sink I/O.  Samples, spans, and stall events are emitted by
    `stage()` on the OBSERVER's thread (the woken blocking query), off
    every store/publisher lock — the same staging rule raft's
    `_metrics_buf` and the store's `_query_metrics` follow.
  * **Bounded memory.**  An OrderedDict ring of TABLE_CAP records keyed
    by store index; old indexes fall off the front.  A watcher waking
    for an index that aged out simply emits nothing.
  * **Trace ids merge in any order.**  The proposer learns the store
    index only when its apply resolves, while replication can wake a
    watcher first — `note_apply` and `bind_trace` both upsert, so the
    record ends up correlated regardless of which side stamps first.
  * **The publish stage is emitted lazily**, once, by the first
    observer of that index: `EventPublisher.publish` also runs under
    the store lock, so it only stamps `publish_ts`; the first `stage()`
    call flips `publish_emitted` and emits the sample off-lock.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Optional, Tuple

from consul_tpu import locks

TABLE_CAP = 4096

# a stage lagging its apply by more than this journals a flight event
# (kv.visibility.stall) — the slow-subscriber tripwire for ROADMAP
# item 2's 1M-watcher redesign
STALL_SECONDS = 1.0

STAGES = ("publish", "wakeup", "flush")

# the mesh control plane's stages (ISSUE 16): a config-changing write's
# proxycfg snapshot rebuild and its xDS push, both measured FROM the
# same raft apply the KV stages measure from
XDS_STAGES = ("rebuild", "push")

# the thread applying a raft command binds the proposer's trace id here
# (raft._apply_committed wraps apply_fn in `applying(tid)`) so the
# store's _bump can correlate the index it mints without the trace
# riding the replicated command payload
_applying = threading.local()


class _ApplyScope:
    def __init__(self, trace_id: Optional[str]):
        self._tid = trace_id

    def __enter__(self):
        _applying.tid = self._tid
        return self

    def __exit__(self, *exc):
        _applying.tid = None
        return False


def applying(trace_id: Optional[str]) -> _ApplyScope:
    """Scope a raft FSM apply: store bumps inside the block bind
    `trace_id` (the proposer's) to the indexes they mint."""
    return _ApplyScope(trace_id)


def apply_trace() -> Optional[str]:
    return getattr(_applying, "tid", None)


class VisibilityTable:
    """index -> {apply_ts, publish_ts, trace_id, publish_emitted}."""

    def __init__(self, cap: int = TABLE_CAP, dc: str = "dc1"):
        self._cap = cap
        # the datacenter dimension (ISSUE 15): every emitted sample,
        # span, and stall event carries {dc} so a federated scrape can
        # tell DC2's wakeup quantiles from DC1's.  Plain attribute —
        # the owning ApiServer/agent rebinds it once at wiring time
        # (the store itself has no concept of a datacenter).
        self.dc = dc
        self._lock = locks.make_lock("visibility.table")
        # the bounded index->record ring  # guarded-by: _lock
        self._rec: "OrderedDict[int, dict]" = OrderedDict()
        locks.register_guards(self, self._lock, "_rec")

    # ------------------------------------------------------------- stamping
    # (called under the STORE lock — table writes only, no emission)

    def note_apply(self, index: int, ts: Optional[float] = None,
                   trace_id: Optional[str] = None) -> None:
        if trace_id is None:
            trace_id = apply_trace()
            if trace_id is None:
                # standalone (non-raft) writes run on the request
                # thread itself — its contextvar IS the proposer trace
                from consul_tpu import trace
                trace_id = trace.current_trace()
        now = time.time() if ts is None else ts
        with self._lock:
            rec = self._rec.get(index)
            if rec is None:
                rec = self._rec[index] = {"apply_ts": now,
                                          "publish_ts": None,
                                          "trace_id": trace_id or "",
                                          "publish_emitted": False}
                while len(self._rec) > self._cap:
                    self._rec.popitem(last=False)
            else:
                # bind_trace may have created the record with no
                # apply stamp yet (setdefault would keep the None)
                if rec.get("apply_ts") is None:
                    rec["apply_ts"] = now
                if trace_id and not rec.get("trace_id"):
                    rec["trace_id"] = trace_id

    def note_publish(self, index: int, ts: Optional[float] = None) -> None:
        now = time.time() if ts is None else ts
        with self._lock:
            rec = self._rec.get(index)
            if rec is not None and rec["publish_ts"] is None:
                rec["publish_ts"] = now

    def bind_trace(self, index: int, trace_id: Optional[str]) -> None:
        """Proposer-side late bind: the apply result carried the store
        index back to the thread that owns the request trace."""
        if not trace_id:
            return
        with self._lock:
            rec = self._rec.get(index)
            if rec is None:
                rec = self._rec[index] = {"apply_ts": None,
                                          "publish_ts": None,
                                          "trace_id": trace_id,
                                          "publish_emitted": False}
                while len(self._rec) > self._cap:
                    self._rec.popitem(last=False)
            elif not rec.get("trace_id"):
                rec["trace_id"] = trace_id

    # -------------------------------------------------------------- reading

    def lookup(self, index: int) -> Optional[dict]:
        with self._lock:
            rec = self._rec.get(index)
            return dict(rec) if rec is not None else None

    def stage(self, stage: str, index: int,
              ts: Optional[float] = None) -> Optional[Tuple[float, str]]:
        """Emit one observed stage for `index`: the
        `consul.kv.visibility{stage}` sample (seconds since apply), a
        `kv.visibility.<stage>` trace span under the WRITER's trace id,
        and a stall event past STALL_SECONDS.  Runs on the observer's
        thread — never call while holding the store/publisher lock.

        Returns (latency_s, trace_id), or None when the index aged out
        of the table (nothing to correlate against)."""
        now = time.time() if ts is None else ts
        emit_publish = None
        with self._lock:
            rec = self._rec.get(index)
            if rec is None or rec.get("apply_ts") is None:
                return None
            apply_ts = rec["apply_ts"]
            tid = rec.get("trace_id") or ""
            if not rec["publish_emitted"] and rec["publish_ts"] is not None:
                rec["publish_emitted"] = True
                emit_publish = rec["publish_ts"] - apply_ts
        from consul_tpu import telemetry, trace
        dc = self.dc
        if emit_publish is not None:
            lat = max(0.0, emit_publish)
            telemetry.add_sample(("kv", "visibility"), lat,
                                 labels={"stage": "publish", "dc": dc})
            trace.record("kv.visibility.publish", tid,
                         apply_ts, lat, index=index, dc=dc)
        lat = max(0.0, now - apply_ts)
        telemetry.add_sample(("kv", "visibility"), lat,
                             labels={"stage": stage, "dc": dc})
        trace.record(f"kv.visibility.{stage}", tid, apply_ts, lat,
                     index=index, dc=dc)
        if lat > STALL_SECONDS:
            from consul_tpu import flight
            flight.emit("kv.visibility.stall",
                        labels={"stage": stage, "index": index,
                                "ms": round(lat * 1000.0, 1),
                                "dc": dc},
                        trace_id=tid)
        return lat, tid

    def stage_xds(self, stage: str, index: int, proxy_kind: str,
                  proxy_id: str = "",
                  ts: Optional[float] = None
                  ) -> Optional[Tuple[float, str]]:
        """Emit one mesh-control-plane stage for `index` (ISSUE 16):
        the `consul.xds.visibility{stage,proxy_kind}` sample (seconds
        since apply), an `xds.visibility.<stage>` trace span under the
        WRITER's trace id, and an `xds.visibility.stall` flight event
        past STALL_SECONDS.  Same discipline as `stage()`: runs on the
        observer's thread (the proxycfg follow loop after releasing
        its condition, or the ADS/HTTP push thread) — never call while
        holding the store, publisher, or proxycfg locks.

        Returns (latency_s, trace_id), or None when the index aged out
        of the table (a rebuild triggered by pre-table history has
        nothing to correlate against)."""
        now = time.time() if ts is None else ts
        with self._lock:
            rec = self._rec.get(index)
            if rec is None or rec.get("apply_ts") is None:
                return None
            apply_ts = rec["apply_ts"]
            tid = rec.get("trace_id") or ""
        from consul_tpu import telemetry, trace
        lat = max(0.0, now - apply_ts)
        telemetry.add_sample(("xds", "visibility"), lat,
                             labels={"stage": stage,
                                     "proxy_kind": proxy_kind})
        trace.record(f"xds.visibility.{stage}", tid, apply_ts, lat,
                     index=index, proxy_kind=proxy_kind,
                     proxy=proxy_id or None, dc=self.dc)
        if lat > STALL_SECONDS:
            from consul_tpu import flight
            flight.emit("xds.visibility.stall",
                        labels={"stage": stage, "index": index,
                                "ms": round(lat * 1000.0, 1),
                                "proxy_kind": proxy_kind},
                        trace_id=tid)
        return lat, tid

    def clear(self) -> None:
        with self._lock:
            self._rec.clear()


# NO process-wide default table, deliberately: index spaces are
# per-store, and one process routinely hosts several stores (multi-DC
# tests, in-process clusters, secondary agents) — a shared table would
# cross-correlate store A's index 7 with store B's.  Each StateStore
# owns a VisibilityTable (`store.visibility`, also reachable through
# its EventPublisher for stream-side consumers); only the applying()
# trace scope is process-global, because a thread applies for exactly
# one store at a time.
