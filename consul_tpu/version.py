"""Version (reference: version/version.go:16)."""

VERSION = "0.1.0-tpu"
