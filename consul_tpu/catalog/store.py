"""Host-side state store: catalog / KV / sessions with watch semantics.

The live-path equivalent of the reference's memdb-backed state store
(agent/consul/state/state_store.go:102-120: Store methods + WatchSet
watches; schema agent/consul/state/schema.go:10).  The TPU oracle owns
membership/coordinates at simulation scale; this store owns the small-N
strongly-consistent side: service catalog, KV, sessions, health — with the
same observable semantics as the reference:

  * every write bumps a monotone raft-style index; reads report the index
    (X-Consul-Index equivalent) so clients can long-poll;
  * blocking queries: `wait_for(index, predicate, timeout)` parks until a
    relevant write lands, mirroring blockingQuery (agent/consul/rpc.go:806)
    with prefix-granular wakeups (memdb per-index watch channels);
  * KV supports flags, CAS, session locks with lock-delay
    (state/kvs.go lock semantics), recurse/prefix reads, tombstone-free
    delete-index tracking (deletes bump the prefix index like the
    reference's graveyard, state/graveyard.go);
  * sessions: TTL expiry + invalidation releases or deletes held locks
    (session behavior — agent/consul/session_ttl.go:110 invalidateSession).

Thread-safe; one process-wide lock (writes are small and fast — the bulk
work lives on the device).
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from consul_tpu import locks
from consul_tpu.native_index import PrefixIndex
from consul_tpu.stream.publisher import Event, EventPublisher

# Fine-grained watch fan-in cap: past this many parked blocking queries the
# store degrades to coarse any-write wakeups, like the reference's 8,192
# watch-channel limit (agent/consul/state/state_store.go:87-97).
WATCH_LIMIT = 8192


class _Waiter:
    """One parked blocking query and the (topic, key) set it watches."""

    __slots__ = ("cond", "fired", "watches")

    def __init__(self, lock, watches):
        self.cond = locks.make_condition(lock)
        self.fired = False
        self.watches = watches


def _watch_matches(watches, topic: str, key: str) -> bool:
    for wt, wk in watches:
        if wt == topic:
            if wk == "" or wk == key:
                return True
        elif wt == topic + ":prefix" and key.startswith(wk):
            return True
    return False


class StateStore:
    def __init__(self):
        self._lock = locks.make_rlock("store.state")
        self._cond = locks.make_condition(self._lock)
        self._index = 0             # guarded-by: _lock
        # streaming + fine-grained watches (stream/event_publisher.go:12;
        # per-index watch channels state_store.go:102-120)
        self.publisher = EventPublisher()
        # commit-to-visibility table (consul_tpu/visibility.py):
        # per-STORE because index spaces are — shared on the publisher
        # so stream-side consumers (submatview) can reach it
        from consul_tpu.visibility import VisibilityTable
        self.visibility = VisibilityTable()
        self.publisher.visibility = self.visibility
        self._waiters: List[_Waiter] = []   # guarded-by: _lock
        # parked blocking queries right now (coarse + fine), feeding the
        # consul.rpc.queries_blocking gauge (rpc.go's queriesBlocking).
        # Guarded by its own lock so gauge publication is ordered
        # WITHOUT holding the store lock across sink I/O.
        self._blocked = 0           # guarded-by: _blocked_lock
        self._blocked_lock = locks.make_lock("store.blocked_gauge")
        # topic -> ordered key->index map (native C++ prefix index when
        # buildable — the go-memdb radix-tree role; consul_tpu/
        # native_index.py): prefix watch lookups are O(log n + m), not a
        # full-topic scan
        self._topic_index: Dict[str, object] = {}   # guarded-by: _lock
        # topic -> idx  # guarded-by: _lock
        self._topic_max: Dict[str, int] = {}
        # compaction floor: when a topic's per-key map is compacted, keys
        # dropped resolve to this index (conservative — may cause a
        # spurious immediate return, never a missed wakeup).  This is the
        # tombstone-GC analogue (reference state/graveyard.go).
        self._topic_floor: Dict[str, int] = {}      # guarded-by: _lock
        # kv: key -> dict(value, flags, create_index, modify_index, session)
        # guarded-by: _lock
        self._kv: Dict[str, dict] = {}
        # prefix-bump on deletes  # guarded-by: _lock
        self._kv_delete_index: Dict[str, int] = {}
        # catalog
        self._nodes: Dict[str, dict] = {}
        self._services: Dict[Tuple[str, str], dict] = {}   # (node, sid) -> svc
        self._checks: Dict[Tuple[str, str], dict] = {}     # (node, cid) -> chk
        # sessions: id -> dict(node, ttl, behavior, create_index, expires, lock_delay)
        # guarded-by: _lock
        self._sessions: Dict[str, dict] = {}
        self._lock_delays: Dict[str, float] = {}           # key -> until ts
        # non-None while a txn is applying: _bump defers its effects
        # here so an abort publishes/wakes nothing (list of (idx, events))
        # guarded-by: _lock
        self._txn_events: Optional[list] = None
        locks.register_guards(self, self._lock, "_index", "_waiters",
                              "_topic_index", "_topic_max",
                              "_topic_floor", "_kv",
                              "_kv_delete_index", "_sessions",
                              "_txn_events")
        locks.register_guards(self, self._blocked_lock, "_blocked")
        # ACL tables (agent/consul/state/acl.go): policies by id, tokens by
        # accessor id; bootstrap is one-shot guarded by a reset index
        self._acl_policies: Dict[str, dict] = {}
        self._acl_tokens: Dict[str, dict] = {}
        self._acl_bootstrap_index = 0
        # prepared queries: id -> definition dict (state/prepared_query.go)
        self._queries: Dict[str, dict] = {}
        # connect intentions: id -> {source, destination, action,
        # precedence, ...} (state/intention.go)
        self._intentions: Dict[str, dict] = {}
        # centralized config entries: (kind, name) -> body
        # (state/config_entry.go)
        self._config_entries: Dict[Tuple[str, str], dict] = {}
        # auth methods + binding rules (state/acl.go auth method tables)
        self._auth_methods: Dict[str, dict] = {}
        self._binding_rules: Dict[str, dict] = {}
        # federation states: dc -> mesh gateway endpoints
        # (state/federation_state.go)
        self._federation_states: Dict[str, dict] = {}
        # pushed network coordinates: node -> coord dict
        # (state/coordinate.go).  Sim nodes read theirs from the oracle;
        # external agents land here via PUT /v1/coordinate/update.
        self._coordinates: Dict[str, dict] = {}

    # ------------------------------------------------------------------ core

    @property
    def index(self) -> int:
        with self._lock:
            return self._index

    # requires-lock: _lock
    def _bump(self, events: Sequence[Tuple[str, str]] = ()) -> int:
        """Advance the commit index, record per-(topic, key) indexes, wake
        matching fine-grained waiters, and publish stream events.

        `events`: (topic, key) pairs this write touched.  An empty list is a
        legacy coarse write: it wakes every waiter (conservative)."""
        self._index += 1
        idx = self._index
        if self._txn_events is not None:
            # mid-transaction: defer every externally visible effect
            # (topic indexes, waiter wakeups, stream events) until
            # commit — an aborted txn must leave no phantom watch
            # indexes and publish nothing (state/txn.go applies against
            # a txn that only commits as a unit)
            self._txn_events.append((idx, list(events)))
            return idx
        self._apply_bump_effects(idx, events)
        return idx

    # requires-lock: _lock
    def _apply_bump_effects(self, idx: int,
                            events: Sequence[Tuple[str, str]]) -> None:
        # commit-to-visibility: stamp (index, apply ts, proposer trace)
        # the moment this write becomes readable.  Pure table writes
        # (consul_tpu/visibility.py) — no sink I/O lands under the
        # store lock; the observing blocking query emits the samples.
        self.visibility.note_apply(idx)
        for topic, key in events:
            tmap = self._topic_index.get(topic)
            if tmap is None:
                tmap = self._topic_index[topic] = PrefixIndex()
            tmap.set(key, idx)
            if self._topic_max.get(topic, 0) < idx:
                self._topic_max[topic] = idx
            if len(tmap) > 65536:
                # compact: drop the whole per-key map behind a coarse
                # floor (one spurious wakeup per parked watcher of this
                # topic; never a missed one) — the tombstone-GC analogue
                self._topic_floor[topic] = self._topic_max[topic]
                self._topic_index[topic] = PrefixIndex()
        self._cond.notify_all()
        for w in self._waiters:
            if w.fired:
                continue
            if not events or any(_watch_matches(w.watches, t, k)
                                 for t, k in events):
                w.fired = True
                w.cond.notify_all()
        if events:
            tid = (self.visibility.lookup(idx) or {}).get(
                "trace_id") or ""
            self.publisher.publish([Event(topic=t, key=k, index=idx,
                                          trace_id=tid)
                                    for t, k in events])
            self.visibility.note_publish(idx)

    def watch_index(self, watches: Sequence[Tuple[str, str]]) -> int:
        """Highest commit index that touched any of `watches`.

        Watch forms: (topic, key) exact, (topic, "") topic-wide,
        (topic + ":prefix", prefix) prefix match (KV recurse)."""
        with self._lock:
            best = 0
            for wt, wk in watches:
                if wk == "" and not wt.endswith(":prefix"):
                    best = max(best, self._topic_max.get(wt, 0))
                elif wt.endswith(":prefix"):
                    topic = wt[: -len(":prefix")]
                    floor = self._topic_floor.get(topic, 0)
                    tmap = self._topic_index.get(topic)
                    pm = tmap.prefix_max(wk, 0) if tmap is not None else 0
                    best = max(best, floor, pm)
                else:
                    floor = self._topic_floor.get(wt, 0)
                    tmap = self._topic_index.get(wt)
                    got = tmap.get(wk, floor) if tmap is not None else floor
                    best = max(best, got)
            return best

    def wait_for(self, index: Optional[int], timeout: float = 300.0) -> int:
        """Park until the store index exceeds `index` (blocking query).

        Returns the current index.  index=None returns immediately.
        Mirrors agent/consul/rpc.go:806 blockingQuery: no spurious early
        return, wait capped by timeout.  This is the coarse (any-write)
        wakeup; prefer `wait_on` with watch specs."""
        deadline = time.time() + timeout
        if index is None or index <= 0:
            with self._lock:
                return self._index
        self._query_metrics()
        try:
            with self._lock:
                while self._index <= index:
                    remaining = deadline - time.time()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
                return self._index
        finally:
            self._query_metrics(-1)

    def wait_on(self, watches: Sequence[Tuple[str, str]],
                index: Optional[int], timeout: float = 300.0) -> int:
        """Park until a write touching `watches` lands with index > `index`.

        The prefix-granular blocking query: a KV write does not wake a
        health watcher.  Falls back to coarse wait past WATCH_LIMIT parked
        waiters (state_store.go:87-97).  Returns the current store index."""
        deadline = time.time() + timeout
        # index<=0 is non-blocking by contract (X-Consul-Index starts
        # at 1; blockingQuery treats MinQueryIndex 0 as immediate)
        if index is None or index <= 0 or not watches:
            with self._lock:
                return self._index
        self._query_metrics()
        try:
            with self._lock:
                if self.watch_index(watches) > index:
                    return self._index
                if len(self._waiters) >= WATCH_LIMIT:
                    while self._index <= index:
                        remaining = deadline - time.time()
                        if remaining <= 0:
                            break
                        self._cond.wait(remaining)
                    return self._index
                w = _Waiter(self._lock, list(watches))
                self._waiters.append(w)
                try:
                    while not w.fired:
                        remaining = deadline - time.time()
                        if remaining <= 0:
                            break
                        w.cond.wait(remaining)
                finally:
                    self._waiters.remove(w)
                return self._index
        finally:
            self._query_metrics(-1)

    def _query_metrics(self, delta: int = 1) -> None:
        """Refresh the parked-queries gauge (consul.rpc.queries_blocking,
        rpc.go's queriesBlocking) on wait entry/exit.  Publication
        happens under _blocked_lock so concurrent exits can't land a
        stale value out of order and wedge the gauge — and never under
        the STORE lock, so sink emission (UDP sendto per configured
        sink) can't serialize kv traffic behind syscalls.  The
        consul.rpc.query COUNTER lives at the HTTP blockingQuery layer
        (api/http.py _block): counting here would tally internal waits
        (consistent-read catch-up, hash-watch wakeups) as client
        queries."""
        from consul_tpu import telemetry
        with self._blocked_lock:
            self._blocked += delta
            # _blocked_lock is a dedicated LEAF lock that exists to
            # ORDER this one gauge publication; never held with the
            # store lock, so the staging rule does not apply here.
            # lint: ok=no-emit-under-lock (ordered publication under a dedicated leaf lock)
            telemetry.set_gauge(("rpc", "queries_blocking"),
                                float(self._blocked))

    # -------------------------------------------------------------------- KV

    def kv_set(self, key: str, value: bytes, flags: int = 0,
               cas: Optional[int] = None, acquire: Optional[str] = None,
               release: Optional[str] = None) -> Tuple[bool, int]:
        """PUT /v1/kv/<key> semantics incl. ?cas= ?acquire= ?release=
        (reference agent/kvs_endpoint.go:15, state/kvs.go)."""
        now = time.time()
        with self._lock:
            entry = self._kv.get(key)
            if cas is not None:
                current = entry["modify_index"] if entry else 0
                if cas != current:
                    return False, self._index
            if acquire is not None:
                if acquire not in self._sessions:
                    return False, self._index
                if now < self._lock_delays.get(key, 0.0):
                    return False, self._index
                if entry and entry.get("session") not in (None, acquire):
                    return False, self._index
            if release is not None:
                if entry is None or entry.get("session") != release:
                    return False, self._index
            idx = self._bump([("kv", key)])
            if entry is None:
                entry = {"value": value, "flags": flags, "create_index": idx,
                         "modify_index": idx, "session": None,
                         "lock_index": 0}
                self._kv[key] = entry
            else:
                entry["value"] = value
                entry["flags"] = flags
                entry["modify_index"] = idx
            if acquire is not None and entry.get("session") != acquire:
                entry["session"] = acquire
                entry["lock_index"] = entry.get("lock_index", 0) + 1
            if release is not None:
                entry["session"] = None
            return True, idx

    def kv_get(self, key: str) -> Optional[dict]:
        with self._lock:
            e = self._kv.get(key)
            return dict(e, key=key) if e else None

    def kv_list(self, prefix: str) -> List[dict]:
        with self._lock:
            return [dict(e, key=k) for k, e in sorted(self._kv.items())
                    if k.startswith(prefix)]

    def kv_keys(self, prefix: str, separator: str = "") -> List[str]:
        with self._lock:
            keys = sorted(k for k in self._kv if k.startswith(prefix))
        if not separator:
            return keys
        out: List[str] = []
        for k in keys:
            rest = k[len(prefix):]
            cut = rest.find(separator)
            item = k if cut < 0 else prefix + rest[: cut + len(separator)]
            if not out or out[-1] != item:
                out.append(item)
        return out

    def kv_delete(self, key: str, recurse: bool = False,
                  cas: Optional[int] = None) -> Tuple[bool, int]:
        with self._lock:
            keys = ([k for k in self._kv if k.startswith(key)] if recurse
                    else ([key] if key in self._kv else []))
            if cas is not None:
                entry = self._kv.get(key)
                current = entry["modify_index"] if entry else 0
                if cas != current:
                    return False, self._index
            if not keys:
                return True, self._index
            idx = self._bump([("kv", k) for k in keys])
            for k in keys:
                del self._kv[k]
                self._kv_delete_index[k] = idx
            return True, idx

    # --------------------------------------------------------------- catalog

    def register_node(self, node: str, address: str, meta: dict | None = None,
                      node_id: str | None = None) -> int:
        """Catalog.Register node part (agent/consul/catalog_endpoint.go:144)."""
        with self._lock:
            # a node UPDATE (address/meta change) alters every catalog
            # and health row of the services it hosts: wake their
            # topic watchers and materialized views too (the reference
            # folds node changes into service-health events,
            # agent/consul/state/events.go) — without this a shared
            # ("services", name) view serves a dead address forever
            ev = [("nodes", node)]
            for (n, _sid), v in self._services.items():
                if n == node:
                    ev += [("services", v["name"]),
                           ("health", v["name"])]
            idx = self._bump(ev)
            existing = self._nodes.get(node, {})
            self._nodes[node] = {
                "address": address, "meta": meta or {},
                "id": node_id or existing.get("id") or str(uuid.uuid4()),
                "create_index": existing.get("create_index", idx),
                "modify_index": idx,
            }
            return idx

    def register_service(self, node: str, service_id: str, name: str,
                         port: int = 0, tags: List[str] | None = None,
                         meta: dict | None = None, address: str = "",
                         kind: str = "", proxy: dict | None = None) -> int:
        """`kind`/`proxy` carry the mesh shape (connect-proxy sidecars
        with destination + upstreams — structs.NodeService Kind/Proxy)."""
        with self._lock:
            if node not in self._nodes:
                self.register_node(node, address or "127.0.0.1")
            idx = self._bump([("nodes", node), ("services", name),
                              ("health", name)])
            key = (node, service_id)
            existing = self._services.get(key, {})
            self._services[key] = {
                "name": name, "port": port, "tags": tags or [],
                "meta": meta or {}, "address": address,
                "kind": kind, "proxy": proxy or {},
                "create_index": existing.get("create_index", idx),
                "modify_index": idx,
            }
            return idx

    def _check_events(self, node: str, service_id: str):
        """Watch events for a check write: a node-level check touches the
        health of every service on the node (the reference's health query
        watches the checks table; health_endpoint.go:174)."""
        ev = [("nodechecks", node)]
        if service_id:
            svc = self._services.get((node, service_id))
            if svc:
                ev.append(("health", svc["name"]))
                # a sidecar's check gates its DESTINATION's connect
                # rows (health_connect_nodes folds proxy checks into
                # the app's health) — wake the app's health watchers
                dest = (svc.get("proxy") or {}).get(
                    "destination_service")
                if svc.get("kind") == "connect-proxy" and dest:
                    ev.append(("health", dest))
        else:
            for (n, _sid), v in self._services.items():
                if n == node:
                    ev.append(("health", v["name"]))
        return ev

    def register_check(self, node: str, check_id: str, name: str,
                       status: str = "critical", service_id: str = "",
                       output: str = "") -> int:
        with self._lock:
            idx = self._bump(self._check_events(node, service_id))
            key = (node, check_id)
            existing = self._checks.get(key, {})
            self._checks[key] = {
                "name": name, "status": status, "service_id": service_id,
                "output": output,
                "create_index": existing.get("create_index", idx),
                "modify_index": idx,
            }
            return idx

    def update_check(self, node: str, check_id: str, status: str,
                     output: str = "") -> int:
        with self._lock:
            key = (node, check_id)
            if key not in self._checks:
                raise KeyError(f"unknown check {key}")
            idx = self._bump(self._check_events(
                node, self._checks[key]["service_id"]))
            self._checks[key]["status"] = status
            self._checks[key]["output"] = output
            self._checks[key]["modify_index"] = idx
            return idx

    def deregister_node(self, node: str) -> int:
        """Full node deregistration cascades services/checks/sessions/locks
        (leader reconcile path, agent/consul/leader.go:1332)."""
        with self._lock:
            ev = [("nodes", node), ("nodechecks", node)]
            for (n, _sid), v in self._services.items():
                if n == node:
                    ev += [("services", v["name"]), ("health", v["name"])]
            idx = self._bump(ev)
            self._nodes.pop(node, None)
            for key in [k for k in self._services if k[0] == node]:
                del self._services[key]
            for key in [k for k in self._checks if k[0] == node]:
                del self._checks[key]
            for sid in [s for s, v in self._sessions.items()
                        if v["node"] == node]:
                self._invalidate_session_locked(sid)
            return idx

    def deregister_check(self, node: str, check_id: str) -> int:
        with self._lock:
            chk = self._checks.get((node, check_id))
            idx = self._bump(self._check_events(
                node, chk["service_id"] if chk else ""))
            self._checks.pop((node, check_id), None)
            return idx

    def deregister_service(self, node: str, service_id: str) -> int:
        with self._lock:
            svc = self._services.get((node, service_id))
            ev = [("nodes", node)]
            if svc:
                ev += [("services", svc["name"]), ("health", svc["name"])]
            idx = self._bump(ev)
            self._services.pop((node, service_id), None)
            for key in [k for k, c in self._checks.items()
                        if k[0] == node and c["service_id"] == service_id]:
                del self._checks[key]
            return idx

    def node_get(self, node: str) -> Optional[dict]:
        with self._lock:
            v = self._nodes.get(node)
            return dict(v, node=node) if v else None

    def nodes(self) -> List[dict]:
        with self._lock:
            return [dict(v, node=k) for k, v in sorted(self._nodes.items())]

    def service_by_id(self, service_id: str) -> Optional[dict]:
        """Single-pass (node, id) lookup — no per-node list builds (the
        proxycfg watch path polls this per xDS request)."""
        with self._lock:
            for (n, sid), v in self._services.items():
                if sid == service_id:
                    return dict(v, id=sid, node=n)
            return None

    def node_service(self, node: str, service_id: str) -> Optional[dict]:
        """Exact (node, id) row — the txn ACL path resolves the
        REGISTERED service name from it, not the client-supplied one."""
        with self._lock:
            v = self._services.get((node, service_id))
            return dict(v, id=service_id, node=node) if v else None

    def node_services(self, node: str) -> List[dict]:
        with self._lock:
            return [dict(v, id=sid, node=n)
                    for (n, sid), v in sorted(self._services.items())
                    if n == node]

    def services(self) -> Dict[str, List[str]]:
        """GET /v1/catalog/services shape: name -> union of tags."""
        with self._lock:
            out: Dict[str, set] = {}
            for v in self._services.values():
                out.setdefault(v["name"], set()).update(v["tags"])
            return {k: sorted(v) for k, v in sorted(out.items())}

    def service_nodes(self, name: str, tag: Optional[str] = None) -> List[dict]:
        with self._lock:
            rows = []
            for (node, sid), v in sorted(self._services.items()):
                if v["name"] != name:
                    continue
                if tag and tag not in v["tags"]:
                    continue
                nrec = self._nodes.get(node, {})
                rows.append({"node": node, "address": nrec.get("address", ""),
                             "service_id": sid, "service_name": name,
                             "port": v["port"], "tags": v["tags"],
                             "meta": v.get("meta", {}),
                             "service_address": v["address"],
                             "kind": v.get("kind", ""),
                             "proxy": v.get("proxy", {}),
                             "modify_index": v["modify_index"]})
            return rows

    def service_kind_map(self) -> Dict[str, set]:
        """{service name -> set of kinds} in ONE table pass — wildcard
        gateway expansion and mesh-gateway rebuilds must not pay a
        per-name table scan."""
        with self._lock:
            kinds: Dict[str, set] = {}
            for (_node, _sid), v in self._services.items():
                kinds.setdefault(v["name"], set()).add(
                    v.get("kind", ""))
            return kinds

    def healthy_plain_endpoints(self) -> Dict[str, List[dict]]:
        """One-pass {plain service -> healthy endpoints}: the
        mesh-gateway snapshot input (every kind-less service, instances
        with no critical check).  Services whose instances are all
        critical still appear, with an empty list."""
        with self._lock:
            crit_node, crit_svc = set(), set()
            for (n, _cid), c in self._checks.items():
                if c["status"] == "critical":
                    if c["service_id"]:
                        crit_svc.add((n, c["service_id"]))
                    else:
                        crit_node.add(n)
            kinds: Dict[str, set] = {}
            for (_node, _sid), v in self._services.items():
                kinds.setdefault(v["name"], set()).add(
                    v.get("kind", ""))
            out: Dict[str, List[dict]] = {}
            for (node, sid), v in sorted(self._services.items()):
                name = v["name"]
                if kinds[name] - {""}:
                    continue       # proxies/gateways are not targets
                out.setdefault(name, [])
                if node in crit_node or (node, sid) in crit_svc:
                    continue
                out[name].append({
                    "address": v["address"]
                    or self._nodes.get(node, {}).get("address", ""),
                    "port": v["port"], "node": node})
            return out

    def usage(self) -> dict:
        """One-pass usage counters (usagemetrics getUsage)."""
        with self._lock:
            names = set()
            connect = 0
            for v in self._services.values():
                names.add(v["name"])
                if v.get("kind") == "connect-proxy":
                    connect += 1
            return {"nodes": len(self._nodes),
                    "services": len(names),
                    "service_instances": len(self._services),
                    "kv_entries": len(self._kv),
                    "sessions": len(self._sessions),
                    "connect_instances": connect}

    def connect_service_nodes(self, name: str) -> List[dict]:
        """Mesh-capable instances for `name`: sidecar proxies whose
        destination is `name` (Catalog.ServiceNodes with Connect=true —
        agent/consul/state/catalog.go serviceNodesConnect).

        Each row carries the APP instance it fronts under `app`
        (id/tags/meta/port of the destination service on the same
        node) — subset bexpr filters evaluate against the app row, as
        the reference's CheckConnectServiceNodes filters actual
        service instances and maps to their sidecars."""
        with self._lock:
            # one linear pass builds the app index the rows resolve
            # against: ALL non-proxy instances per (node, service
            # name) — the fallback when a registration omits
            # destination_service_id
            node_apps: Dict[Tuple[str, str],
                            List[Tuple[str, dict]]] = {}
            for (node, sid), v in sorted(self._services.items()):
                if not v.get("kind"):
                    node_apps.setdefault((node, v["name"]),
                                         []).append((sid, v))
            rows = []
            for (node, sid), v in sorted(self._services.items()):
                if v.get("kind") != "connect-proxy":
                    continue
                proxy = v.get("proxy") or {}
                dest = proxy.get("destination_service", "")
                if dest != name:
                    continue
                dest_id = proxy.get("destination_service_id", "")
                app = self._services.get((node, dest_id)) \
                    if dest_id else None
                # a mis-set id (another sidecar, a different service)
                # must not attach an unrelated record's metadata
                if app is not None and (app.get("kind")
                                        or app["name"] != dest):
                    app = None
                if app is None:
                    candidates = node_apps.get((node, dest), [])
                    # the auto-registration naming convention pairs
                    # "<app-id>-sidecar-proxy" to its app even when
                    # the id field was stripped
                    by_name = [(aid, a) for aid, a in candidates
                               if sid == f"{aid}-sidecar-proxy"]
                    if by_name:
                        dest_id, app = by_name[0]
                    elif len(candidates) == 1:
                        # unambiguous: the node's only instance
                        dest_id, app = candidates[0]
                    else:
                        # several instances, none claimable: attaching
                        # an arbitrary one would steer subset traffic
                        # to the wrong sidecar — attach none
                        dest_id, app = "", None
                nrec = self._nodes.get(node, {})
                rows.append({"node": node,
                             "address": nrec.get("address", ""),
                             "service_id": sid,
                             "service_name": v["name"],
                             "port": v["port"], "tags": v["tags"],
                             "meta": v.get("meta", {}),
                             "service_address": v["address"],
                             "kind": v.get("kind", ""),
                             "proxy": v.get("proxy", {}),
                             "app": ({"id": dest_id,
                                      "service_name": app["name"],
                                      "tags": app.get("tags", []),
                                      "meta": app.get("meta", {}),
                                      "port": app.get("port", 0)}
                                     if app is not None else None),
                             "modify_index": v["modify_index"]})
            return rows

    def health_connect_nodes(self, name: str,
                             passing_only: bool = False) -> List[dict]:
        """health_service_nodes over the connect (proxy) instances
        (Health.ServiceNodes Connect=true, health_endpoint.go)."""
        with self._lock:
            rows = []
            for svc in self.connect_service_nodes(name):
                node, sid = svc["node"], svc["service_id"]
                checks = [dict(c, check_id=cid, node=n)
                          for (n, cid), c in sorted(self._checks.items())
                          if n == node and c["service_id"] in ("", sid)]
                if passing_only and any(c["status"] != "passing"
                                        for c in checks):
                    continue
                rows.append({"service": svc, "checks": checks})
            return rows

    def health_service_nodes(self, name: str, tag: Optional[str] = None,
                             passing_only: bool = False) -> List[dict]:
        """GET /v1/health/service/<name> (agent/consul/health_endpoint.go:174):
        service rows joined with their node+service checks."""
        with self._lock:
            rows = []
            for svc in self.service_nodes(name, tag):
                node, sid = svc["node"], svc["service_id"]
                checks = [dict(c, check_id=cid, node=n)
                          for (n, cid), c in sorted(self._checks.items())
                          if n == node and c["service_id"] in ("", sid)]
                if passing_only and any(c["status"] != "passing"
                                        for c in checks):
                    continue
                rows.append({"service": svc, "checks": checks})
            return rows

    def node_checks(self, node: str) -> List[dict]:
        with self._lock:
            return [dict(c, check_id=cid) for (n, cid), c
                    in sorted(self._checks.items()) if n == node]

    def checks_in_state(self, status: str) -> List[dict]:
        with self._lock:
            return [dict(c, check_id=cid, node=n)
                    for (n, cid), c in sorted(self._checks.items())
                    if status == "any" or c["status"] == status]

    # -------------------------------------------------------------- sessions

    def session_create(self, node: str, ttl: float = 0.0,
                       behavior: str = "release",
                       lock_delay: float = 15.0,
                       checks: List[str] | None = None,
                       sid: Optional[str] = None,
                       now: Optional[float] = None) -> Tuple[str, int]:
        """PUT /v1/session/create (agent/consul/session_endpoint.go).

        `sid` and `now` are caller-supplied when the write is
        raft-replicated: ids and clocks must be fixed at the proposer so
        replica FSM applies stay pure functions of the command."""
        now = now if now is not None else time.time()
        with self._lock:
            if node not in self._nodes:
                raise KeyError(f"unknown node {node}")
            sid = sid or str(uuid.uuid4())
            idx = self._bump([("sessions", sid)])
            self._sessions[sid] = {
                "node": node, "ttl": ttl, "behavior": behavior,
                "lock_delay": lock_delay, "checks": checks or ["serfHealth"],
                "create_index": idx,
                "expires": (now + ttl) if ttl > 0 else None,
            }
            return sid, idx

    def session_renew(self, sid: str, now: Optional[float] = None) -> bool:
        now = now if now is not None else time.time()
        with self._lock:
            sess = self._sessions.get(sid)
            if sess is None:
                return False
            if sess["ttl"] > 0:
                sess["expires"] = now + sess["ttl"]
            return True

    def session_destroy(self, sid: str, now: Optional[float] = None) -> int:
        with self._lock:
            self._invalidate_session_locked(sid, now)
            return self._index

    def session_info(self, sid: str) -> Optional[dict]:
        with self._lock:
            s = self._sessions.get(sid)
            return dict(s, id=sid) if s else None

    def session_list(self) -> List[dict]:
        with self._lock:
            return [dict(v, id=k) for k, v in sorted(self._sessions.items())]

    def peek_expired_sessions(self, now: Optional[float] = None) -> List[str]:
        """Expired-but-not-yet-invalidated session ids, WITHOUT mutating —
        the leader proposes the destroys through raft, every replica applies
        (session_ttl.go:45: timers run on the leader only)."""
        now = now if now is not None else time.time()
        with self._lock:
            return [sid for sid, s in self._sessions.items()
                    if s["expires"] is not None and now >= s["expires"]]

    def expire_sessions(self, now: Optional[float] = None) -> List[str]:
        """TTL sweep — the leader's session timer loop
        (agent/consul/session_ttl.go:45 initializeSessionTimers)."""
        now = now if now is not None else time.time()
        expired = []
        with self._lock:
            for sid, sess in list(self._sessions.items()):
                if sess["expires"] is not None and now >= sess["expires"]:
                    expired.append(sid)
                    self._invalidate_session_locked(sid)
        return expired

    # requires-lock: _lock
    def _invalidate_session_locked(self, sid: str,
                                   now: Optional[float] = None) -> None:
        """Release/delete locks held by the session, then drop it
        (invalidateSession — agent/consul/session_ttl.go:110)."""
        now = now if now is not None else time.time()
        sess = self._sessions.pop(sid, None)
        if sess is None:
            return
        idx = self._bump([("sessions", sid)] +
                         [("kv", k) for k, e in self._kv.items()
                          if e.get("session") == sid])
        delay = sess.get("lock_delay", 0.0)
        for key, entry in list(self._kv.items()):
            if entry.get("session") == sid:
                if sess["behavior"] == "delete":
                    del self._kv[key]
                    self._kv_delete_index[key] = idx
                else:
                    entry["session"] = None
                    entry["modify_index"] = idx
                if delay > 0:
                    self._lock_delays[key] = now + delay

    # -------------------------------------------------------------------- ACL
    # CRUD mirrors agent/consul/state/acl.go (ACLPolicySet/Get/List/Delete,
    # ACLTokenSet/...); ids are proposer-supplied so replicas stay pure.

    def acl_policy_set(self, pid: str, name: str, rules: str,
                       description: str = "") -> int:
        with self._lock:
            clash = next((p for p, v in self._acl_policies.items()
                          if v["name"] == name and p != pid), None)
            if clash:
                raise ValueError(f"policy name {name!r} already in use")
            idx = self._bump([("acl", f"policy:{pid}")])
            existing = self._acl_policies.get(pid, {})
            self._acl_policies[pid] = {
                "name": name, "rules": rules, "description": description,
                "create_index": existing.get("create_index", idx),
                "modify_index": idx,
            }
            return idx

    def acl_policy_get(self, pid: str) -> Optional[dict]:
        with self._lock:
            p = self._acl_policies.get(pid)
            return dict(p, id=pid) if p else None

    def acl_policy_get_by_name(self, name: str) -> Optional[dict]:
        with self._lock:
            for pid, p in self._acl_policies.items():
                if p["name"] == name:
                    return dict(p, id=pid)
            return None

    def acl_policy_list(self) -> List[dict]:
        with self._lock:
            return [dict(v, id=k)
                    for k, v in sorted(self._acl_policies.items(),
                                       key=lambda kv: kv[1]["name"])]

    def acl_policy_delete(self, pid: str) -> int:
        with self._lock:
            if pid not in self._acl_policies:
                return self._index
            idx = self._bump([("acl", f"policy:{pid}")])
            name = self._acl_policies[pid]["name"]
            del self._acl_policies[pid]
            # strip links by id AND by name — a dangling name link would
            # silently re-bind to any future policy reusing the name
            for tok in self._acl_tokens.values():
                tok["policies"] = [p for p in tok["policies"]
                                   if p not in (pid, name)]
            return idx

    def acl_token_set(self, accessor: str, secret: str,
                      policies: List[str] | None = None,
                      description: str = "", token_type: str = "client",
                      local: bool = False,
                      service_identities: List[dict] | None = None,
                      node_identities: List[dict] | None = None) -> int:
        """Identities are the high-level grants real deployments mint
        per-sidecar/per-agent tokens with (structs.ACLServiceIdentity
        agent/structs/acl.go:141, ACLNodeIdentity :193); the resolver
        synthesizes their policies at compile time."""
        with self._lock:
            idx = self._bump([("acl", f"token:{accessor}")])
            existing = self._acl_tokens.get(accessor, {})
            self._acl_tokens[accessor] = {
                "secret": secret, "policies": policies or [],
                "description": description, "type": token_type,
                "local": local,
                "service_identities": service_identities or [],
                "node_identities": node_identities or [],
                "create_index": existing.get("create_index", idx),
                "modify_index": idx,
            }
            return idx

    def acl_token_get(self, accessor: str) -> Optional[dict]:
        with self._lock:
            t = self._acl_tokens.get(accessor)
            return dict(t, accessor=accessor) if t else None

    def acl_token_get_by_secret(self, secret: str) -> Optional[dict]:
        with self._lock:
            for accessor, t in self._acl_tokens.items():
                if t["secret"] == secret:
                    return dict(t, accessor=accessor)
            return None

    def acl_token_list(self) -> List[dict]:
        with self._lock:
            return [dict(v, accessor=k)
                    for k, v in sorted(self._acl_tokens.items())]

    def acl_token_delete(self, accessor: str) -> int:
        with self._lock:
            if accessor not in self._acl_tokens:
                return self._index
            idx = self._bump([("acl", f"token:{accessor}")])
            del self._acl_tokens[accessor]
            return idx

    def acl_bootstrap(self, accessor: str, secret: str) -> Tuple[bool, int]:
        """One-shot management-token mint (ACLBootstrap —
        agent/consul/acl_endpoint.go Bootstrap; reset via bootstrap index)."""
        with self._lock:
            if self._acl_bootstrap_index:
                return False, self._acl_bootstrap_index
            idx = self.acl_token_set(accessor, secret, [],
                                     "Bootstrap Token (Global Management)",
                                     token_type="management")
            self._acl_bootstrap_index = idx
            return True, idx

    def acl_bootstrap_reset(self) -> int:
        """Operator escape hatch: write the reset index to re-arm bootstrap
        (the reference's acl-bootstrap-reset file protocol)."""
        with self._lock:
            self._acl_bootstrap_index = 0
            return self._index

    # -------------------------------------------------------- prepared queries
    # CRUD mirrors state/prepared_query.go (PreparedQuerySet/Get/List/
    # Delete); ids are proposer-supplied uuids.

    def query_set(self, qid: str, query: dict) -> int:
        tpl = query.get("template") or {}
        if tpl.get("type") == "regexp":
            import re as _re
            try:
                _re.compile(tpl.get("regexp", ""))
            except _re.error as e:
                raise ValueError(f"invalid template regexp: {e}")
        with self._lock:
            name = query.get("name", "")
            if name:
                clash = next((q for i, q in self._queries.items()
                              if q.get("name") == name and i != qid), None)
                if clash is not None:
                    raise ValueError(f"query name {name!r} already in use")
            idx = self._bump([("queries", qid)])
            existing = self._queries.get(qid, {})
            self._queries[qid] = dict(
                query,
                create_index=existing.get("create_index", idx),
                modify_index=idx)
            return idx

    def query_get(self, qid: str) -> Optional[dict]:
        with self._lock:
            q = self._queries.get(qid)
            return dict(q, id=qid) if q else None

    def query_get_by_name(self, name: str) -> Optional[dict]:
        with self._lock:
            for qid, q in self._queries.items():
                if q.get("name") == name:
                    return dict(q, id=qid)
            return None

    def query_list(self) -> List[dict]:
        with self._lock:
            return [dict(q, id=i) for i, q in sorted(self._queries.items())]

    def query_delete(self, qid: str) -> int:
        with self._lock:
            if qid not in self._queries:
                return self._index
            idx = self._bump([("queries", qid)])
            del self._queries[qid]
            return idx

    # ------------------------------------------------------ federation states
    # pushed network coordinates (agent/consul/state/coordinate.go;
    # batched writes coordinate_endpoint.go:63-113)

    def coordinate_batch_update(self, updates: List[dict]) -> int:
        """Apply a batch of {node, coord} updates (the reference stages
        Coordinate.Update calls and raft-applies batches of 128×5)."""
        with self._lock:
            idx = self._bump([("coordinates", u["node"])
                              for u in updates])
            for u in updates:
                self._coordinates[u["node"]] = {
                    "coord": dict(u["coord"]),
                    "modify_index": idx,
                }
            return idx

    def coordinate_get(self, node: str) -> Optional[dict]:
        with self._lock:
            c = self._coordinates.get(node)
            return dict(c, node=node) if c else None

    def coordinate_list(self) -> List[dict]:
        with self._lock:
            return [dict(v, node=k)
                    for k, v in sorted(self._coordinates.items())]

    # per-DC mesh gateway lists replicated from the primary
    # (state/federation_state.go FederationStateSet/Get/List)

    def federation_state_set(self, dc: str, gateways: List[dict],
                             updated: str = "") -> int:
        with self._lock:
            idx = self._bump([("federation", dc)])
            existing = self._federation_states.get(dc, {})
            self._federation_states[dc] = {
                "datacenter": dc, "mesh_gateways": list(gateways),
                "updated": updated,
                "create_index": existing.get("create_index", idx),
                "modify_index": idx}
            return idx

    def federation_state_get(self, dc: str) -> Optional[dict]:
        with self._lock:
            f = self._federation_states.get(dc)
            return dict(f) if f else None

    def federation_state_list(self) -> List[dict]:
        with self._lock:
            return [dict(v) for _k, v in
                    sorted(self._federation_states.items())]

    def federation_state_delete(self, dc: str) -> int:
        with self._lock:
            if dc not in self._federation_states:
                return self._index
            idx = self._bump([("federation", dc)])
            del self._federation_states[dc]
            return idx

    # ---------------------------------------------------------- auth methods
    # CRUD mirrors state/acl.go ACLAuthMethod*/ACLBindingRule*

    def auth_method_set(self, name: str, method_type: str,
                        config: dict | None = None,
                        description: str = "") -> int:
        with self._lock:
            idx = self._bump([("acl", f"authmethod:{name}")])
            existing = self._auth_methods.get(name, {})
            self._auth_methods[name] = {
                "name": name, "type": method_type,
                "config": config or {}, "description": description,
                "create_index": existing.get("create_index", idx),
                "modify_index": idx}
            return idx

    def auth_method_get(self, name: str) -> Optional[dict]:
        with self._lock:
            m = self._auth_methods.get(name)
            return dict(m) if m else None

    def auth_method_list(self) -> List[dict]:
        with self._lock:
            return [dict(v) for _k, v in sorted(self._auth_methods.items())]

    def auth_method_delete(self, name: str) -> int:
        with self._lock:
            if name not in self._auth_methods:
                return self._index
            idx = self._bump([("acl", f"authmethod:{name}")])
            del self._auth_methods[name]
            for rid in [r for r, v in self._binding_rules.items()
                        if v["auth_method"] == name]:
                del self._binding_rules[rid]
            return idx

    def binding_rule_set(self, rid: str, auth_method: str,
                         selector: str = "", bind_type: str = "policy",
                         bind_name: str = "") -> int:
        with self._lock:
            if auth_method not in self._auth_methods:
                raise ValueError(f"unknown auth method {auth_method!r}")
            idx = self._bump([("acl", f"bindingrule:{rid}")])
            existing = self._binding_rules.get(rid, {})
            self._binding_rules[rid] = {
                "id": rid, "auth_method": auth_method,
                "selector": selector, "bind_type": bind_type,
                "bind_name": bind_name,
                "create_index": existing.get("create_index", idx),
                "modify_index": idx}
            return idx

    def binding_rule_list(self,
                          auth_method: Optional[str] = None) -> List[dict]:
        with self._lock:
            return [dict(v) for _k, v in sorted(self._binding_rules.items())
                    if auth_method is None
                    or v["auth_method"] == auth_method]

    def binding_rule_delete(self, rid: str) -> int:
        with self._lock:
            if rid not in self._binding_rules:
                return self._index
            idx = self._bump([("acl", f"bindingrule:{rid}")])
            del self._binding_rules[rid]
            return idx

    # -------------------------------------------------------- config entries
    # CRUD mirrors state/config_entry.go (EnsureConfigEntry/ConfigEntry/
    # ConfigEntries/DeleteConfigEntry); kinds are the L7 routing trio

    def config_entry_set(self, kind: str, name: str, body: dict) -> int:
        from consul_tpu.discoverychain import KINDS
        if kind not in KINDS:
            raise ValueError(f"unsupported config entry kind {kind!r}")
        if kind == "ingress-gateway":
            # tcp carries no routing discriminator: exactly one service
            # per tcp listener (structs/config_entry_gateways.go
            # validation); a wildcard cannot be a tcp target either
            for li in body.get("listeners") or []:
                svcs = li.get("services") or []
                if li.get("protocol", "tcp") == "tcp":
                    if len(svcs) != 1:
                        raise ValueError(
                            f"ingress tcp listener on port "
                            f"{li.get('port', 0)} must have exactly "
                            f"one service, got {len(svcs)}")
                    if svcs[0].get("name", "") == "*":
                        raise ValueError(
                            "ingress tcp listener cannot bind the "
                            "wildcard service")
        with self._lock:
            idx = self._bump([("config", f"{kind}/{name}")])
            existing = self._config_entries.get((kind, name), {})
            self._config_entries[(kind, name)] = dict(
                body, kind=kind, name=name,
                create_index=existing.get("create_index", idx),
                modify_index=idx)
            return idx

    def config_entry_get(self, kind: str, name: str) -> Optional[dict]:
        with self._lock:
            e = self._config_entries.get((kind, name))
            return dict(e) if e else None

    def config_entry_list(self, kind: Optional[str] = None) -> List[dict]:
        with self._lock:
            return [dict(v) for (k, _n), v in
                    sorted(self._config_entries.items())
                    if kind is None or k == kind]

    def config_entry_delete(self, kind: str, name: str) -> int:
        with self._lock:
            if (kind, name) not in self._config_entries:
                return self._index
            idx = self._bump([("config", f"{kind}/{name}")])
            del self._config_entries[(kind, name)]
            return idx

    # ------------------------------------------------------------ intentions
    # CRUD mirrors state/intention.go; precedence is computed at write so
    # match/check order is a pure read (structs.Intention UpdatePrecedence)

    def intention_set(self, iid: str, source: str, destination: str,
                      action: str, description: str = "",
                      meta: dict | None = None) -> int:
        from consul_tpu.connect.intentions import precedence
        if action not in ("allow", "deny"):
            raise ValueError(f"intention action must be allow|deny, "
                             f"got {action!r}")
        if not source or not destination:
            raise ValueError("intention source/destination must be "
                             "non-empty (use \"*\" for wildcard)")
        with self._lock:
            dup = next((i for i, v in self._intentions.items()
                        if v["source"] == source
                        and v["destination"] == destination
                        and i != iid), None)
            if dup is not None:
                raise ValueError(
                    f"duplicate intention {source!r} -> {destination!r}")
            idx = self._bump([("intentions", destination)])
            existing = self._intentions.get(iid, {})
            self._intentions[iid] = {
                "source": source, "destination": destination,
                "action": action, "description": description,
                "meta": meta or {},
                "precedence": precedence(source, destination),
                "create_index": existing.get("create_index", idx),
                "modify_index": idx,
            }
            return idx

    def intention_get(self, iid: str) -> Optional[dict]:
        with self._lock:
            v = self._intentions.get(iid)
            return dict(v, id=iid) if v else None

    def intention_list(self) -> List[dict]:
        with self._lock:
            rows = [dict(v, id=i) for i, v in self._intentions.items()]
        return sorted(rows, key=lambda r: (-r["precedence"],
                                           r["destination"], r["source"]))

    def intention_topology(self, name: str, downstreams: bool = False,
                           default_allow: bool = False) -> List[dict]:
        """Candidate services `name` may dial (upstreams) or that may
        dial `name` (downstreams), inferred from intentions + the ACL
        default (state/intention.go IntentionTopology:944,
        intentionTopologyTxn:965; backs the intention_upstreams cache
        type agent/cache-types/intention_upstreams.go).

        Every catalog service (non-proxy, non-gateway) is a candidate;
        the decision evaluates the intentions that match `name` on the
        source side (dest side for downstreams) against the candidate,
        like the reference's per-candidate IntentionDecision.  Returns
        [{name, allowed, has_exact}] for allowed candidates only.
        """
        from consul_tpu.connect import intentions as imod
        with self._lock:
            ints = [dict(v) for v in self._intentions.values()]
            # candidates are plain (non-proxy, non-gateway) services —
            # EXCEPT in the downstreams direction, where ingress
            # gateways may dial the service and must appear (the
            # reference's intentionTopologyTxn includes
            # ServiceKindIngressGateway iff downstreams,
            # state/intention.go:1009; ADVICE r5)
            candidates = sorted({
                v["name"] for v in self._services.values()
                if (not v.get("kind")
                    or (downstreams
                        and v.get("kind") == "ingress-gateway"))
                and v["name"] != name})
        match_by = "destination" if downstreams else "source"
        matched = [i for i in ints
                   if i[match_by] in (imod.WILDCARD, name)]
        out = []
        for cand in candidates:
            src, dst = (cand, name) if downstreams else (name, cand)
            allowed, _ = imod.authorize(matched, src, dst,
                                        default_allow)
            if not allowed:
                continue
            has_exact = any(i["source"] == src
                            and i["destination"] == dst
                            for i in matched)
            out.append({"name": cand, "allowed": True,
                        "has_exact": has_exact})
        return out

    def service_topology(self, name: str,
                         default_allow: bool = False,
                         kind: str = "") -> dict:
        """Upstream/downstream topology of a mesh service
        (state/catalog.go ServiceTopology:2870, served by
        Internal.ServiceTopology and /v1/internal/ui/service-topology).

        Upstreams come from the proxy registrations fronting `name`
        (source "registration"); when any of those proxies runs in
        transparent mode, intention-derived candidates join with
        source "specific-intention"/"default-allow".  Downstreams are
        the services whose proxies list `name` as an upstream, plus
        intention-derived ones for downstreams that run transparent
        proxies.  Each edge carries its intention decision (our
        intentions are L4 action-only, so HasPermissions is always
        False).
        """
        from consul_tpu.connect import intentions as imod
        from consul_tpu.discoverychain import service_protocol
        if kind == "ingress-gateway":
            # an ingress gateway's upstreams are the services its
            # config entry binds (catalog.go ServiceTopology
            # ServiceKindIngressGateway; gateway-services mapping);
            # external traffic means no mesh downstreams
            from consul_tpu import gateways as gmod
            with self._lock:
                ints = [dict(v) for v in self._intentions.values()]
            # per-kind bindings only: a same-named terminating gateway
            # must not leak its services into the ingress view
            bound = gmod.resolve_wildcard(
                self, [r for r in gmod.gateway_services(self, name)
                       if r.get("GatewayKind") == "ingress-gateway"])
            ups = sorted({r["Service"] for r in bound
                          if r.get("Service")})

            def gw_decision(dst: str) -> dict:
                allowed, _ = imod.authorize(ints, name, dst,
                                            default_allow)
                return {"Allowed": allowed, "HasPermissions": False,
                        "HasExact": any(i["source"] == name
                                        and i["destination"] == dst
                                        for i in ints),
                        "ExternalSource": ""}

            return {
                "protocol": service_protocol(self, name),
                "transparent_proxy": False,
                "upstreams": [{"name": n, "source": "routing-config",
                               "decision": gw_decision(n)}
                              for n in ups],
                "downstreams": [],
            }
        with self._lock:
            ints = [dict(v) for v in self._intentions.values()]
            proxies = [v for v in self._services.values()
                       if v.get("kind") == "connect-proxy"]
        ups: Dict[str, str] = {}
        downs: Dict[str, str] = {}
        tproxy_of: Dict[str, bool] = {}
        my_modes: List[str] = []
        for v in proxies:
            p = v.get("proxy") or {}
            dest = p.get("destination_service", "")
            mode = p.get("mode") or ""
            if mode == "transparent":
                tproxy_of[dest] = True
            if dest == name:
                my_modes.append(mode)
                for u in p.get("upstreams") or []:
                    un = u.get("destination_name", "")
                    if un and un != name:
                        ups[un] = "registration"
            else:
                for u in p.get("upstreams") or []:
                    if u.get("destination_name") == name and dest:
                        downs[dest] = "registration"
        has_tproxy = any(m == "transparent" for m in my_modes)
        fully_tproxy = bool(my_modes) and all(
            m == "transparent" for m in my_modes)
        # intention-inferred edges only apply where traffic is
        # captured implicitly (transparent mode) — the reference drops
        # non-registration upstreams when the target has no tproxy
        # instance (catalog.go:3002) and non-registration downstreams
        # whose OWN proxies aren't transparent (:3104)
        if has_tproxy:
            for e in self.intention_topology(name, False,
                                             default_allow):
                ups.setdefault(e["name"],
                               "specific-intention" if e["has_exact"]
                               else "default-allow")
        for e in self.intention_topology(name, True, default_allow):
            if tproxy_of.get(e["name"]):
                downs.setdefault(e["name"],
                                 "specific-intention"
                                 if e["has_exact"] else "default-allow")

        def decision(src: str, dst: str) -> dict:
            allowed, _ = imod.authorize(ints, src, dst, default_allow)
            return {"Allowed": allowed,
                    "HasPermissions": False,
                    "HasExact": any(i["source"] == src
                                    and i["destination"] == dst
                                    for i in ints),
                    "ExternalSource": ""}

        return {
            "protocol": service_protocol(self, name),
            "transparent_proxy": fully_tproxy,
            "upstreams": [
                {"name": n, "source": srcof,
                 "decision": decision(name, n)}
                for n, srcof in sorted(ups.items())],
            "downstreams": [
                {"name": n, "source": srcof,
                 "decision": decision(n, name)}
                for n, srcof in sorted(downs.items())],
        }

    def intention_delete(self, iid: str) -> int:
        with self._lock:
            v = self._intentions.pop(iid, None)
            if v is None:
                return self._index
            return self._bump([("intentions", v["destination"])])

    # ------------------------------------------------------------------- txn

    def txn(self, ops: List[dict]) -> Tuple[bool, List[Any], int]:
        """Atomic multi-op (Txn.Apply — agent/consul/txn_endpoint.go:142).

        Each op: {"verb": ..., ...args}.  All-or-nothing: state mutates only
        if every op succeeds.  Beyond the KV verbs, catalog
        (node-/service-/check-) and session verbs apply atomically too,
        matching the reference's full TxnOp union (structs Txn*Op;
        agent/consul/state/txn.go dispatch)."""
        import copy
        with self._lock:
            snapshot = (copy.deepcopy(self._kv),
                        copy.deepcopy(self._kv_delete_index),
                        copy.deepcopy(self._nodes),
                        copy.deepcopy(self._services),
                        copy.deepcopy(self._checks),
                        copy.deepcopy(self._sessions),
                        dict(self._lock_delays),
                        self._index)
            results: List[Any] = []
            ok = True
            self._txn_events = []
            try:
                ok = self._txn_ops_locked(ops, results)
            except Exception:
                self._txn_events = None
                (self._kv, self._kv_delete_index, self._nodes,
                 self._services, self._checks, self._sessions,
                 self._lock_delays, self._index) = snapshot
                raise
            deferred, self._txn_events = self._txn_events, None
            if not ok:
                (self._kv, self._kv_delete_index, self._nodes,
                 self._services, self._checks, self._sessions,
                 self._lock_delays, self._index) = snapshot
                return False, results, self._index
            for idx, events in deferred:
                self._apply_bump_effects(idx, events)
            return True, results, self._index

    # requires-lock: _lock
    def _txn_ops_locked(self, ops: List[dict],
                        results: List[Any]) -> bool:
        """Apply ops under the held lock, appending per-op results;
        False on the first failed op (caller rolls back)."""
        import copy
        for op in ops:
                verb = op["verb"]
                good = True
                if verb == "set":
                    good, _ = self.kv_set(op["key"], op["value"],
                                          op.get("flags", 0))
                elif verb == "cas":
                    good, _ = self.kv_set(op["key"], op["value"],
                                          op.get("flags", 0), cas=op["index"])
                elif verb == "delete":
                    good, _ = self.kv_delete(op["key"])
                elif verb == "delete-cas":
                    good, _ = self.kv_delete(op["key"], cas=op["index"])
                elif verb == "get":
                    # a get on a missing entry ABORTS the txn (the
                    # reference's TxnKVOp Get returns "key not found"
                    # and rolls back — state/txn.go KVSGet path)
                    res = self.kv_get(op["key"])
                    results.append(res)
                    if res is None:
                        return False
                    continue
                elif verb == "check-index":
                    e = self.kv_get(op["key"])
                    good = e is not None and e["modify_index"] == op["index"]
                elif verb == "lock":
                    good, _ = self.kv_set(op["key"], op["value"],
                                          acquire=op["session"])
                # --- catalog verbs (TxnNodeOp / TxnServiceOp / TxnCheckOp)
                elif verb == "node-get":
                    row = self._nodes.get(op["node"])
                    results.append(dict(row, node=op["node"])
                                   if row else None)
                    if row is None:
                        return False
                    continue
                elif verb in ("node-set", "node-cas"):
                    if verb == "node-cas":
                        row = self._nodes.get(op["node"])
                        if row is None or \
                                row["modify_index"] != op.get("index", 0):
                            good = False
                    if good:
                        # node_id fixed at the proposer (http txn) so
                        # raft replicas don't each mint a uuid
                        self.register_node(op["node"], op["address"],
                                           meta=op.get("meta"),
                                           node_id=op.get("node_id"))
                elif verb == "node-delete":
                    good = op["node"] in self._nodes
                    if good:
                        self.deregister_node(op["node"])
                elif verb == "service-get":
                    row = self._services.get((op["node"], op["service_id"]))
                    results.append(copy.deepcopy(row) if row else None)
                    if row is None:
                        return False
                    continue
                elif verb in ("service-set", "service-cas"):
                    if verb == "service-cas":
                        row = self._services.get(
                            (op["node"], op["service_id"]))
                        if row is None or \
                                row["modify_index"] != op.get("index", 0):
                            good = False
                    if good:
                        self.register_service(
                            op["node"], op["service_id"],
                            op.get("name", op["service_id"]),
                            port=op.get("port", 0),
                            tags=op.get("tags"), meta=op.get("meta"),
                            address=op.get("address", ""))
                elif verb == "service-delete":
                    good = (op["node"], op["service_id"]) in self._services
                    if good:
                        self.deregister_service(op["node"],
                                                op["service_id"])
                elif verb == "check-get":
                    row = self._checks.get((op["node"], op["check_id"]))
                    results.append(copy.deepcopy(row) if row else None)
                    if row is None:
                        return False
                    continue
                elif verb in ("check-set", "check-cas"):
                    if verb == "check-cas":
                        row = self._checks.get((op["node"], op["check_id"]))
                        if row is None or \
                                row["modify_index"] != op.get("index", 0):
                            good = False
                    if good:
                        self.register_check(
                            op["node"], op["check_id"],
                            op.get("name", op["check_id"]),
                            status=op.get("status", "critical"),
                            service_id=op.get("service_id", ""),
                            output=op.get("output", ""))
                elif verb == "check-delete":
                    good = (op["node"], op["check_id"]) in self._checks
                    if good:
                        self.deregister_check(op["node"], op["check_id"])
                # --- session verbs
                elif verb == "session-create":
                    # sid + clock fixed at the proposer: every raft
                    # replica must apply the identical session (the
                    # fsm.py proposer-fixed-ids discipline)
                    sid, _ = self.session_create(
                        op["node"], ttl=op.get("ttl", 0.0),
                        behavior=op.get("behavior", "release"),
                        sid=op.get("sid"), now=op.get("now"))
                    results.append(sid)
                    continue
                elif verb == "session-destroy":
                    good = op["session"] in self._sessions
                    if good:
                        self.session_destroy(op["session"])
                else:
                    raise ValueError(f"unknown txn verb {verb}")
                results.append(good)
                if not good:
                    return False
        return True

    # -------------------------------------------------------- snapshot/restore

    def snapshot(self) -> dict:
        """Serializable full-state image (FSM Snapshot —
        agent/consul/fsm/fsm.go:145; user archive snapshot/snapshot.go:164)."""
        import base64
        import copy
        with self._lock:
            # deep copies: the raft layer retains the snapshot across later
            # in-place mutations (renew etc.) and ships it to followers —
            # aliasing live dicts would both smear the point-in-time image
            # and let replicas share mutable state outside the log
            return {
                "index": self._index,
                "kv": {k: dict(v, value=base64.b64encode(v["value"]).decode())
                       for k, v in self._kv.items()},
                "kv_delete_index": dict(self._kv_delete_index),
                "nodes": copy.deepcopy(self._nodes),
                "services": {f"{n}\x00{s}": copy.deepcopy(v)
                             for (n, s), v in self._services.items()},
                "checks": {f"{n}\x00{c}": copy.deepcopy(v)
                           for (n, c), v in self._checks.items()},
                "sessions": copy.deepcopy(self._sessions),
                "acl_policies": copy.deepcopy(self._acl_policies),
                "acl_tokens": copy.deepcopy(self._acl_tokens),
                "acl_bootstrap_index": self._acl_bootstrap_index,
                "queries": copy.deepcopy(self._queries),
                "intentions": copy.deepcopy(self._intentions),
                "config_entries": {f"{k}\x00{n}": copy.deepcopy(v)
                                   for (k, n), v in
                                   self._config_entries.items()},
                "auth_methods": copy.deepcopy(self._auth_methods),
                "binding_rules": copy.deepcopy(self._binding_rules),
                "federation_states": copy.deepcopy(
                    self._federation_states),
                "coordinates": copy.deepcopy(self._coordinates),
            }

    def load_snapshot(self, snap: dict) -> None:
        """In-place restore — raft InstallSnapshot hits a live store whose
        identity is shared with the FSM and API (the reference swaps the
        whole store and abandons the old one, state_store.go:106; here the
        watchers are woken by the index bump instead)."""
        import base64
        import copy
        with self._lock:
            self._index = snap["index"]
            self._kv = {k: dict(v, value=base64.b64decode(v["value"]))
                        for k, v in snap["kv"].items()}
            self._kv_delete_index = dict(snap.get("kv_delete_index", {}))
            self._nodes = copy.deepcopy(snap["nodes"])
            self._services = {tuple(k.split("\x00")): copy.deepcopy(v)
                              for k, v in snap["services"].items()}
            self._checks = {tuple(k.split("\x00")): copy.deepcopy(v)
                            for k, v in snap["checks"].items()}
            self._sessions = copy.deepcopy(snap["sessions"])
            self._acl_policies = copy.deepcopy(snap.get("acl_policies", {}))
            self._acl_tokens = copy.deepcopy(snap.get("acl_tokens", {}))
            self._acl_bootstrap_index = snap.get("acl_bootstrap_index", 0)
            self._queries = copy.deepcopy(snap.get("queries", {}))
            self._intentions = copy.deepcopy(snap.get("intentions", {}))
            self._config_entries = {
                tuple(k.split("\x00")): copy.deepcopy(v)
                for k, v in snap.get("config_entries", {}).items()}
            self._auth_methods = copy.deepcopy(
                snap.get("auth_methods", {}))
            self._binding_rules = copy.deepcopy(
                snap.get("binding_rules", {}))
            self._federation_states = copy.deepcopy(
                snap.get("federation_states", {}))
            self._coordinates = copy.deepcopy(
                snap.get("coordinates", {}))
            # watch bookkeeping must rewind with the index, or restored-
            # to-older stores report watch indexes beyond _index and
            # blocking queries busy-loop returning immediately
            self._topic_index = {}
            self._topic_max = {}
            self._topic_floor = {}
            # restore abandons the old state: EVERY parked query wakes and
            # re-reads (state_store.go:106-112 AbandonCh parity)
            self._cond.notify_all()
            for w in self._waiters:
                w.fired = True
                w.cond.notify_all()

    @classmethod
    def restore(cls, snap: dict) -> "StateStore":
        st = cls()
        st.load_snapshot(snap)
        return st
