from consul_tpu.catalog.store import StateStore

__all__ = ["StateStore"]
