from consul_tpu.local.state import LocalState

__all__ = ["LocalState"]
