"""Agent-local state: the desired-state registry AE syncs to the catalog.

The reference's agent/local/state.go:158 keeps the node's services and
checks with per-entry InSync/Deferred flags; updateSyncState (:880) diffs
them against the server catalog, SyncFull (:1053) resets and pushes
everything, SyncChanges (:1071) pushes only out-of-sync entries.  Same
model here against a duck-typed catalog surface (StateStore or a
raft-replicated Server — both expose register_/deregister_/node_services/
node_checks).

The per-entry map walk the reference does is the host-side small-N path;
the 1M-entry batched equivalent is ops/reconcile.diff_sorted consumed by
models/antientropy (SURVEY.md §7 step 4).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple


class LocalState:
    def __init__(self, node_name: str, address: str = "127.0.0.1",
                 on_change: Optional[Callable[[], None]] = None):
        self.node_name = node_name
        self.address = address
        self._lock = threading.RLock()
        self._services: Dict[str, dict] = {}        # sid -> defn + in_sync
        self._checks: Dict[str, dict] = {}          # cid -> defn + in_sync
        self._on_change = on_change or (lambda: None)

    # ------------------------------------------------------------- mutation

    def add_service(self, service_id: str, name: str, port: int = 0,
                    tags: List[str] | None = None, meta: dict | None = None,
                    address: str = "") -> None:
        with self._lock:
            self._services[service_id] = {
                "name": name, "port": port, "tags": tags or [],
                "meta": meta or {}, "address": address, "in_sync": False}
        self._on_change()

    def remove_service(self, service_id: str) -> None:
        with self._lock:
            if service_id in self._services:
                self._services[service_id]["deleted"] = True
                self._services[service_id]["in_sync"] = False
            for cid, c in self._checks.items():
                if c["service_id"] == service_id:
                    c["deleted"] = True
                    c["in_sync"] = False
        self._on_change()

    def add_check(self, check_id: str, name: str, status: str = "critical",
                  service_id: str = "", output: str = "") -> None:
        with self._lock:
            self._checks[check_id] = {
                "name": name, "status": status, "service_id": service_id,
                "output": output, "in_sync": False}
        self._on_change()

    def remove_check(self, check_id: str) -> None:
        with self._lock:
            if check_id in self._checks:
                self._checks[check_id]["deleted"] = True
                self._checks[check_id]["in_sync"] = False
        self._on_change()

    def update_check(self, check_id: str, status: str,
                     output: str = "") -> bool:
        """Check runner callback (the reference defers frequent output-only
        updates via CheckUpdateInterval; status flips always sync)."""
        with self._lock:
            c = self._checks.get(check_id)
            if c is None or c.get("deleted"):
                return False
            if c["status"] == status and c["output"] == output:
                return True
            c["status"] = status
            c["output"] = output
            c["in_sync"] = False
        self._on_change()
        return True

    # ---------------------------------------------------------------- reads

    def services(self) -> Dict[str, dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._services.items()
                    if not v.get("deleted")}

    def checks(self) -> Dict[str, dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._checks.items()
                    if not v.get("deleted")}

    def check_status(self, check_id: str) -> Optional[str]:
        with self._lock:
            c = self._checks.get(check_id)
            return None if c is None or c.get("deleted") else c["status"]

    # ----------------------------------------------------------------- sync

    def update_sync_state(self, catalog) -> Tuple[int, int]:
        """Diff local vs catalog and mark out-of-sync entries
        (updateSyncState, state.go:880).  Returns (dirty_services,
        dirty_checks) counts."""
        remote_svcs = {s["id"]: s
                       for s in catalog.node_services(self.node_name)}
        remote_chks = {c["check_id"]: c
                       for c in catalog.node_checks(self.node_name)}
        dirty_s = dirty_c = 0
        with self._lock:
            for sid, svc in self._services.items():
                if svc.get("deleted"):
                    svc["in_sync"] = sid not in remote_svcs
                    continue
                r = remote_svcs.get(sid)
                same = r is not None and (
                    r["name"] == svc["name"] and r["port"] == svc["port"]
                    and r["tags"] == svc["tags"]
                    and r["meta"] == svc["meta"]
                    and r["address"] == svc["address"])
                svc["in_sync"] = same
                if not same:
                    dirty_s += 1
            for cid, chk in self._checks.items():
                if chk.get("deleted"):
                    chk["in_sync"] = cid not in remote_chks
                    continue
                r = remote_chks.get(cid)
                same = r is not None and (
                    r["status"] == chk["status"]
                    and r["output"] == chk["output"]
                    and r["service_id"] == chk["service_id"])
                chk["in_sync"] = same
                if not same:
                    dirty_c += 1
        return dirty_s, dirty_c

    def sync_changes(self, catalog) -> int:
        """Push only out-of-sync entries (SyncChanges, state.go:1071).
        Returns number of operations pushed."""
        ops = 0
        with self._lock:
            services = list(self._services.items())
            checks = list(self._checks.items())
        for sid, svc in services:
            if svc["in_sync"]:
                continue
            if svc.get("deleted"):
                catalog.deregister_service(self.node_name, sid)
                with self._lock:
                    self._services.pop(sid, None)
            else:
                catalog.register_service(
                    self.node_name, sid, svc["name"], port=svc["port"],
                    tags=svc["tags"], meta=svc["meta"],
                    address=svc["address"])
                with self._lock:
                    svc["in_sync"] = True
            ops += 1
        for cid, chk in checks:
            if chk["in_sync"]:
                continue
            if chk.get("deleted"):
                catalog.deregister_check(self.node_name, cid)
                with self._lock:
                    self._checks.pop(cid, None)
            else:
                catalog.register_check(
                    self.node_name, cid, chk["name"], status=chk["status"],
                    service_id=chk["service_id"], output=chk["output"])
                with self._lock:
                    chk["in_sync"] = True
            ops += 1
        return ops

    def sync_full(self, catalog) -> int:
        """Full anti-entropy pass: re-diff then push (SyncFull,
        state.go:1053)."""
        self.update_sync_state(catalog)
        return self.sync_changes(catalog)
