"""Read plane: consistency-mode resolution for every read route.

The reference serves most production read traffic from FOLLOWERS: a
`?stale` query may be answered by any server from its local replica
(agent/consul/rpc.go:~880 canServeReadRequest), `?consistent` adds a
leader barrier, and the default mode is leader-verified — a non-leader
server forwards the RPC to the leader (rpc.go:549 ForwardRPC).  Every
read response carries `X-Consul-KnownLeader` and `X-Consul-LastContact`
so the CALLER can judge the staleness it was served
(agent/http.go setMeta; website/content/api-docs/features/consistency).

This module is that policy, factored into one object the HTTP layer
(api/http.py `_dispatch`, api/fastfront.py hot path) consults per
request:

  mode        resolved from the query string: `default` / `?stale` /
              `?consistent` (`?max_stale=<dur>` implies stale, the
              reference's MaxStaleDuration semantics); requesting
              stale AND consistent together is a 400.

  stale       served LOCALLY from this node's replicated store —
              never a leader RPC (the readplane-discipline lint rule
              enforces the never statically).  `?max_stale` bounds it:
              the node's own staleness estimate
              (raft.staleness(): last-leader-contact age ∨ oldest
              received-but-unapplied entry age, the follower-side
              sibling of the PR 10 `_append_ts` lag machinery) must
              not exceed the caller's bound, else the read is REJECTED
              with 503 + `X-Consul-Reason: max-stale`
              (`consul.readplane.rejected{reason="max_stale"}` + a
              `readplane.rejected` flight event).  The reference
              re-forwards to the leader instead; rejecting keeps the
              contract visible and lets a client-side LB retry a
              fresher replica.

  consistent  the existing leader barrier (api/http.py `_consistent`);
              500s leaderless.

  default     leader-verified.  On a follower whose fleet HTTP map is
              configured (`ApiServer.cluster_nodes` — the same fixed,
              never-caller-supplied set the federation endpoint uses),
              the request is FORWARDED to the leader's HTTP surface;
              leaderless, it 500s like the reference's
              structs.ErrNoLeader.  Without the fleet map (standalone
              agents, in-process rigs) the node serves locally — the
              pre-readplane behavior, kept so a lone agent stays
              useful.

Metrics: `consul.readplane.{stale,consistent,default}{route}` count
mode resolution per route family, `consul.readplane.forward{route}`
counts default-mode leader forwards (the counter the "stale reads do
NO leader RPC" acceptance asserts against), and
`consul.readplane.rejected{reason}` counts refusals.  Route-family
labels are a bounded vocabulary (the /v1 surface's first segment).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional

from consul_tpu import telemetry

# routes whose reads are REPLICATED state and honor the consistency
# modes (the reference's blockingQuery surface); /v1/agent, /v1/status,
# /v1/operator and friends are node-local by design and never forward
LEADER_READ_PREFIXES = (
    "/v1/kv/", "/v1/catalog/", "/v1/health/", "/v1/session/",
    "/v1/coordinate/", "/v1/query",
)

# bounded route-family vocabulary for the {route} label
_FAMILIES = ("kv", "catalog", "health", "session", "coordinate",
             "query", "txn", "agent", "status", "acl", "event",
             "config", "connect", "internal", "operator", "snapshot")

_HDR_FORWARDED = "X-Consul-Read-Forwarded"


def route_family(path: str) -> str:
    """`/v1/<family>/...` → bounded label value ("other" off-surface)."""
    parts = path.split("/", 3)
    fam = parts[2] if len(parts) > 2 and parts[1] == "v1" else ""
    return fam if fam in _FAMILIES else "other"


def parse_max_stale(val: str) -> float:
    from consul_tpu.utils.duration import parse_duration
    return parse_duration(val, 10.0)


class ReadDecision:
    """resolve()'s verdict for one read request."""

    __slots__ = ("mode", "route", "action", "code", "message", "reason")

    def __init__(self, mode: str, route: str, action: str = "local",
                 code: int = 0, message: str = "",
                 reason: str = ""):
        self.mode = mode            # default | stale | consistent
        self.route = route          # bounded family label
        self.action = action        # local | forward | reject
        self.code = code            # HTTP status when action == reject
        self.message = message
        self.reason = reason        # rejected{reason} label value

    @property
    def is_stale(self) -> bool:
        return self.mode == "stale"


class ReadPlane:
    """Per-ApiServer consistency policy over a duck-typed store.

    `store` may be a raft-backed Server (read_staleness / known_leader /
    leader_id / is_leader) or a bare StateStore (trivially leader-like:
    0-stale, leader always "known").  `cluster_nodes_fn` returns the
    fleet's {node name: http url} map (ApiServer.cluster_nodes) or
    None — the leader-forward route table."""

    def __init__(self, store, node_name: str = "",
                 cluster_nodes_fn: Optional[Callable[[], Optional[Dict[str, str]]]] = None):
        self.store = store
        self.node_name = node_name
        self._cluster_nodes = cluster_nodes_fn or (lambda: None)

    # ------------------------------------------------------------- state

    @property
    def raft_backed(self) -> bool:
        return getattr(self.store, "raft", None) is not None

    def is_leader(self) -> bool:
        if not self.raft_backed:
            return True
        return self.store.is_leader()

    def known_leader(self) -> bool:
        if not self.raft_backed:
            return True
        return bool(self.store.known_leader())

    def staleness_s(self) -> float:
        """This node's current staleness bound in seconds (0 when it
        is the leader or a bare store)."""
        if not self.raft_backed:
            return 0.0
        return float(self.store.read_staleness())

    def last_contact_ms(self) -> float:
        if not self.raft_backed:
            return 0.0
        return float(self.store.last_contact_ms())

    def leader_http(self) -> Optional[str]:
        """The leader's HTTP address from the fleet map, or None."""
        nodes = self._cluster_nodes()
        if not nodes or not self.raft_backed:
            return None
        lid = self.store.leader_id
        if lid is None or lid == self.node_name:
            return None
        return nodes.get(lid)

    # ----------------------------------------------------------- headers

    def headers(self) -> Dict[str, str]:
        """The consistency metadata stamped on every read response
        (agent/http.go setMeta): whether a leader is known, and how
        long ago this node last heard from it."""
        lc = self.last_contact_ms()
        return {
            "X-Consul-KnownLeader":
                "true" if self.known_leader() else "false",
            "X-Consul-LastContact":
                "0" if lc == float("inf") else str(int(lc)),
        }

    # ----------------------------------------------------------- resolve

    def resolve(self, path: str, q, headers=None) -> ReadDecision:
        """Resolve the consistency mode for one GET and decide where it
        is served.  Counts the mode, counts/journals rejections, and
        never touches the leader itself — forwarding is the CALLER's
        move (api/http.py `_forward_leader`)."""
        route = route_family(path)
        if not path.startswith(LEADER_READ_PREFIXES):
            # node-local surface: modes are inert, headers still stamp
            return ReadDecision("default", route)
        stale = "stale" in q or "max_stale" in q
        consistent = "consistent" in q
        if stale and consistent:
            return self._reject(
                ReadDecision("default", route), 400, "conflicting",
                "?stale and ?consistent are mutually exclusive")
        if stale:
            dec = ReadDecision("stale", route)
            self._count(dec)
            max_stale = q.get("max_stale")
            if max_stale is not None:
                bound = parse_max_stale(max_stale)
                lag = self.staleness_s()
                if lag > bound:
                    # 503 (unavailable: THIS replica cannot honor the
                    # bound right now — retry a fresher one), not a
                    # 500: the condition is operational, not a bug,
                    # and clients discriminate on X-Consul-Reason
                    return self._reject(
                        dec, 503, "max_stale",
                        f"stale read refused: replica lag "
                        f"{'inf' if lag == float('inf') else round(lag, 3)}s"
                        f" exceeds max_stale {bound:g}s")
            return dec
        if consistent:
            dec = ReadDecision("consistent", route)
            self._count(dec)
            # leaderless consistent reads fail in the barrier itself
            # (api/http.py _consistent → 500); nothing to decide here
            return dec
        dec = ReadDecision("default", route)
        self._count(dec)
        if not self.raft_backed or self.is_leader():
            return dec
        forwarded = bool(headers and headers.get(_HDR_FORWARDED))
        if forwarded:
            # loop guard: the forwarder believed we were leader and we
            # are not — bounce rather than chase a moving leader hint
            return self._reject(
                dec, 503, "not_leader",
                "not the leader (stale read-forward hint); retry")
        nodes = self._cluster_nodes()
        if not nodes:
            # no fleet route table (standalone/in-process): serve the
            # local replica like the pre-readplane tree did — the
            # headers still tell the caller how stale it may be
            return dec
        target = self.leader_http()
        if target is None:
            if not self.known_leader():
                return self._reject(
                    dec, 503, "no_leader", "No cluster leader")
            # leader known but not in the fleet map: local, degraded
            return dec
        dec.action = "forward"
        telemetry.incr_counter(("readplane", "forward"),
                               labels={"route": route})
        return dec

    # ----------------------------------------------------------- helpers

    def _count(self, dec: ReadDecision) -> None:
        telemetry.incr_counter(("readplane", dec.mode),
                               labels={"route": dec.route})

    def _reject(self, dec: ReadDecision, code: int, reason: str,
                message: str) -> ReadDecision:
        dec.action = "reject"
        dec.code = code
        dec.reason = reason
        dec.message = message
        telemetry.incr_counter(("readplane", "rejected"),
                               labels={"reason": reason})
        from consul_tpu import flight
        flight.emit("readplane.rejected",
                    labels={"reason": reason, "route": dec.route,
                            "node": self.node_name})
        return dec

    # fastfront's cheap gate: may a plain (no-param) KV GET be served
    # inline, or must it fall back to the legacy handler for mode
    # resolution (leader forward / no-leader reject)?
    def hot_default_ok(self) -> bool:
        if not self.raft_backed:
            return True
        if self.store.is_leader():
            return True
        return not self._cluster_nodes()

    # fastfront's stale gate: serve ?stale inline unless a max_stale
    # bound needs the full reject path
    def hot_stale_ok(self, q) -> bool:
        if "max_stale" not in q:
            return True
        try:
            return self.staleness_s() <= parse_max_stale(q["max_stale"])
        except (TypeError, ValueError):
            return False


def now_ms() -> float:
    return time.time() * 1000.0
