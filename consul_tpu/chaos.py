"""Nemesis: a deterministic fault-injection engine with cross-layer
safety invariant checkers.

SWIM (Das et al., 2002) and Lifeguard (Dadgar et al., 2018) state their
guarantees *under* message loss and local degradation, and Consul's own
partition tests shut sockets down mid-write — yet until this module the
repo's only fault surfaces were a scalar `p_loss`, ad-hoc
`partition()`/`isolate()` hooks, and one blind TCP reconnect.  The
nemesis drives seeded, scenario-shaped fault timelines through THREE
layers with one API and checks the safety properties that must survive
them:

  layer 1  in-memory raft transport (consensus/raft.py InMemTransport):
           partitions/heals via the generalized cut hooks, plus a
           message-level `LinkInjector` (loss, delay, duplication,
           reorder — delayed frames flush on `transport.advance(now)`,
           so the whole cluster stays tick-synchronous and
           bit-reproducible from the seed);
  layer 2  live framed-TCP path (rpc/net.py FaultyTcpTransport +
           NetFaultSchedule): severs/delays pooled connections on a
           seeded decision stream;
  layer 3  the jitted SWIM tick (models/swim.py): per-node partition
           groups (`chaos_grp`) and delivery-rate multipliers
           (`chaos_ok`) are STATE fields the host mutates between
           device scans — faults evolve on a host-side schedule with
           zero recompiles.

Invariant checkers:

  election safety      at most one raft leader per term, ever
                       (Raft §5.2; ElectionSafetyChecker)
  committed durability acked writes survive crash-restart-from-
                       durable-log and replicas never fork (Raft §5.4;
                       DurabilityChecker pairwise prefix + final
                       presence/order check)
  linearizability      recorded client histories over one KV register
                       admit a legal linearization (Wing & Gong search
                       with ambiguous-outcome writes; check_linearizable)
  SWIM bounds          no committed death of a reachable live node; the
                       pool re-converges within a tick budget after
                       heal (SwimChaosHarness)

`tools/chaos_soak.py` replays scenario suites built on these pieces,
prints the reproducing seed on any violation, and emits CHAOS_r01.json;
its `--check` mode is the fixed-seed tier-1 smoke.
"""

from __future__ import annotations

import hashlib
import json
import math
import os
import random
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from consul_tpu.consensus.raft import (
    LEADER, InMemTransport, NotLeaderError, RaftConfig, RaftNode,
)

# ---------------------------------------------------------------------------
# layer 1: schedule-driven message injector for InMemTransport
# ---------------------------------------------------------------------------


@dataclass
class LinkRule:
    """Per-link fault mix.  All probabilities are per-message; delays
    are seconds of virtual time (flushed by transport.advance)."""

    drop_p: float = 0.0
    delay_p: float = 0.0
    delay: Tuple[float, float] = (0.01, 0.05)
    dup_p: float = 0.0


class LinkInjector:
    """Deterministic per-message fault decisions for InMemTransport.

    `on_send` returns a list of delivery delays for the frame: empty =
    dropped; 0.0 = deliver now; positive = queue until advance(now)
    passes it (variable delays ARE reordering — a later frame with a
    shorter draw overtakes); an extra positive entry = duplicate.  At
    most one non-positive entry is ever returned (the transport
    delivers at most one immediate copy).  One seeded RNG consumed in
    tick-synchronous call order keeps the whole stream reproducible."""

    def __init__(self, seed: int = 0):
        self._rng = random.Random(seed)
        self.default: Optional[LinkRule] = None
        self.links: Dict[tuple, LinkRule] = {}   # (src|None, dst|None)

    def set_default(self, **kw) -> None:
        self.default = LinkRule(**kw) if kw else None

    def set_link(self, src: Optional[str], dst: Optional[str],
                 **kw) -> None:
        """Rule for a directed link; None is a wildcard endpoint —
        asymmetric faults are (src, None) rules."""
        self.links[(src, dst)] = LinkRule(**kw)

    def clear(self) -> None:
        self.default = None
        self.links.clear()

    def _rule(self, src: str, dst: str) -> Optional[LinkRule]:
        return (self.links.get((src, dst))
                or self.links.get((src, None))
                or self.links.get((None, dst))
                or self.default)

    def on_send(self, src: str, dst: str, msg: dict,
                now: float) -> Optional[List[float]]:
        rule = self._rule(src, dst)
        if rule is None:
            return None                      # transport default path
        rng = self._rng
        if rule.drop_p and rng.random() < rule.drop_p:
            return []
        lo, hi = rule.delay
        plan = [lo + rng.random() * (hi - lo)
                if rule.delay_p and rng.random() < rule.delay_p else 0.0]
        if rule.dup_p and rng.random() < rule.dup_p:
            plan.append(lo + rng.random() * (hi - lo))
        return plan


# ---------------------------------------------------------------------------
# invariant checkers
# ---------------------------------------------------------------------------


class ElectionSafetyChecker:
    """Raft §5.2: at most one leader may ever exist in a given term.
    Observe the cluster every step; a term with two distinct leader
    ids — even at different wall moments — is a safety violation."""

    def __init__(self):
        self.leaders_by_term: Dict[int, set] = {}
        self.violations: List[str] = []

    def observe(self, nodes) -> None:
        for n in nodes:
            if n.state == LEADER:
                self.note(n.current_term, n.node_id)

    def note(self, term: int, node_id: str) -> None:
        seen = self.leaders_by_term.setdefault(term, set())
        if node_id not in seen:
            seen.add(node_id)
            if len(seen) > 1:
                self.violations.append(
                    f"election safety: term {term} has leaders "
                    f"{sorted(seen)}")


class DurabilityChecker:
    """Raft §5.4 / state-machine safety: replicas' applied sequences
    never fork (pairwise prefix consistency at every step), and every
    ACKED write is present — exactly once, in ack order — on every
    live replica after the cluster settles (committed entries survive
    crash-restart)."""

    def __init__(self):
        self.acked: List[Any] = []
        self.violations: List[str] = []
        self._forked = False

    def note_acked(self, val: Any) -> None:
        self.acked.append(val)

    def observe(self, logs: Dict[str, list]) -> None:
        if self._forked:
            return       # a fork is terminal: report it once, not per step
        items = sorted(logs.items())
        for i in range(len(items)):
            for j in range(i + 1, len(items)):
                a_id, a = items[i]
                b_id, b = items[j]
                k = min(len(a), len(b))
                if a[:k] != b[:k]:
                    d = next(x for x in range(k) if a[x] != b[x])
                    self._forked = True
                    self.violations.append(
                        f"fork: {a_id}[{d}]={a[d]!r} vs "
                        f"{b_id}[{d}]={b[d]!r}")
                    return

    def final_check(self, logs: Dict[str, list],
                    live: List[str]) -> List[str]:
        out = []
        for nid in live:
            log = logs[nid]
            pos = -1
            for val in self.acked:
                hits = log.count(val)
                if hits == 0:
                    out.append(f"durability: acked write {val!r} "
                               f"missing from {nid}")
                    continue
                if hits > 1:
                    # a re-applied resent entry (double-apply) is as
                    # much a state-machine-safety bug as a lost one
                    out.append(f"durability: acked write {val!r} "
                               f"applied {hits}x on {nid}")
                p = log.index(val)
                if p <= pos:
                    out.append(f"durability: acked write {val!r} "
                               f"out of order on {nid}")
                pos = max(pos, p)
        return out


class RegisterHistory:
    """Client-side invoke/complete record over one KV register, fed to
    check_linearizable.  Writes carry unique values; a write whose
    outcome the client never learned (timeout, leader deposed mid-
    flight) is AMBIGUOUS — it may have applied at any point after its
    invocation, or never."""

    def __init__(self):
        self.ops: List[dict] = []

    def invoke(self, kind: str, val: Any, now: float) -> int:
        self.ops.append({"kind": kind, "val": val, "call": now,
                         "ret": None, "ok": True, "discard": False})
        return len(self.ops) - 1

    def complete(self, op_id: int, now: float, val: Any = None) -> None:
        op = self.ops[op_id]
        op["ret"] = now
        if val is not None or op["kind"] == "r":
            op["val"] = val

    def ambiguous(self, op_id: int, now: Optional[float] = None) -> None:
        op = self.ops[op_id]
        op["ok"] = None
        op["ret"] = now          # None = never returned to the client

    def discard(self, op_id: int) -> None:
        self.ops[op_id]["discard"] = True

    def recorded(self) -> List[dict]:
        return [o for o in self.ops if not o["discard"]]


def check_linearizable(ops: List[dict],
                       init: Any = None) -> Tuple[bool, Optional[str]]:
    """Wing & Gong linearizability search for a single register.

    ops: dicts with kind ('w'/'r'), val, call, ret (None = pending
    forever), ok (None = ambiguous write: may apply anywhere after its
    call, or never).  Memoized on (remaining-ops, register value); the
    harness keeps histories small and concurrency bounded, so the
    search stays well under the exponential worst case."""
    INF = float("inf")
    ops = [dict(o) for o in ops if not o.get("discard")]
    for o in ops:
        if o["ret"] is None:
            o["ret"] = INF
    n = len(ops)
    seen = set()

    def search(remaining: frozenset, state) -> bool:
        if not remaining:
            return True
        key = (remaining, state)
        if key in seen:
            return False
        seen.add(key)
        min_ret = min(ops[i]["ret"] for i in remaining)
        for i in sorted(remaining):
            o = ops[i]
            if o["call"] > min_ret:
                continue         # someone finished before i was called
            rest = remaining - {i}
            if o["kind"] == "w":
                if search(rest, o["val"]):
                    return True
                if o["ok"] is None and search(rest, state):
                    return True  # ambiguous write: never took effect
            else:
                if o["val"] == state and search(rest, state):
                    return True
        return False

    if search(frozenset(range(n)), init):
        return True, None
    # smallest offending read for the report
    reads = [o for o in ops if o["kind"] == "r"]
    return False, (f"no linearization of {n} ops "
                   f"({len(reads)} reads); history="
                   + json.dumps([[o['kind'], o['val'], o['call'],
                                  (None if o['ret'] == INF else o['ret'])]
                                 for o in ops], default=str)[:2000])


# ---------------------------------------------------------------------------
# raft chaos harness (virtual time, bit-reproducible)
# ---------------------------------------------------------------------------


class RaftChaosHarness:
    """An in-process raft cluster stepped on virtual time under the
    nemesis, with the checkers wired to every step.

    The FSM is an append-log + register: each committed write appends
    its value to the node's `logs` entry and becomes the register
    value; snapshots carry the full log so crash-restart replays into
    the same sequence.  Reads are leader barriers (VerifyLeader): the
    value observed after the barrier commits is linearizable iff raft
    is — which is exactly what the checker verifies."""

    def __init__(self, n: int = 3, seed: int = 0,
                 data_root: Optional[str] = None,
                 config: Optional[RaftConfig] = None):
        self.seed = seed
        self.transport = InMemTransport(seed=seed)
        self.injector = LinkInjector(seed ^ 0x9E3779B9)
        self.transport.injector = self.injector
        self.cfg = config or RaftConfig()
        self.data_root = data_root
        self.durable = data_root is not None
        self.ids = [f"n{i}" for i in range(n)]
        self.logs: Dict[str, list] = {nid: [] for nid in self.ids}
        self.value: Dict[str, Any] = {nid: None for nid in self.ids}
        self.alive: Dict[str, bool] = {nid: True for nid in self.ids}
        self.skew: Dict[str, float] = {nid: 0.0 for nid in self.ids}
        self.nodes: Dict[str, RaftNode] = {}
        for nid in self.ids:
            self.nodes[nid] = self._mk_node(nid)
        self.now = 0.0
        self.election = ElectionSafetyChecker()
        self.durability = DurabilityChecker()
        self.history = RegisterHistory()
        self._inflight: List[dict] = []
        self._next_val = 0

    # ------------------------------------------------------------ lifecycle

    def _mk_node(self, nid: str) -> RaftNode:
        store = None
        if self.durable:
            from consul_tpu.consensus.logstore import DurableLog
            store = DurableLog(os.path.join(self.data_root, nid))

        def apply_fn(cmd, nid=nid):
            v = cmd["v"]
            self.logs[nid].append(v)
            self.value[nid] = v
            return v

        def snapshot_fn(nid=nid):
            return {"log": list(self.logs[nid])}

        def restore_fn(data, nid=nid):
            self.logs[nid][:] = data["log"]
            self.value[nid] = self.logs[nid][-1] if self.logs[nid] else None

        node = RaftNode(nid, list(self.ids), self.transport, apply_fn,
                        snapshot_fn, restore_fn, config=self.cfg,
                        seed=self.seed, store=store)
        self.transport.register(node)
        return node

    def crash(self, nid: str) -> None:
        """kill -9: the node object drops, queued frames drop with it;
        only its DurableLog (when data_root is set) survives."""
        node = self.nodes[nid]
        if node.store is not None:
            node.store.close()
        self.transport.unregister(nid)
        self.alive[nid] = False

    def restart(self, nid: str) -> None:
        """Boot from the durable log (crash recovery path)."""
        if not self.durable:
            raise RuntimeError("restart without a durable log would "
                               "forge raft persistent state")
        self.logs[nid].clear()
        self.value[nid] = None
        self.nodes[nid] = self._mk_node(nid)
        self.alive[nid] = True

    # ------------------------------------------------------------- stepping

    def step(self, seconds: float, dt: float = 0.01) -> None:
        end = self.now + seconds
        while self.now < end - 1e-9:
            self.now += dt
            self.transport.advance(self.now)
            for nid in self.ids:
                if self.alive[nid]:
                    self.nodes[nid].tick(self.now + self.skew[nid])
            self._reap()
            self.election.observe(
                n for nid, n in self.nodes.items() if self.alive[nid])
            self.durability.observe(
                {nid: log for nid, log in self.logs.items()
                 if self.alive[nid]})

    def _leader(self) -> Optional[RaftNode]:
        leaders = [n for nid, n in self.nodes.items()
                   if self.alive[nid] and n.is_leader()]
        if not leaders:
            return None
        return max(leaders, key=lambda n: n.current_term)

    # ------------------------------------------------------------- clients

    MAX_INFLIGHT = 4

    def do_write(self, deadline_s: float = 1.0) -> None:
        if len(self._inflight) >= self.MAX_INFLIGHT:
            return
        leader = self._leader()
        if leader is None:
            return
        val = self._next_val
        self._next_val += 1
        hid = self.history.invoke("w", val, self.now)
        try:
            pend = leader.apply({"v": val})
        except NotLeaderError:
            self.history.discard(hid)     # definite no-op
            return
        self._inflight.append({"hid": hid, "pend": pend, "kind": "w",
                               "val": val, "node": leader.node_id,
                               "deadline": self.now + deadline_s})

    def do_read(self, deadline_s: float = 1.0) -> None:
        if len(self._inflight) >= self.MAX_INFLIGHT:
            return
        leader = self._leader()
        if leader is None:
            return
        hid = self.history.invoke("r", None, self.now)
        try:
            pend = leader.barrier()
        except NotLeaderError:
            self.history.discard(hid)
            return
        self._inflight.append({"hid": hid, "pend": pend, "kind": "r",
                               "val": None, "node": leader.node_id,
                               "deadline": self.now + deadline_s})

    def _reap(self) -> None:
        still = []
        for item in self._inflight:
            pend = item["pend"]
            if pend.event.is_set():
                if pend.error is None:
                    if item["kind"] == "w":
                        self.history.complete(item["hid"], self.now)
                        self.durability.note_acked(item["val"])
                    else:
                        # barrier committed on this leader: its applied
                        # register value is the linearizable read
                        self.history.complete(item["hid"], self.now,
                                              self.value[item["node"]])
                elif item["kind"] == "w":
                    # deposed mid-flight: the entry may still commit
                    # under ANY later leader that kept it, so its
                    # linearization point is unbounded — ret stays
                    # open (a finite ret here would let the checker
                    # flag legal raft executions where the entry
                    # resurfaces after an intervening read)
                    self.history.ambiguous(item["hid"], None)
                else:
                    self.history.discard(item["hid"])
            elif self.now >= item["deadline"]:
                if item["kind"] == "w":
                    self.history.ambiguous(item["hid"], None)
                else:
                    self.history.discard(item["hid"])
            else:
                still.append(item)
        self._inflight = still

    # ---------------------------------------------------------------- check

    def settle(self, seconds: float = 1.5) -> None:
        """Fault-free tail: give the cluster time to re-elect, commit,
        and converge before the final checks."""
        self.injector.clear()
        self.transport.heal()
        self.skew = {nid: 0.0 for nid in self.ids}
        self.step(seconds)

    def violations(self, final: bool = True) -> List[str]:
        v = list(self.election.violations) + list(self.durability.violations)
        if final:
            live = [nid for nid in self.ids if self.alive[nid]]
            v += self.durability.final_check(self.logs, live)
            ok, why = check_linearizable(self.history.recorded())
            if not ok:
                v.append(f"linearizability: {why}")
        return v

    def digest_detail(self) -> dict:
        """Canonical end-state for the reproducibility digest."""
        return {
            "logs": {nid: self.logs[nid] for nid in self.ids},
            "acked": self.durability.acked,
            "ops": len(self.history.recorded()),
            "terms": max((n.current_term for n in self.nodes.values()),
                         default=0),
        }


# ---------------------------------------------------------------------------
# layer 3: SWIM chaos harness (device scans, host-side schedule)
# ---------------------------------------------------------------------------

_SWIM_COMPILED: dict = {}


def compiled_swim_run(params, ticks: int, monitor=None):
    """One jitted chunk runner per (params, ticks, monitor), returning
    swim.run's (state, trace) tuple.  The bare swim.run RETRACES its
    whole step graph on every call (~1-2 s of tracing each); this
    cache traces once per key — every scenario in a process shares the
    compilation (and the persistent XLA cache shares it across
    processes).  Tests with convergence loops use it too
    (tests/test_correlated_failures.py)."""
    key = (params, ticks, monitor)
    if key not in _SWIM_COMPILED:
        import jax

        from consul_tpu.models import swim as _swim
        _SWIM_COMPILED[key] = jax.jit(
            lambda st: _swim.run(params, st, ticks, monitor))
    return _SWIM_COMPILED[key]


class SwimChaosHarness:
    """The jitted SWIM pool under the nemesis: partition groups and
    per-node delivery multipliers live in SwimState (chaos_grp /
    chaos_ok), so the host evolves the fault schedule BETWEEN device
    scans without a single recompile.  `clean` tracks nodes the
    nemesis never touched — the invariant is that a clean, up, member
    node is NEVER committed dead (no committed death of a reachable
    live node)."""

    def __init__(self, seed: int, n: int = 128, slots: int = 16,
                 p_loss: float = 0.01, chunk: int = 50):
        import numpy as np

        from consul_tpu.config import GossipConfig, SimConfig
        from consul_tpu.models import swim
        self._np = np
        self._swim = swim
        self.seed = seed
        self.params = swim.make_params(
            GossipConfig.lan(),
            SimConfig(n_nodes=n, rumor_slots=slots, p_loss=p_loss,
                      seed=seed, chaos=True))
        self.state = swim.init_state(self.params)
        self.n = n
        self.chunk = chunk
        self.clean = np.ones(n, bool)
        self.crashed = np.zeros(n, bool)
        # sticky record of every node EVER committed dead — a later
        # rejoin clears the live flag, not the historical fact the
        # checkers assert on
        self.ever_committed = np.zeros(n, bool)
        self.violations: List[str] = []
        self._run = compiled_swim_run(self.params, chunk)

    # ------------------------------------------------------------ stepping

    def advance(self, ticks: int) -> None:
        for _ in range(max(1, math.ceil(ticks / self.chunk))):
            self.state = self._run(self.state)[0]
            self._check_clean()

    def _check_clean(self) -> None:
        np = self._np
        committed = np.asarray(self.state.committed_dead) \
            | np.asarray(self.state.committed_left)
        self.ever_committed |= committed
        bad = committed & self.clean & np.asarray(self.state.up) \
            & np.asarray(self.state.member)
        if bad.any():
            ids = np.flatnonzero(bad)[:8].tolist()
            self.violations.append(
                f"swim: reachable live nodes {ids} committed dead/left "
                f"at tick {int(self.state.tick)}")
            self.clean[bad] = False       # report each node once

    # -------------------------------------------------------------- faults

    def partition(self, mask) -> None:
        """Split the pool: mask nodes into group 1 (unreachable from
        group 0).  Masked nodes may legitimately be declared dead by
        the majority, so they leave the clean set."""
        np, jnp = self._np, _jnp()
        mask = np.asarray(mask, bool)
        self.clean &= ~mask
        self.state = self.state.replace(
            chaos_grp=jnp.asarray(mask.astype(np.int16)))

    def heal_partition(self) -> None:
        jnp = _jnp()
        self.state = self.state.replace(
            chaos_grp=jnp.zeros((self.n,), jnp.int16))

    def crash(self, mask) -> None:
        np = self._np
        mask = np.asarray(mask, bool)
        self.clean &= ~mask
        self.crashed |= mask
        self.state = self._swim.kill_mask(self.state, _jnp().asarray(mask))

    def flap_revive(self, mask) -> None:
        """Restart crashed nodes inside the suspicion/dissemination
        window — the satellite path: they rejoin with a bumped
        incarnation so stale death rumors can't re-commit them."""
        np = self._np
        mask = np.asarray(mask, bool)
        self.crashed &= ~mask
        self.state = self._swim.revive_mask(self.state,
                                            _jnp().asarray(mask))

    def degrade(self, mask, ok: float) -> None:
        """Asymmetric local degradation (Lifeguard's bad-NIC): masked
        nodes deliver each of THEIR legs at rate `ok`."""
        np, jnp = self._np, _jnp()
        mask = np.asarray(mask, bool)
        cur = np.array(self.state.chaos_ok)      # writable host copy
        cur[mask] = ok
        self.state = self.state.replace(chaos_ok=jnp.asarray(cur))

    def loss_burst(self, p: float) -> None:
        """Symmetric loss burst: every leg delivers at (1-p) on top of
        the baseline — realized as a global per-node multiplier of
        sqrt(1-p) (a leg pays both endpoints)."""
        jnp = _jnp()
        self.state = self.state.replace(
            chaos_ok=jnp.full((self.n,), math.sqrt(max(0.0, 1.0 - p)),
                              jnp.float32))

    def calm(self) -> None:
        jnp = _jnp()
        self.state = self.state.replace(
            chaos_ok=jnp.ones((self.n,), jnp.float32))

    # --------------------------------------------------------------- checks

    def rejoin_committed(self) -> int:
        """Operator rejoin for every UP node the cluster declared dead
        — committed, or carrying an active dead rumor (post-heal
        reconciliation: a real agent that hears itself declared dead
        rejoins with a bumped incarnation, serf snapshot rejoin).  The
        sim has no alive-refutes-dead channel (memberlist aliveNode on
        a dead entry), so this host sweep IS that mechanism."""
        np = self._np
        declared = np.asarray(self.state.committed_dead).copy()
        r_active = np.asarray(self.state.r_active)
        r_kind = np.asarray(self.state.r_kind)
        r_subject = np.asarray(self.state.r_subject)
        dead_rumor = r_active & (r_kind == self._swim.DEAD)
        declared[r_subject[dead_rumor]] = True
        up = np.asarray(self.state.up) & np.asarray(self.state.member)
        todo = np.flatnonzero(declared & up)
        for node in todo:
            self.state = self._swim.rejoin(self.params, self.state,
                                           int(node))
        return len(todo)

    def check_not_committed(self, mask, label: str) -> None:
        np = self._np
        bad = self.ever_committed & np.asarray(mask, bool)
        if bad.any():
            self.violations.append(
                f"swim: {label}: nodes {np.flatnonzero(bad)[:8].tolist()} "
                f"were committed dead")

    def reconverge(self, budget_ticks: int,
                   label: str = "reconverge") -> dict:
        """After heal: within `budget_ticks` every still-crashed node
        must be cluster-detected and NO live member may remain
        believed-down.  Each chunk runs the rejoin sweep — live nodes
        that discover they were declared dead during the fault window
        rejoin, exactly as their agents would."""
        victims = _jnp().asarray(self.crashed)
        recall, fp = 0.0, -1
        spent = 0
        while spent < budget_ticks:
            self.advance(self.chunk)
            spent += self.chunk
            self.rejoin_committed()
            recall, fp = self._swim.mass_detection_stats(
                self.params, self.state, victims)
            recall, fp = float(recall), int(fp)
            if (not self.crashed.any() or recall >= 0.999) and fp == 0:
                return {"recall": recall, "false_positives": fp,
                        "ticks": spent}
        self.violations.append(
            f"swim: {label}: no re-convergence within {budget_ticks} "
            f"ticks (recall={recall}, believed-down live nodes={fp})")
        return {"recall": recall, "false_positives": fp, "ticks": spent}

    def digest_detail(self) -> dict:
        np = self._np
        return {
            "tick": int(self.state.tick),
            "committed_dead": np.flatnonzero(
                np.asarray(self.state.committed_dead)).tolist(),
            "incarnation_sum": int(np.asarray(
                self.state.incarnation).sum()),
        }


def _jnp():
    import jax.numpy as jnp
    return jnp


# ---------------------------------------------------------------------------
# scenarios
# ---------------------------------------------------------------------------


def _drive(h: RaftChaosHarness, seconds: float, write_every: float = 0.06,
           read_every: float = 0.17, dt: float = 0.01) -> None:
    """Step the raft harness while issuing a deterministic client
    schedule of writes + barrier reads."""
    end = h.now + seconds
    next_w = h.now + write_every
    next_r = h.now + read_every
    while h.now < end - 1e-9:
        if h.now >= next_w:
            h.do_write()
            next_w += write_every
        if h.now >= next_r:
            h.do_read()
            next_r += read_every
        h.step(dt, dt)


def _report(name: str, seed: int, violations: List[str],
            detail: dict) -> dict:
    digest = hashlib.sha256(
        json.dumps(detail, sort_keys=True, default=str).encode()
    ).hexdigest()[:16]
    return {
        "scenario": name, "seed": seed, "ok": not violations,
        "violations": violations, "digest": digest, "detail": detail,
        "repro": f"python tools/chaos_soak.py --seed {seed} "
                 f"--scenario {name}",
    }


def scenario_partition_heal(seed: int, tmp: Optional[str] = None,
                            soak: bool = False) -> dict:
    """Partition both layers, write through it, heal, reconverge.

    Raft: a 5-node cluster loses {old leader, one follower} to a
    minority partition mid-traffic; the majority elects and serves;
    heal; every acked write must survive and histories linearize.
    SWIM: 25% of the pool splits off long enough for the majority to
    COMMIT the minority's deaths; on heal the committed-but-alive
    nodes rejoin with bumped incarnations and the pool reconverges."""
    h = RaftChaosHarness(n=5, seed=seed)
    h.step(1.0)                                  # elect
    _drive(h, 1.0)
    leader = h._leader()
    minority = [leader.node_id if leader else h.ids[0]]
    minority.append(next(i for i in h.ids if i not in minority))
    majority = [i for i in h.ids if i not in minority]
    for a in minority:
        for b in majority:
            h.transport.partition(a, b)
    _drive(h, 2.0 if soak else 1.5)
    h.transport.heal()
    _drive(h, 1.5)
    h.settle()
    violations = h.violations()
    detail = {"raft": h.digest_detail(), "minority": minority}

    sw = SwimChaosHarness(seed, n=256 if soak else 128)
    sw.advance(50)                               # settle the pool
    np = sw._np
    mask = np.arange(sw.n) % 4 == 3              # deterministic 25%
    sw.partition(mask)
    p = sw.params
    # long enough for the majority to commit minority deaths: timer +
    # declare lag + the 4x coverage-capped slot lifetime, with slack
    sw.advance(p.suspicion_max_ticks + p.declare_lag_ticks
               + 6 * p.expiry_gossip_ticks)
    sw.heal_partition()
    rejoined = sw.rejoin_committed()
    rec = sw.reconverge(4000, "partition_heal")
    violations += sw.violations
    detail["swim"] = dict(sw.digest_detail(), rejoined=rejoined, **rec)
    return _report("partition_heal", seed, violations, detail)


def scenario_crash_restart(seed: int, tmp: Optional[str] = None,
                           soak: bool = False) -> dict:
    """Crash + restart-from-durable-log on raft; kill_mask + flap
    revive on SWIM (the incarnation-bump satellite path)."""
    import tempfile
    with tempfile.TemporaryDirectory(dir=tmp) as d:
        h = RaftChaosHarness(n=3, seed=seed, data_root=d)
        h.step(1.0)
        _drive(h, 1.0)
        follower = next(i for i in h.ids
                        if not h.nodes[i].is_leader())
        h.crash(follower)
        _drive(h, 1.0)
        h.restart(follower)
        _drive(h, 1.0)
        leader = h._leader()
        if leader is not None:
            h.crash(leader.node_id)
            _drive(h, 1.5)                      # re-elect + serve
            h.restart(leader.node_id)
        _drive(h, 1.0)
        h.settle()
        violations = h.violations()
        detail = {"raft": h.digest_detail()}

    sw = SwimChaosHarness(seed, n=256 if soak else 128)
    sw.advance(50)
    np = sw._np
    rng = np.random.default_rng(seed)
    victims = rng.choice(sw.n, size=10, replace=False)
    mask = np.zeros(sw.n, bool)
    mask[victims] = True
    sw.crash(mask)
    # let suspicions get airborne (timers started, rumors circulating)
    # but flap BEFORE the suspicion timeout can expire into commits —
    # one chunk (50 ticks) sits inside the ~sus_min+lag window
    sw.advance(sw.chunk)
    revived = np.zeros(sw.n, bool)
    revived[victims[:5]] = True
    sw.flap_revive(revived)
    rec = sw.reconverge(6000, "crash_restart")
    sw.check_not_committed(revived, "flap-revived nodes")
    violations += sw.violations
    detail["swim"] = dict(sw.digest_detail(), **rec)
    return _report("crash_restart", seed, violations, detail)


def scenario_loss_burst(seed: int, tmp: Optional[str] = None,
                        soak: bool = False) -> dict:
    """Symmetric lossy window on both layers.  Loss alone must never
    commit a death (Lifeguard refutation + coverage-guarded commit):
    the SWIM side asserts ZERO committed deaths throughout."""
    h = RaftChaosHarness(n=3, seed=seed)
    h.step(1.0)
    _drive(h, 0.8)
    h.injector.set_default(drop_p=0.35)
    _drive(h, 2.0 if soak else 1.2)
    h.injector.clear()
    _drive(h, 1.0)
    h.settle()
    violations = h.violations()
    detail = {"raft": h.digest_detail()}

    sw = SwimChaosHarness(seed, n=256 if soak else 128)
    sw.advance(50)
    sw.loss_burst(0.30)
    sw.advance(sw.params.suspicion_max_ticks * (2 if soak else 1))
    sw.calm()
    sw.advance(500)
    np = sw._np
    n_committed = int(np.asarray(sw.state.committed_dead).sum())
    if n_committed:
        sw.violations.append(
            f"swim: loss burst committed {n_committed} deaths with "
            f"zero crashes")
    violations += sw.violations
    detail["swim"] = dict(sw.digest_detail(), committed=n_committed)
    return _report("loss_burst", seed, violations, detail)


def scenario_asym_degradation(seed: int, tmp: Optional[str] = None,
                              soak: bool = False) -> dict:
    """Lifeguard's motivating fault: a few nodes with a degraded NIC.
    Raft: one node's OUTBOUND links drop 50% (asymmetric).  SWIM: 10%
    of nodes deliver their legs at 55% — they must neither be
    committed dead (they are up and refute) nor poison the pool."""
    h = RaftChaosHarness(n=3, seed=seed)
    h.step(1.0)
    _drive(h, 0.8)
    h.injector.set_link(h.ids[0], None, drop_p=0.5)
    _drive(h, 2.0 if soak else 1.2)
    h.injector.clear()
    _drive(h, 0.8)
    h.settle()
    violations = h.violations()
    detail = {"raft": h.digest_detail()}

    sw = SwimChaosHarness(seed, n=256 if soak else 128)
    sw.advance(50)
    np = sw._np
    degraded = np.arange(sw.n) % 10 == 5         # deterministic 10%
    sw.degrade(degraded, 0.55)
    sw.advance(sw.params.suspicion_max_ticks)
    sw.calm()
    sw.advance(800)
    sw.check_not_committed(degraded, "degraded-but-live nodes")
    n_committed = int(np.asarray(sw.state.committed_dead).sum())
    if n_committed:
        sw.violations.append(
            f"swim: degradation committed {n_committed} deaths with "
            f"zero crashes")
    violations += sw.violations
    detail["swim"] = dict(sw.digest_detail(),
                          degraded=int(degraded.sum()))
    return _report("asym_degradation", seed, violations, detail)


def scenario_clock_skew(seed: int, tmp: Optional[str] = None,
                        soak: bool = False) -> dict:
    """Per-node clock skew on the raft layer: one node runs 150 ms
    ahead, one 100 ms behind, and the offsets JUMP mid-run (an NTP
    step).  Elections churn; safety and linearizability must not."""
    h = RaftChaosHarness(n=3, seed=seed)
    h.step(1.0)
    _drive(h, 0.8)
    h.skew = {"n0": 0.15, "n1": -0.10, "n2": 0.0}
    _drive(h, 1.5 if soak else 1.0)
    # NTP step: n1 jumps > election_timeout forward (it fires an
    # immediate pre-vote), n0 steps BACKWARD (its timers stall until
    # its clock catches back up)
    h.skew = {"n0": -0.30, "n1": 0.45, "n2": 0.05}
    _drive(h, 1.5 if soak else 1.0)
    h.settle()
    violations = h.violations()
    return _report("clock_skew", seed, violations,
                   {"raft": h.digest_detail()})


def scenario_link_chaos(seed: int, tmp: Optional[str] = None,
                        soak: bool = False) -> dict:
    """Message-level chaos on every raft link: variable delays (which
    ARE reordering), duplication, and light loss, all at once."""
    h = RaftChaosHarness(n=3, seed=seed)
    h.step(1.0)
    _drive(h, 0.8)
    h.injector.set_default(drop_p=0.1, delay_p=0.5,
                           delay=(0.01, 0.06), dup_p=0.3)
    _drive(h, 2.5 if soak else 1.5)
    h.injector.clear()
    _drive(h, 0.8)
    h.settle()
    return _report("link_chaos", seed, h.violations(),
                   {"raft": h.digest_detail()})


def scenario_tcp_flaky(seed: int, tmp: Optional[str] = None,
                       soak: bool = False) -> dict:
    """Layer 2: a live socket cluster under the NetFaultSchedule —
    severed pooled connections and head-of-line delays while writes
    forward through followers.  Wall-clock (sockets + threads), so the
    INVARIANT here is end-state: every acked write is readable after
    the faults calm, and replicas agree.  The seeded schedule makes
    the fault stream reproducible; thread interleaving is the OS's."""
    import threading
    import time as wall

    from consul_tpu.rpc import FaultyTcpTransport, NetFaultSchedule
    from consul_tpu.server import Server

    faults = NetFaultSchedule(seed)
    addresses: Dict[str, Tuple[str, int]] = {}
    ids = [f"s{i}" for i in range(3)]
    servers = []
    for nid in ids:
        transport = FaultyTcpTransport(faults, addresses=addresses)
        srv = Server(nid, list(ids), transport, registry={}, seed=seed)
        srv.serve_rpc()
        servers.append(srv)
    stop = threading.Event()

    def pump():
        while not stop.is_set():
            for s in servers:
                s.tick(wall.time())
            wall.sleep(0.01)

    t = threading.Thread(target=pump, daemon=True)
    t.start()
    acked: List[str] = []
    violations: List[str] = []
    try:
        deadline = wall.time() + 20.0
        while wall.time() < deadline:
            if any(s.is_leader() for s in servers):
                break
            wall.sleep(0.05)
        else:
            violations.append("tcp: no leader elected")
        follower = next((s for s in servers if not s.is_leader()),
                        servers[0])
        for i in range(10):
            if i == 3:
                faults.drop_p, faults.sever_p, faults.delay_p = \
                    0.15, 0.1, 0.3
            if i == 7:
                faults.calm()
            try:
                ok, _ = follower.kv_set(f"chaos/{i}", f"v{i}".encode())
                if ok:
                    acked.append(f"chaos/{i}")
            except Exception:
                pass          # unacked under faults: no durability claim
        faults.calm()
        wall.sleep(0.5)
        leader = next((s for s in servers if s.is_leader()), None)
        if leader is None:
            violations.append("tcp: no leader after calm")
        else:
            for key in acked:
                row = leader.store.kv_get(key)
                if row is None:
                    violations.append(f"tcp: acked write {key} lost")
    finally:
        stop.set()
        t.join(timeout=2.0)
        for s in servers:
            s.close_rpc()
    return _report("tcp_flaky", seed, violations,
                   {"acked": len(acked)})


SCENARIOS = {
    "partition_heal": scenario_partition_heal,
    "crash_restart": scenario_crash_restart,
    "loss_burst": scenario_loss_burst,
    "asym_degradation": scenario_asym_degradation,
    "clock_skew": scenario_clock_skew,
    "link_chaos": scenario_link_chaos,
    "tcp_flaky": scenario_tcp_flaky,
}

# the fixed-seed tier-1 smoke set: every virtual-time scenario (the
# wall-clock tcp_flaky rides the full soak, its transport is unit-
# tested in tests/test_chaos.py)
CHECK_SCENARIOS = ("partition_heal", "crash_restart", "loss_burst",
                   "asym_degradation", "clock_skew", "link_chaos")


def run_scenario(name: str, seed: int, tmp: Optional[str] = None,
                 soak: bool = False) -> dict:
    return SCENARIOS[name](seed, tmp=tmp, soak=soak)
