"""Nemesis: a deterministic fault-injection engine with cross-layer
safety invariant checkers.

SWIM (Das et al., 2002) and Lifeguard (Dadgar et al., 2018) state their
guarantees *under* message loss and local degradation, and Consul's own
partition tests shut sockets down mid-write — yet until this module the
repo's only fault surfaces were a scalar `p_loss`, ad-hoc
`partition()`/`isolate()` hooks, and one blind TCP reconnect.  The
nemesis drives seeded, scenario-shaped fault timelines through FOUR
layers with one API and checks the safety properties that must survive
them:

  layer 0  the disk (consensus/logstore.py through the
           consul_tpu/storage.py seam): `FaultyStorage` models the
           page-cache/durable split and injects torn writes, lost and
           failing fsyncs, ENOSPC, rename reordering, and seeded bit
           rot; `run_crash_matrix` crashes at EVERY I/O boundary of a
           write/compact/snapshot/restart trace and checks recovery
           against a durable-prefix model (tools/crash_matrix.py);
  layer 1  in-memory raft transport (consensus/raft.py InMemTransport):
           partitions/heals via the generalized cut hooks, plus a
           message-level `LinkInjector` (loss, delay, duplication,
           reorder — delayed frames flush on `transport.advance(now)`,
           so the whole cluster stays tick-synchronous and
           bit-reproducible from the seed);
  layer 2  live framed-TCP path (rpc/net.py FaultyTcpTransport +
           NetFaultSchedule): severs/delays pooled connections on a
           seeded decision stream;
  layer 3  the jitted SWIM tick (models/swim.py): per-node partition
           groups (`chaos_grp`) and delivery-rate multipliers
           (`chaos_ok`) are STATE fields the host mutates between
           device scans — faults evolve on a host-side schedule with
           zero recompiles.

Invariant checkers:

  WAL recovery         recovered storage equals the replay of SOME
                       durable prefix at least as new as everything
                       acked (WalModel + check_wal_recovery: acked
                       present/in order/once, term-vote monotone past
                       acks, no resurrection of acked truncations,
                       corruption detected never replayed)
  election safety      at most one raft leader per term, ever
                       (Raft §5.2; ElectionSafetyChecker)
  committed durability acked writes survive crash-restart-from-
                       durable-log and replicas never fork (Raft §5.4;
                       DurabilityChecker pairwise prefix + final
                       presence/order check)
  linearizability      recorded client histories over one KV register
                       admit a legal linearization (Wing & Gong search
                       with ambiguous-outcome writes; check_linearizable)
  SWIM bounds          no committed death of a reachable live node; the
                       pool re-converges within a tick budget after
                       heal (SwimChaosHarness)

`tools/chaos_soak.py` replays scenario suites built on these pieces,
prints the reproducing seed on any violation, and emits CHAOS_r02.json;
its `--check` mode is the fixed-seed tier-1 smoke (network scenarios
plus the bounded storage-nemesis set).
"""

from __future__ import annotations

import errno
import hashlib
import json
import math
import os
import random
import struct
import tempfile
import zlib
from dataclasses import dataclass
from typing import Any, BinaryIO, Callable, Dict, List, Optional, Tuple

from consul_tpu import storage
from consul_tpu.consensus.logstore import DurableLog
from consul_tpu.consensus.raft import (
    LEADER, InMemTransport, NotLeaderError, RaftConfig, RaftNode,
)

# ---------------------------------------------------------------------------
# layer 1: schedule-driven message injector for InMemTransport
# ---------------------------------------------------------------------------


@dataclass
class LinkRule:
    """Per-link fault mix.  All probabilities are per-message; delays
    are seconds of virtual time (flushed by transport.advance)."""

    drop_p: float = 0.0
    delay_p: float = 0.0
    delay: Tuple[float, float] = (0.01, 0.05)
    dup_p: float = 0.0


class LinkInjector:
    """Deterministic per-message fault decisions for InMemTransport.

    `on_send` returns a list of delivery delays for the frame: empty =
    dropped; 0.0 = deliver now; positive = queue until advance(now)
    passes it (variable delays ARE reordering — a later frame with a
    shorter draw overtakes); an extra positive entry = duplicate.  At
    most one non-positive entry is ever returned (the transport
    delivers at most one immediate copy).  One seeded RNG consumed in
    tick-synchronous call order keeps the whole stream reproducible."""

    def __init__(self, seed: int = 0):
        self._rng = random.Random(seed)
        self.default: Optional[LinkRule] = None
        self.links: Dict[tuple, LinkRule] = {}   # (src|None, dst|None)

    def set_default(self, **kw) -> None:
        self.default = LinkRule(**kw) if kw else None
        from consul_tpu import flight
        flight.emit("chaos.fault.injected" if kw
                    else "chaos.fault.healed",
                    labels={"fault": "link", "target": "*"})

    def set_link(self, src: Optional[str], dst: Optional[str],
                 **kw) -> None:
        """Rule for a directed link; None is a wildcard endpoint —
        asymmetric faults are (src, None) rules."""
        self.links[(src, dst)] = LinkRule(**kw)
        from consul_tpu import flight
        flight.emit("chaos.fault.injected",
                    labels={"fault": "link",
                            "target": f"{src or '*'}|{dst or '*'}"})

    def clear(self) -> None:
        self.default = None
        self.links.clear()
        from consul_tpu import flight
        flight.emit("chaos.fault.healed",
                    labels={"fault": "link", "target": "*"})

    def _rule(self, src: str, dst: str) -> Optional[LinkRule]:
        return (self.links.get((src, dst))
                or self.links.get((src, None))
                or self.links.get((None, dst))
                or self.default)

    def on_send(self, src: str, dst: str, msg: dict,
                now: float) -> Optional[List[float]]:
        rule = self._rule(src, dst)
        if rule is None:
            return None                      # transport default path
        rng = self._rng
        if rule.drop_p and rng.random() < rule.drop_p:
            return []
        lo, hi = rule.delay
        plan = [lo + rng.random() * (hi - lo)
                if rule.delay_p and rng.random() < rule.delay_p else 0.0]
        if rule.dup_p and rng.random() < rule.dup_p:
            plan.append(lo + rng.random() * (hi - lo))
        return plan


# ---------------------------------------------------------------------------
# layer 0: the storage nemesis — a deterministic disk between the WAL
# and the bytes that survive a crash
# ---------------------------------------------------------------------------


class SimulatedCrash(BaseException):
    """Raised by FaultyStorage when the scheduled crash point arrives —
    BaseException so no storage-layer handler can swallow the `power
    loss` on its way out of the I/O stack."""

    def __init__(self, op_index: int, kind: str, path: str):
        super().__init__(f"simulated crash at I/O op {op_index} "
                         f"({kind} {os.path.basename(path)})")
        self.op_index = op_index
        self.kind = kind
        self.path = path


class FaultyStorage(storage.StorageOps):
    """The storage seam with a disk model underneath: real files carry
    the PAGE-CACHE view (what the running process reads back), while a
    shadow map carries the DURABLE view (what survives power loss).
    Writes land only in the cache; fsync promotes a file's cache to
    durable; rename is visible immediately but durable only at the
    parent-dir fsync.  `crash()` collapses the cache: every file
    reverts to its durable bytes — plus, under the torn-write model, a
    seeded prefix of its unsynced tail, the way a page cache drains
    partially — and injectable faults betray the contract on the way:

      lose_next_fsyncs   N fsyncs return success without persisting
                         (a lying disk / ignored barrier)
      fail_next_fsyncs   N fsyncs raise EIO (and persist nothing)
      enospc             every write raises ENOSPC
      enospc_after_writes  arm enospc after N more successful writes
      torn               crash keeps a seeded partial unsynced tail
      rename_reorder     crash commits un-fsynced renames while the
                         renamed file's data may be lost (journal
                         metadata outran the data blocks)
      corrupt_on_crash   basenames that get one seeded bit flipped in
                         their durable bytes at crash (bit rot)

    Every durable-relevant call is one numbered I/O boundary;
    `crash_at=k` raises SimulatedCrash in place of boundary k, which is
    how tools/crash_matrix.py enumerates every cut of a trace.  All
    randomness (tear lengths, flip positions) comes from per-file RNGs
    derived from the seed, so a (seed, crash_at) pair is a complete
    reproducer."""

    def __init__(self, seed: int = 0, crash_at: Optional[int] = None,
                 torn: bool = False, rename_reorder: bool = False,
                 corrupt_on_crash: Tuple[str, ...] = (),
                 adopt_existing: bool = False):
        self.seed = seed
        self.crash_at = crash_at
        self.torn = torn
        self.rename_reorder = rename_reorder
        self.corrupt_on_crash = tuple(corrupt_on_crash)
        # adopt_existing: a FRESH FaultyStorage opening files written
        # by a PREVIOUS process life (the live nemesis restarts a
        # server on its data-dir) must treat their on-disk bytes as
        # already durable — without this, the first crash() of the new
        # life could tear into bytes an earlier fsync made safe, a
        # disk state no real power loss can produce
        self.adopt_existing = adopt_existing
        self.lose_next_fsyncs = 0
        self.fail_next_fsyncs = 0
        self.enospc = False
        self.enospc_after_writes: Optional[int] = None
        self.op_count = 0
        self.oplog: List[Tuple[str, str]] = []
        self.files: Dict[str, bytes] = {}      # durable view
        self.flips: List[Tuple[str, int, int]] = []
        self._pending: List[Tuple[str, str]] = []   # un-fsynced renames
        self._paths: Dict[int, str] = {}       # id(handle) -> path
        self._handles: List[BinaryIO] = []
        self._tracked: set = set()
        self._tmp_n = 0

    # ------------------------------------------------------------- plumbing

    def _op(self, kind: str, path: str) -> int:
        i = self.op_count
        self.op_count += 1
        self.oplog.append((kind, os.path.basename(path)))
        if self.crash_at is not None and i >= self.crash_at:
            self._journal("crash_at",
                          f"{kind}:{os.path.basename(path)}@{i}")
            raise SimulatedCrash(i, kind, path)
        return i

    @staticmethod
    def _journal(fault: str, target: str) -> None:
        """Each storage betrayal is one correlated flight-recorder row
        (ts from the recorder's clock — constant under the nemesis, so
        timelines stay byte-identical)."""
        from consul_tpu import flight
        flight.emit("chaos.fault.injected",
                    labels={"fault": fault, "target": target})

    def _file_rng(self, path: str) -> random.Random:
        return random.Random(
            (self.seed << 32)
            ^ zlib.crc32(os.path.basename(path).encode()))

    def _register(self, f: BinaryIO, path: str) -> BinaryIO:
        if self.adopt_existing and path not in self.files \
                and path not in self._tracked and os.path.exists(path):
            try:
                with open(path, "rb") as r:
                    blob = r.read()
                if blob:
                    self.files[path] = blob
            except OSError:
                pass
        self._paths[id(f)] = path
        self._handles.append(f)
        self._tracked.add(path)
        return f

    def _path_of(self, f: BinaryIO) -> str:
        return self._paths.get(id(f)) or f.name

    # -------------------------------------------------------------- handles

    def open_append(self, path: str) -> BinaryIO:
        # unbuffered: the cache view must reflect every seam write
        # immediately, or tear lengths depend on libc buffer timing
        return self._register(open(path, "ab", buffering=0), path)

    def open_rw(self, path: str) -> BinaryIO:
        return self._register(open(path, "r+b", buffering=0), path)

    def create_tmp(self, directory: str,
                   prefix: str) -> Tuple[BinaryIO, str]:
        # deterministic names: tmp paths feed the durable map and the
        # per-file RNGs, so mkstemp randomness would leak into digests
        self._tmp_n += 1
        tmp = os.path.join(directory, f"{prefix}{self._tmp_n:06d}")
        return self._register(open(tmp, "wb", buffering=0), tmp), tmp

    # ---------------------------------------------------------- durable ops

    def write(self, f: BinaryIO, data: bytes) -> None:
        path = self._path_of(f)
        self._op("write", path)
        if self.enospc_after_writes is not None:
            if self.enospc_after_writes <= 0:
                self.enospc = True
            else:
                self.enospc_after_writes -= 1
        if self.enospc:
            self._journal("enospc", os.path.basename(path))
            raise OSError(errno.ENOSPC, "No space left on device")
        f.write(data)

    def fsync(self, f: BinaryIO) -> None:
        path = self._path_of(f)
        self._op("fsync", path)
        f.flush()
        if self.fail_next_fsyncs > 0:
            self.fail_next_fsyncs -= 1
            self._journal("fsync_eio", os.path.basename(path))
            raise OSError(errno.EIO, "Input/output error")
        if self.lose_next_fsyncs > 0:
            self.lose_next_fsyncs -= 1
            self._journal("fsync_lost", os.path.basename(path))
            return                      # the disk lied: nothing durable
        try:
            with open(path, "rb") as r:
                self.files[path] = r.read()
        except FileNotFoundError:
            pass

    def truncate(self, f: BinaryIO, size: int) -> None:
        self._op("truncate", self._path_of(f))
        f.truncate(size)

    def replace(self, src: str, dst: str) -> None:
        self._op("replace", dst)
        if self.adopt_existing and dst not in self.files \
                and dst not in self._tracked and os.path.exists(dst):
            # the file being replaced carries a previous process life's
            # durable bytes: a crash before fsync_dir must be able to
            # roll back to them, so adopt them before the rename
            try:
                with open(dst, "rb") as r:
                    blob = r.read()
                if blob:
                    self.files[dst] = blob
            except OSError:
                pass
        storage.StorageOps.replace(self, src, dst)
        self._tracked.add(dst)
        self._pending.append((src, dst))

    def fsync_dir(self, directory: str) -> None:
        self._op("fsync_dir", directory)
        still = []
        for src, dst in self._pending:
            if os.path.dirname(dst) == directory:
                # the rename journals: dst durably takes src's DURABLE
                # bytes (un-fsynced src data does not ride along)
                self.files[dst] = self.files.pop(src, b"")
            else:
                still.append((src, dst))
        self._pending = still

    # ------------------------------------------------------------ the crash

    def crash(self) -> None:
        """Power loss: collapse the cache to the durable view and
        materialize it onto the real files, applying the armed
        betrayals (torn tails, reordered renames, bit rot).  The model
        stays usable afterwards — its durable map is the new disk."""
        self._journal("power_loss", "disk")
        for f in self._handles:
            try:
                f.close()
            except OSError:
                pass
        self._handles.clear()
        self._paths.clear()
        survivors = dict(self.files)
        # a path touched by an un-fsynced rename holds a DIFFERENT
        # inode than its durable bytes — torn-tail extension across
        # inodes would fabricate impossible disk states
        renamed = {p for pair in self._pending for p in pair}
        if self.rename_reorder:
            for src, dst in self._pending:
                survivors[dst] = survivors.pop(src, b"")
        self._pending.clear()
        final: Dict[str, bytes] = {}
        for path in sorted(self._tracked):
            base = survivors.get(path)
            try:
                with open(path, "rb") as r:
                    real = r.read()
            except FileNotFoundError:
                real = None
            out = base
            if self.torn and real is not None and path not in renamed:
                pre = base if base is not None else b""
                if len(real) > len(pre) and real[:len(pre)] == pre:
                    tail = real[len(pre):]
                    k = self._file_rng(path).randint(0, len(tail))
                    if base is not None or k > 0:
                        out = pre + tail[:k]
            if out is not None:
                final[path] = out
        for name in self.corrupt_on_crash:
            for path, blob in final.items():
                if os.path.basename(path) == name and blob:
                    rng = self._file_rng(path + "#rot")
                    pos = rng.randrange(len(blob))
                    bit = 1 << rng.randrange(8)
                    final[path] = (blob[:pos]
                                   + bytes([blob[pos] ^ bit])
                                   + blob[pos + 1:])
                    self.flips.append((name, pos, bit))
        for path in sorted(self._tracked):
            if path in final:
                with open(path, "wb") as w:
                    w.write(final[path])
            else:
                try:
                    os.unlink(path)
                except OSError:
                    pass
        self.files = final
        self._tracked = set(final)


# ---------------------------------------------------------------------------
# storage recovery model: `recovered state == replay of SOME durable
# prefix at least as new as everything acked`
# ---------------------------------------------------------------------------


class WalModel:
    """Ground truth for one DurableLog's trace.  The driver mirrors
    every logical WAL record / meta write / snapshot write into this
    model (note_* BEFORE the call, ack_* after it returns), and the
    checker then verifies that the recovered state equals the replay
    of some legal durable cut:

      WAL    ∃ j >= acked-floor with replay(records[:j]) == recovered
             (mid-rewrite crashes add the would-be rewritten file as a
             second candidate list)
      meta   recovered (term, vote) ∈ states from the last acked one
             onward — term/vote never move backwards past an ack
      snap   recovered (index, term, data) likewise

    That one containment check subsumes PR 3's durability invariants
    at this layer: acked entries present (cut >= floor), in order and
    once (replay equality), no resurrection of acked truncations
    (records after the trunc are inside every legal cut), and no
    garbage (nothing outside the model ever compares equal)."""

    def __init__(self):
        self.records: List[tuple] = []
        self.acked = 0
        self.alt: Optional[List[tuple]] = None
        self.meta_states: List[tuple] = [(0, None)]
        self.meta_acked = 0
        self.snap_states: List[tuple] = [(0, 0, None)]
        self.snap_acked = 0

    # WAL records ----------------------------------------------------------

    def note_entry(self, idx: int, term: int, cmd,
                   noop: bool = False) -> None:
        self.records.append(("e", idx, term, cmd, noop))

    def note_trunc(self, idx: int) -> None:
        self.records.append(("trunc", idx))

    def note_base(self, idx: int, term: int) -> None:
        self.records.append(("base", idx, term))

    def rollback_record(self) -> None:
        """The write raised (ENOSPC) before the frame hit the file."""
        self.records.pop()

    def ack_wal(self) -> None:
        self.acked = len(self.records)

    # meta / snap ----------------------------------------------------------

    def begin_meta(self, term: int, vote) -> None:
        self.meta_states.append((term, vote))

    def ack_meta(self) -> None:
        self.meta_acked = len(self.meta_states) - 1

    def begin_snap(self, index: int, term: int, data) -> None:
        self.snap_states.append((index, term, data))

    def ack_snap(self) -> None:
        self.snap_acked = len(self.snap_states) - 1

    # rewrite --------------------------------------------------------------

    def begin_rewrite(self, new_records: List[tuple]) -> None:
        self.alt = new_records

    def end_rewrite(self, rewrote: bool) -> None:
        if rewrote:
            self.records = list(self.alt)
            self.acked = len(self.records)
        self.alt = None


def _model_replay(records: List[tuple], snap_index: Optional[int],
                  snap_term: int) -> Tuple[int, int, dict]:
    """Mirror DurableLog.load()'s WAL semantics over logical records."""
    base, base_term = 0, 0
    entries: Dict[int, tuple] = {}
    for r in records:
        if r[0] == "e":
            entries[r[1]] = (r[2], r[3], r[4])
        elif r[0] == "trunc":
            for i in [i for i in entries if i >= r[1]]:
                del entries[i]
        elif r[0] == "base":
            if r[1] >= base:
                base, base_term = r[1], r[2]
    if snap_index is not None and base == 0:
        base, base_term = snap_index, snap_term
    for i in [i for i in entries if i <= base]:
        del entries[i]
    return base, base_term, entries


def check_wal_recovery(recovered: Optional[dict], model: WalModel,
                       lenient: frozenset = frozenset()) -> List[str]:
    """Recovery invariant check; `lenient` relaxes the acked floor for
    components a scenario deliberately corrupted ('wal', 'meta',
    'snap' — e.g. bit rot on snap.json legitimately falls back one
    generation)."""
    out = []
    if recovered is None:
        if (model.acked or model.meta_acked or model.snap_acked):
            return ["recovery: acked state exists but the directory "
                    "loaded as fresh"]
        return []
    got_meta = (recovered["term"], recovered["voted_for"])
    allowed = model.meta_states if "meta" in lenient \
        else model.meta_states[model.meta_acked:]
    if got_meta not in allowed:
        out.append(f"meta: recovered term/vote {got_meta} not in the "
                   f"legal set {allowed} (term/vote moved backwards "
                   f"past an acked write)")
    got_snap = (recovered["snap_index"], recovered["snap_term"],
                recovered["snapshot"])
    allowed_s = model.snap_states if "snap" in lenient \
        else model.snap_states[model.snap_acked:]
    if got_snap not in allowed_s:
        out.append(f"snap: recovered snapshot index "
                   f"{recovered['snap_index']} not in the legal set "
                   f"{[s[0] for s in allowed_s]}")
    snap_idx = recovered["snap_index"] if recovered["snapshot"] is not None \
        else None
    candidates = [(model.records,
                   0 if "wal" in lenient else model.acked)]
    if model.alt is not None:
        candidates.append((model.alt,
                           0 if "wal" in lenient else len(model.alt)))
    for recs, floor in candidates:
        for j in range(floor, len(recs) + 1):
            b, bt, ents = _model_replay(recs[:j], snap_idx,
                                        recovered["snap_term"])
            if (b == recovered["base"] and bt == recovered["base_term"]
                    and ents == recovered["entries"]):
                return out
    out.append(
        f"wal: recovered entries {sorted(recovered['entries'])} "
        f"(base {recovered['base']}) match no legal durable prefix — "
        f"acked entries lost, resurrected, reordered, or corrupt "
        f"bytes replayed")
    return out


# ---------------------------------------------------------------------------
# crash-point trace + matrix
# ---------------------------------------------------------------------------


def _drive_wal_trace(directory: str, fs: FaultyStorage, seed: int,
                     steps: int, model: WalModel, holder: dict,
                     rewrite_threshold: int = 14) -> None:
    """One seeded write/compact/snapshot/restart trace against a
    DurableLog on `fs`.  The trace script depends only on `seed`, so
    every crash_at cell of the matrix cuts the SAME op sequence.
    `holder['log']` always carries the live DurableLog so the caller
    can abort() it when SimulatedCrash unwinds."""
    rng = random.Random(seed ^ 0x5EED)
    log = holder["log"] = DurableLog(directory,
                                     rewrite_threshold=rewrite_threshold,
                                     io=fs)
    log.load()
    term, vote = 1, None
    model.begin_meta(term, vote)
    log.set_term_vote(term, vote)
    model.ack_meta()
    next_idx, base, base_term, val = 1, 0, 0, 0
    all_ents: Dict[int, tuple] = {}    # idx -> (term, cmd, noop), never
    #                                    pruned by compaction
    for _ in range(steps):
        r = rng.random()
        if r < 0.52 or next_idx <= 3:
            for _ in range(rng.randint(1, 3)):
                cmd = f"v{val}"
                val += 1
                model.note_entry(next_idx, term, cmd)
                try:
                    log.append(next_idx, term, cmd)
                except OSError:
                    model.rollback_record()
                    continue
                all_ents[next_idx] = (term, cmd, False)
                next_idx += 1
            log.sync()
            model.ack_wal()
        elif r < 0.62:
            term += 1
            vote = rng.choice(["n0", "n1", None])
            model.begin_meta(term, vote)
            try:
                log.set_term_vote(term, vote)
            except OSError:
                continue
            model.ack_meta()
        elif r < 0.74 and next_idx - 1 > base + 1:
            # conflict resolution: truncate a suffix, re-append under
            # a bumped term (the deposed-leader shape)
            j = rng.randint(base + 2, next_idx - 1)
            model.note_trunc(j)
            try:
                log.truncate_from(j)
            except OSError:
                model.rollback_record()
                continue
            for i in range(j, next_idx):
                all_ents.pop(i, None)
            next_idx = j
            term += 1
            cmd = f"v{val}"
            val += 1
            model.note_entry(next_idx, term, cmd)
            try:
                log.append(next_idx, term, cmd)
            except OSError:
                model.rollback_record()
                log.sync()
                model.ack_wal()
                continue
            all_ents[next_idx] = (term, cmd, False)
            next_idx += 1
            log.sync()
            model.ack_wal()
        elif r < 0.90 and next_idx - 1 > base + 4:
            # compact: snapshot the applied prefix, base trails it
            snap_idx = next_idx - 1 - rng.randint(0, 2)
            new_base = max(base, snap_idx - rng.randint(0, 2))
            if snap_idx <= base:
                continue
            snap_term = all_ents[snap_idx][0]
            nb_term = all_ents[new_base][0] if new_base in all_ents \
                else base_term
            data = {"log": [all_ents[i][1]
                            for i in sorted(all_ents) if i <= snap_idx]}
            live = {i: all_ents[i] for i in all_ents if i > new_base}
            model.begin_snap(snap_idx, snap_term, data)
            model.note_base(new_base, nb_term)
            will_rewrite = (log._records_since_rewrite + 1
                            >= log.rewrite_threshold)
            if will_rewrite:
                model.begin_rewrite(
                    [("base", new_base, nb_term)]
                    + [("e", i, *live[i]) for i in sorted(live)
                       if i > new_base])
            try:
                res = log.save_snapshot(snap_idx, snap_term, data, live,
                                        base=new_base, base_term=nb_term)
            except OSError:
                model.rollback_record()     # the base frame never wrote
                model.end_rewrite(False)
                continue
            model.ack_snap()
            model.ack_wal()
            model.end_rewrite(res["rewrote"])
            base, base_term = new_base, nb_term
        else:
            # process restart (no power loss): the page cache — the
            # real files — survives; only the fds drop
            log.abort()
            log = holder["log"] = DurableLog(
                directory, rewrite_threshold=rewrite_threshold, io=fs)
            log.load()


def run_crash_matrix(seed: int, steps: int = 14, torn: bool = True,
                     stride: int = 1, tmp: Optional[str] = None,
                     crash_at: Optional[int] = None,
                     rewrite_threshold: int = 14) -> dict:
    """Enumerate every I/O boundary of the seeded trace, crash at each
    one, restart from the surviving bytes, and check recovery.  Pass
    `crash_at` to replay a single cell (the printed reproducer)."""

    def one_cell(k: Optional[int]) -> Tuple[List[str], str]:
        with tempfile.TemporaryDirectory(dir=tmp) as d:
            cell_seed = seed if k is None \
                else (seed * 1000003 + k) & 0xFFFFFFFF
            fs = FaultyStorage(seed=cell_seed, crash_at=k, torn=torn)
            model = WalModel()
            holder: dict = {}
            try:
                _drive_wal_trace(d, fs, seed, steps, model, holder,
                                 rewrite_threshold)
            except SimulatedCrash:
                pass
            if holder.get("log") is not None:
                holder["log"].abort()
            fs.crash()
            rec = DurableLog(d)
            st = rec.load()
            rec.close()
            digest = hashlib.sha256(json.dumps(
                {"st": None if st is None else
                 {"term": st["term"], "base": st["base"],
                  "entries": sorted(st["entries"].items())},
                 }, sort_keys=True, default=str).encode()
            ).hexdigest()[:8]
            return check_wal_recovery(st, model), digest

    # pass 0: record the full op trace (no crash) to size the matrix
    with tempfile.TemporaryDirectory(dir=tmp) as d:
        fs = FaultyStorage(seed=seed)
        model = WalModel()
        holder = {}
        _drive_wal_trace(d, fs, seed, steps, model, holder,
                         rewrite_threshold)
        holder["log"].close()
        n_ops = fs.op_count
        kinds = {}
        for kind, _ in fs.oplog:
            kinds[kind] = kinds.get(kind, 0) + 1
    cells = [crash_at] if crash_at is not None \
        else list(range(0, n_ops, stride)) + [n_ops]
    violations: List[str] = []
    digests: List[str] = []
    for k in cells:
        vs, digest = one_cell(k if k < n_ops else None)
        digests.append(digest)
        for v in vs:
            # the reproducer must replay the IDENTICAL run: torn mode
            # and rewrite threshold both change the op sequence/model
            torn_flag = " --torn" if torn else " --clean"
            violations.append(
                f"crash_at={k}: {v} [reproduce: python "
                f"tools/crash_matrix.py --seed {seed} --steps {steps}"
                f"{torn_flag} --rewrite-threshold {rewrite_threshold}"
                f" --crash-at {k}]")
    return {"boundaries": n_ops, "cells": len(cells),
            "op_kinds": kinds, "violations": violations,
            "digest": hashlib.sha256(
                "".join(digests).encode()).hexdigest()[:16]}


# ---------------------------------------------------------------------------
# invariant checkers
# ---------------------------------------------------------------------------


class ElectionSafetyChecker:
    """Raft §5.2: at most one leader may ever exist in a given term.
    Observe the cluster every step; a term with two distinct leader
    ids — even at different wall moments — is a safety violation."""

    def __init__(self):
        self.leaders_by_term: Dict[int, set] = {}
        self.violations: List[str] = []

    def observe(self, nodes) -> None:
        for n in nodes:
            if n.state == LEADER:
                self.note(n.current_term, n.node_id)

    def note(self, term: int, node_id: str) -> None:
        seen = self.leaders_by_term.setdefault(term, set())
        if node_id not in seen:
            seen.add(node_id)
            if len(seen) > 1:
                self.violations.append(
                    f"election safety: term {term} has leaders "
                    f"{sorted(seen)}")


class DurabilityChecker:
    """Raft §5.4 / state-machine safety: replicas' applied sequences
    never fork (pairwise prefix consistency at every step), and every
    ACKED write is present — exactly once, in ack order — on every
    live replica after the cluster settles (committed entries survive
    crash-restart)."""

    def __init__(self):
        self.acked: List[Any] = []
        self.violations: List[str] = []
        self._forked = False

    def note_acked(self, val: Any) -> None:
        self.acked.append(val)

    def observe(self, logs: Dict[str, list]) -> None:
        if self._forked:
            return       # a fork is terminal: report it once, not per step
        items = sorted(logs.items())
        for i in range(len(items)):
            for j in range(i + 1, len(items)):
                a_id, a = items[i]
                b_id, b = items[j]
                k = min(len(a), len(b))
                if a[:k] != b[:k]:
                    d = next(x for x in range(k) if a[x] != b[x])
                    self._forked = True
                    self.violations.append(
                        f"fork: {a_id}[{d}]={a[d]!r} vs "
                        f"{b_id}[{d}]={b[d]!r}")
                    return

    def final_check(self, logs: Dict[str, list],
                    live: List[str]) -> List[str]:
        out = []
        for nid in live:
            log = logs[nid]
            pos = -1
            for val in self.acked:
                hits = log.count(val)
                if hits == 0:
                    out.append(f"durability: acked write {val!r} "
                               f"missing from {nid}")
                    continue
                if hits > 1:
                    # a re-applied resent entry (double-apply) is as
                    # much a state-machine-safety bug as a lost one
                    out.append(f"durability: acked write {val!r} "
                               f"applied {hits}x on {nid}")
                p = log.index(val)
                if p <= pos:
                    out.append(f"durability: acked write {val!r} "
                               f"out of order on {nid}")
                pos = max(pos, p)
        return out


class RegisterHistory:
    """Client-side invoke/complete record over one KV register, fed to
    check_linearizable.  Writes carry unique values; a write whose
    outcome the client never learned (timeout, leader deposed mid-
    flight) is AMBIGUOUS — it may have applied at any point after its
    invocation, or never."""

    def __init__(self):
        self.ops: List[dict] = []

    def invoke(self, kind: str, val: Any, now: float,
               stale: bool = False,
               max_stale: Optional[float] = None) -> int:
        """`stale=True` tags a follower read (?stale): it is checked
        against the weaker serializable-prefix-within-max_stale model
        instead of strict linearizability.  `max_stale` is the bound
        in SECONDS the caller requested (None = unbounded)."""
        op = {"kind": kind, "val": val, "call": now,
              "ret": None, "ok": True, "discard": False}
        if stale:
            op["stale"] = True
            op["max_stale"] = max_stale
        self.ops.append(op)
        return len(self.ops) - 1

    def complete(self, op_id: int, now: float, val: Any = None) -> None:
        op = self.ops[op_id]
        op["ret"] = now
        if val is not None or op["kind"] == "r":
            op["val"] = val

    def ambiguous(self, op_id: int, now: Optional[float] = None) -> None:
        op = self.ops[op_id]
        op["ok"] = None
        op["ret"] = now          # None = never returned to the client

    def discard(self, op_id: int) -> None:
        self.ops[op_id]["discard"] = True

    def recorded(self) -> List[dict]:
        return [o for o in self.ops if not o["discard"]]


def _stale_read_ok(op: dict, writes: List[dict],
                   init: Any) -> Tuple[bool, Optional[str]]:
    """The stale-read taxonomy (ISSUE 12): a read tagged `stale=True`
    is NOT required to linearize — it may observe any *serializable
    prefix* of the write order that was possibly current within
    `max_stale` of its invocation (the reference's AllowStale +
    MaxStaleDuration contract: a follower serves its replica, whose
    state is some commit prefix at most its replication lag behind).

    Formally: the read of value v over window [call − max_stale, ret]
    is legal iff there is an instant τ in that window at which v was
    POSSIBLY the committed register — v's write may have taken effect
    by τ (w.call ≤ τ) and no acked write that is *certainly after* it
    (w2.call ≥ w.ret) had certainly completed by τ (w2.ret ≤ τ).
    A genuinely FORKED stale read — a value never written, or one
    certainly overwritten before the window opened — still fails."""
    INF = float("inf")
    bound = op.get("max_stale")
    t0 = op["call"] - (bound if bound is not None else INF)
    t1 = op["ret"]
    v = op["val"]

    def certainly_dead_by(w_ret: float, tau: float) -> bool:
        return any(w2["ok"] is True and w2["call"] >= w_ret
                   and w2["ret"] <= tau for w2 in writes)

    if v is None:
        # the initial state: possibly current at the window's OPEN
        # unless some acked write had certainly completed by then
        if not certainly_dead_by(-INF, t0):
            return True, None
        return False, (f"stale read of initial state at "
                       f"call={op['call']} but an acked write "
                       f"certainly completed before its "
                       f"max_stale={bound}s window opened")
    for w in writes:
        if w["val"] != v or w["call"] > t1:
            continue
        # earliest instant v could be current inside the window
        tau = max(t0, w["call"])
        if not certainly_dead_by(w["ret"], tau):
            return True, None
    return False, (f"stale read of {v!r} (call={op['call']}, "
                   f"max_stale={bound}) is a fork: value never "
                   f"possibly current within its staleness window")


def check_linearizable(ops: List[dict],
                       init: Any = None) -> Tuple[bool, Optional[str]]:
    """Wing & Gong linearizability search for a single register.

    ops: dicts with kind ('w'/'r'), val, call, ret (None = pending
    forever), ok (None = ambiguous write: may apply anywhere after its
    call, or never).  Memoized on (remaining-ops, register value); the
    harness keeps histories small and concurrency bounded, so the
    search stays well under the exponential worst case.

    Reads tagged `stale=True` (follower ?stale reads) are verified
    against the weaker serializable-prefix-within-max_stale model
    (`_stale_read_ok`) and excluded from the strict search — the
    reference never promises linearizable stale reads, only bounded
    ones."""
    INF = float("inf")
    ops = [dict(o) for o in ops if not o.get("discard")]
    for o in ops:
        if o["ret"] is None:
            o["ret"] = INF
    stale_reads = [o for o in ops
                   if o["kind"] == "r" and o.get("stale")]
    if stale_reads:
        writes = [o for o in ops if o["kind"] == "w"]
        for o in stale_reads:
            ok, why = _stale_read_ok(o, writes, init)
            if not ok:
                return False, why
        ops = [o for o in ops
               if not (o["kind"] == "r" and o.get("stale"))]
    n = len(ops)
    seen = set()

    def search(remaining: frozenset, state) -> bool:
        if not remaining:
            return True
        key = (remaining, state)
        if key in seen:
            return False
        seen.add(key)
        min_ret = min(ops[i]["ret"] for i in remaining)
        for i in sorted(remaining):
            o = ops[i]
            if o["call"] > min_ret:
                continue         # someone finished before i was called
            rest = remaining - {i}
            if o["kind"] == "w":
                if search(rest, o["val"]):
                    return True
                if o["ok"] is None and search(rest, state):
                    return True  # ambiguous write: never took effect
            else:
                if o["val"] == state and search(rest, state):
                    return True
        return False

    if search(frozenset(range(n)), init):
        return True, None
    # smallest offending read for the report
    reads = [o for o in ops if o["kind"] == "r"]
    return False, (f"no linearization of {n} ops "
                   f"({len(reads)} reads); history="
                   + json.dumps([[o['kind'], o['val'], o['call'],
                                  (None if o['ret'] == INF else o['ret'])]
                                 for o in ops], default=str)[:2000])


def check_stale_routes(deregs: List[dict],
                       holds: Dict[str, List[tuple]],
                       slo_s: float,
                       end_ts: float) -> Tuple[List[str], List[dict]]:
    """The no-stale-route invariant (ISSUE 19): once an instance
    deregisters at `ts`, every proxy whose config routed to it must
    stop holding that endpoint within `slo_s` seconds.

    deregs: [{"ts", "service", "address", "port"}] — catalog dereg
        apply times (instances never re-register the same
        address:port, so "cleared" is monotone).
    holds: {proxy_id: [(ts, {service: {(addr, port), ...}}), ...]} —
        every config a watcher RECEIVED, in arrival order: the proxy
        HOLDS holds[p][i] from its ts until the next entry's ts.
    end_ts: when observation stopped — a proxy still holding a dead
        endpoint then is judged on the time it held it.

    Returns (violations, lags): one lag row per (dereg, affected
    proxy) = {"proxy", "service", "address", "port", "lag_s",
    "cleared"}; a violation (and an `xds.stale_route` flight event)
    whenever lag_s exceeds the SLO.  Pure function over the correlated
    timeline — unit-testable without a live cluster."""
    from consul_tpu import flight
    violations: List[str] = []
    lags: List[dict] = []
    for d in deregs:
        ep = (d["address"], d["port"])
        svc = d["service"]
        for proxy_id, timeline in sorted(holds.items()):
            # the config the proxy held AT the dereg moment
            held_at = None
            for ts, cfg in timeline:
                if ts <= d["ts"]:
                    held_at = cfg
                else:
                    break
            if held_at is None or ep not in held_at.get(svc, set()):
                continue        # this proxy never routed to it
            cleared_ts = None
            for ts, cfg in timeline:
                if ts > d["ts"] and ep not in cfg.get(svc, set()):
                    cleared_ts = ts
                    break
            lag = (cleared_ts if cleared_ts is not None
                   else end_ts) - d["ts"]
            row = {"proxy": proxy_id, "service": svc,
                   "address": d["address"], "port": d["port"],
                   "lag_s": round(lag, 4),
                   "cleared": cleared_ts is not None}
            lags.append(row)
            if lag > slo_s or cleared_ts is None:
                violations.append(
                    f"stale route: proxy {proxy_id} held dead "
                    f"{svc}@{d['address']}:{d['port']} for "
                    f"{lag:.3f}s (slo {slo_s:.3f}s, "
                    f"cleared={cleared_ts is not None})")
                flight.emit("xds.stale_route",
                            labels={"proxy": proxy_id, "service": svc,
                                    "ms": round(lag * 1000.0, 1)})
    return violations, lags


# ---------------------------------------------------------------------------
# raft chaos harness (virtual time, bit-reproducible)
# ---------------------------------------------------------------------------


class RaftChaosHarness:
    """An in-process raft cluster stepped on virtual time under the
    nemesis, with the checkers wired to every step.

    The FSM is an append-log + register: each committed write appends
    its value to the node's `logs` entry and becomes the register
    value; snapshots carry the full log so crash-restart replays into
    the same sequence.  Reads are leader barriers (VerifyLeader): the
    value observed after the barrier commits is linearizable iff raft
    is — which is exactly what the checker verifies."""

    def __init__(self, n: int = 3, seed: int = 0,
                 data_root: Optional[str] = None,
                 config: Optional[RaftConfig] = None,
                 storage_factory: Optional[
                     Callable[[str], storage.StorageOps]] = None):
        self.seed = seed
        self.transport = InMemTransport(seed=seed)
        self.injector = LinkInjector(seed ^ 0x9E3779B9)
        self.transport.injector = self.injector
        self.cfg = config or RaftConfig()
        self.data_root = data_root
        self.durable = data_root is not None
        # per-node storage seam (FaultyStorage for the disk nemesis);
        # instances persist across crash/restart — their durable map
        # IS the node's disk
        self.storage_factory = storage_factory
        self._ios: Dict[str, storage.StorageOps] = {}
        self.ids = [f"n{i}" for i in range(n)]
        self.logs: Dict[str, list] = {nid: [] for nid in self.ids}
        self.value: Dict[str, Any] = {nid: None for nid in self.ids}
        self.alive: Dict[str, bool] = {nid: True for nid in self.ids}
        self.skew: Dict[str, float] = {nid: 0.0 for nid in self.ids}
        self.nodes: Dict[str, RaftNode] = {}
        for nid in self.ids:
            self.nodes[nid] = self._mk_node(nid)
        self.now = 0.0
        self.election = ElectionSafetyChecker()
        self.durability = DurabilityChecker()
        self.history = RegisterHistory()
        self._inflight: List[dict] = []
        self._next_val = 0

    # ------------------------------------------------------------ lifecycle

    def _mk_node(self, nid: str) -> RaftNode:
        store = None
        if self.durable:
            io = None
            if self.storage_factory is not None:
                if nid not in self._ios:
                    self._ios[nid] = self.storage_factory(nid)
                io = self._ios[nid]
            store = DurableLog(os.path.join(self.data_root, nid),
                               io=io)

        def apply_fn(cmd, nid=nid):
            v = cmd["v"]
            self.logs[nid].append(v)
            self.value[nid] = v
            return v

        def snapshot_fn(nid=nid):
            return {"log": list(self.logs[nid])}

        def restore_fn(data, nid=nid):
            self.logs[nid][:] = data["log"]
            self.value[nid] = self.logs[nid][-1] if self.logs[nid] else None

        node = RaftNode(nid, list(self.ids), self.transport, apply_fn,
                        snapshot_fn, restore_fn, config=self.cfg,
                        seed=self.seed, store=store)
        self.transport.register(node)
        return node

    def crash(self, nid: str) -> None:
        """kill -9: the node object drops, queued frames drop with it,
        and un-synced WAL bytes stay wherever the page cache left them
        (abort, not close — a real SIGKILL doesn't flush).  Under a
        FaultyStorage the crash also collapses the simulated page
        cache, tearing/losing whatever the fault schedule dictates;
        only durable bytes greet the restart."""
        from consul_tpu import flight
        flight.emit("chaos.fault.injected",
                    labels={"fault": "crash", "target": nid},
                    ts=self.now)
        node = self.nodes[nid]
        if node.store is not None:
            node.store.abort()
        io = self._ios.get(nid)
        if io is not None and hasattr(io, "crash"):
            io.crash()
        self.transport.unregister(nid)
        self.alive[nid] = False

    def restart(self, nid: str) -> None:
        """Boot from the durable log (crash recovery path)."""
        if not self.durable:
            raise RuntimeError("restart without a durable log would "
                               "forge raft persistent state")
        from consul_tpu import flight
        flight.emit("chaos.fault.healed",
                    labels={"fault": "crash", "target": nid},
                    ts=self.now)
        self.logs[nid].clear()
        self.value[nid] = None
        self.nodes[nid] = self._mk_node(nid)
        self.alive[nid] = True

    # ------------------------------------------------------------- stepping

    def step(self, seconds: float, dt: float = 0.01) -> None:
        end = self.now + seconds
        while self.now < end - 1e-9:
            self.now += dt
            self.transport.advance(self.now)
            for nid in self.ids:
                if self.alive[nid]:
                    self.nodes[nid].tick(self.now + self.skew[nid])
            self._reap()
            self.election.observe(
                n for nid, n in self.nodes.items() if self.alive[nid])
            self.durability.observe(
                {nid: log for nid, log in self.logs.items()
                 if self.alive[nid]})

    def _leader(self) -> Optional[RaftNode]:
        leaders = [n for nid, n in self.nodes.items()
                   if self.alive[nid] and n.is_leader()]
        if not leaders:
            return None
        return max(leaders, key=lambda n: n.current_term)

    # ------------------------------------------------------------- clients

    MAX_INFLIGHT = 4

    def do_write(self, deadline_s: float = 1.0) -> None:
        if len(self._inflight) >= self.MAX_INFLIGHT:
            return
        leader = self._leader()
        if leader is None:
            return
        val = self._next_val
        self._next_val += 1
        hid = self.history.invoke("w", val, self.now)
        try:
            pend = leader.apply({"v": val})
        except NotLeaderError:
            self.history.discard(hid)     # definite no-op
            return
        self._inflight.append({"hid": hid, "pend": pend, "kind": "w",
                               "val": val, "node": leader.node_id,
                               "deadline": self.now + deadline_s})

    def do_read(self, deadline_s: float = 1.0) -> None:
        if len(self._inflight) >= self.MAX_INFLIGHT:
            return
        leader = self._leader()
        if leader is None:
            return
        hid = self.history.invoke("r", None, self.now)
        try:
            pend = leader.barrier()
        except NotLeaderError:
            self.history.discard(hid)
            return
        self._inflight.append({"hid": hid, "pend": pend, "kind": "r",
                               "val": None, "node": leader.node_id,
                               "deadline": self.now + deadline_s})

    def _reap(self) -> None:
        still = []
        for item in self._inflight:
            pend = item["pend"]
            if pend.event.is_set():
                if pend.error is None:
                    if item["kind"] == "w":
                        self.history.complete(item["hid"], self.now)
                        self.durability.note_acked(item["val"])
                    else:
                        # barrier committed on this leader: its applied
                        # register value is the linearizable read
                        self.history.complete(item["hid"], self.now,
                                              self.value[item["node"]])
                elif item["kind"] == "w":
                    # deposed mid-flight: the entry may still commit
                    # under ANY later leader that kept it, so its
                    # linearization point is unbounded — ret stays
                    # open (a finite ret here would let the checker
                    # flag legal raft executions where the entry
                    # resurfaces after an intervening read)
                    self.history.ambiguous(item["hid"], None)
                else:
                    self.history.discard(item["hid"])
            elif self.now >= item["deadline"]:
                if item["kind"] == "w":
                    self.history.ambiguous(item["hid"], None)
                else:
                    self.history.discard(item["hid"])
            else:
                still.append(item)
        self._inflight = still

    # ---------------------------------------------------------------- check

    def settle(self, seconds: float = 1.5) -> None:
        """Fault-free tail: give the cluster time to re-elect, commit,
        and converge before the final checks."""
        self.injector.clear()
        self.transport.heal()
        self.skew = {nid: 0.0 for nid in self.ids}
        self.step(seconds)

    def violations(self, final: bool = True) -> List[str]:
        v = list(self.election.violations) + list(self.durability.violations)
        if final:
            live = [nid for nid in self.ids if self.alive[nid]]
            v += self.durability.final_check(self.logs, live)
            ok, why = check_linearizable(self.history.recorded())
            if not ok:
                v.append(f"linearizability: {why}")
        return v

    def digest_detail(self) -> dict:
        """Canonical end-state for the reproducibility digest."""
        return {
            "logs": {nid: self.logs[nid] for nid in self.ids},
            "acked": self.durability.acked,
            "ops": len(self.history.recorded()),
            "terms": max((n.current_term for n in self.nodes.values()),
                         default=0),
        }


# ---------------------------------------------------------------------------
# layer 3: SWIM chaos harness (device scans, host-side schedule)
# ---------------------------------------------------------------------------

_SWIM_COMPILED: dict = {}


def compiled_swim_run(params, ticks: int, monitor=None):
    """One jitted chunk runner per (params, ticks, monitor), returning
    swim.run's (state, trace) tuple.  The bare swim.run RETRACES its
    whole step graph on every call (~1-2 s of tracing each); this
    cache traces once per key — every scenario in a process shares the
    compilation (and the persistent XLA cache shares it across
    processes).  Tests with convergence loops use it too
    (tests/test_correlated_failures.py)."""
    key = (params, ticks, monitor)
    if key not in _SWIM_COMPILED:
        import jax

        from consul_tpu.models import swim as _swim
        _SWIM_COMPILED[key] = jax.jit(
            lambda st: _swim.run(params, st, ticks, monitor))
    return _SWIM_COMPILED[key]


class SwimChaosHarness:
    """The jitted SWIM pool under the nemesis: partition groups and
    per-node delivery multipliers live in SwimState (chaos_grp /
    chaos_ok), so the host evolves the fault schedule BETWEEN device
    scans without a single recompile.  `clean` tracks nodes the
    nemesis never touched — the invariant is that a clean, up, member
    node is NEVER committed dead (no committed death of a reachable
    live node)."""

    def __init__(self, seed: int, n: int = 128, slots: int = 16,
                 p_loss: float = 0.01, chunk: int = 50):
        import numpy as np

        from consul_tpu.config import GossipConfig, SimConfig
        from consul_tpu.models import swim
        self._np = np
        self._swim = swim
        self.seed = seed
        self.params = swim.make_params(
            GossipConfig.lan(),
            SimConfig(n_nodes=n, rumor_slots=slots, p_loss=p_loss,
                      seed=seed, chaos=True))
        self.state = swim.init_state(self.params)
        self.n = n
        self.chunk = chunk
        self.clean = np.ones(n, bool)
        self.crashed = np.zeros(n, bool)
        # sticky record of every node EVER committed dead — a later
        # rejoin clears the live flag, not the historical fact the
        # checkers assert on
        self.ever_committed = np.zeros(n, bool)
        self.violations: List[str] = []
        self._run = compiled_swim_run(self.params, chunk)

    # ------------------------------------------------------------ stepping

    def advance(self, ticks: int) -> None:
        for _ in range(max(1, math.ceil(ticks / self.chunk))):
            self.state = self._run(self.state)[0]
            self._check_clean()

    def _check_clean(self) -> None:
        np = self._np
        dead = np.asarray(self.state.committed_dead)
        committed = dead | np.asarray(self.state.committed_left)
        # flap feed: each NEWLY committed member journals one event —
        # O(changes) rows per chunk, stamped with the device tick so a
        # seeded scenario's timeline replays byte-identical
        new = committed & ~self.ever_committed
        if new.any():
            from consul_tpu import flight
            tick = int(self.state.tick)
            for i in np.flatnonzero(new):
                flight.emit(
                    "serf.member.flap",
                    labels={"node": f"node{int(i)}",
                            "status": "failed" if dead[i] else "left",
                            "tick": tick},
                    ts=float(tick))
        self.ever_committed |= committed
        bad = committed & self.clean & np.asarray(self.state.up) \
            & np.asarray(self.state.member)
        if bad.any():
            ids = np.flatnonzero(bad)[:8].tolist()
            self.violations.append(
                f"swim: reachable live nodes {ids} committed dead/left "
                f"at tick {int(self.state.tick)}")
            self.clean[bad] = False       # report each node once

    # -------------------------------------------------------------- faults

    def partition(self, mask) -> None:
        """Split the pool: mask nodes into group 1 (unreachable from
        group 0).  Masked nodes may legitimately be declared dead by
        the majority, so they leave the clean set."""
        np, jnp = self._np, _jnp()
        mask = np.asarray(mask, bool)
        self.clean &= ~mask
        self._journal("chaos.fault.injected", "partition",
                      f"{int(mask.sum())}nodes")
        self.state = self.state.replace(
            chaos_grp=jnp.asarray(mask.astype(np.int16)))

    def heal_partition(self) -> None:
        jnp = _jnp()
        self._journal("chaos.fault.healed", "partition", "*")
        self.state = self.state.replace(
            chaos_grp=jnp.zeros((self.n,), jnp.int16))

    def crash(self, mask) -> None:
        np = self._np
        mask = np.asarray(mask, bool)
        self.clean &= ~mask
        self.crashed |= mask
        self._journal("chaos.fault.injected", "crash",
                      f"{int(mask.sum())}nodes")
        self.state = self._swim.kill_mask(self.state, _jnp().asarray(mask))

    def flap_revive(self, mask) -> None:
        """Restart crashed nodes inside the suspicion/dissemination
        window — the satellite path: they rejoin with a bumped
        incarnation so stale death rumors can't re-commit them."""
        np = self._np
        mask = np.asarray(mask, bool)
        self.crashed &= ~mask
        self._journal("chaos.fault.healed", "crash",
                      f"{int(mask.sum())}nodes")
        self.state = self._swim.revive_mask(self.state,
                                            _jnp().asarray(mask))

    def degrade(self, mask, ok: float) -> None:
        """Asymmetric local degradation (Lifeguard's bad-NIC): masked
        nodes deliver each of THEIR legs at rate `ok`."""
        np, jnp = self._np, _jnp()
        mask = np.asarray(mask, bool)
        self._journal("chaos.fault.injected", "degrade",
                      f"{int(mask.sum())}nodes@{ok}")
        cur = np.array(self.state.chaos_ok)      # writable host copy
        cur[mask] = ok
        self.state = self.state.replace(chaos_ok=jnp.asarray(cur))

    def loss_burst(self, p: float) -> None:
        """Symmetric loss burst: every leg delivers at (1-p) on top of
        the baseline — realized as a global per-node multiplier of
        sqrt(1-p) (a leg pays both endpoints)."""
        jnp = _jnp()
        self._journal("chaos.fault.injected", "loss", f"p={p}")
        self.state = self.state.replace(
            chaos_ok=jnp.full((self.n,), math.sqrt(max(0.0, 1.0 - p)),
                              jnp.float32))

    def calm(self) -> None:
        jnp = _jnp()
        self._journal("chaos.fault.healed", "loss", "*")
        self.state = self.state.replace(
            chaos_ok=jnp.ones((self.n,), jnp.float32))

    def _journal(self, name: str, fault: str, target: str) -> None:
        """One correlated flight-recorder row per injected fault,
        stamped with the device tick (deterministic)."""
        from consul_tpu import flight
        tick = int(self.state.tick)
        flight.emit(name, labels={"fault": fault, "target": target,
                                  "tick": tick}, ts=float(tick))

    # --------------------------------------------------------------- checks

    def rejoin_committed(self) -> int:
        """Operator rejoin for every UP node the cluster declared dead
        — committed, or carrying an active dead rumor (post-heal
        reconciliation: a real agent that hears itself declared dead
        rejoins with a bumped incarnation, serf snapshot rejoin).  The
        sim has no alive-refutes-dead channel (memberlist aliveNode on
        a dead entry), so this host sweep IS that mechanism."""
        np = self._np
        declared = np.asarray(self.state.committed_dead).copy()
        r_active = np.asarray(self.state.r_active)
        r_kind = np.asarray(self.state.r_kind)
        r_subject = np.asarray(self.state.r_subject)
        dead_rumor = r_active & (r_kind == self._swim.DEAD)
        declared[r_subject[dead_rumor]] = True
        up = np.asarray(self.state.up) & np.asarray(self.state.member)
        todo = np.flatnonzero(declared & up)
        for node in todo:
            self.state = self._swim.rejoin(self.params, self.state,
                                           int(node))
        return len(todo)

    def check_not_committed(self, mask, label: str) -> None:
        np = self._np
        bad = self.ever_committed & np.asarray(mask, bool)
        if bad.any():
            self.violations.append(
                f"swim: {label}: nodes {np.flatnonzero(bad)[:8].tolist()} "
                f"were committed dead")

    def reconverge(self, budget_ticks: int,
                   label: str = "reconverge") -> dict:
        """After heal: within `budget_ticks` every still-crashed node
        must be cluster-detected and NO live member may remain
        believed-down.  Each chunk runs the rejoin sweep — live nodes
        that discover they were declared dead during the fault window
        rejoin, exactly as their agents would."""
        victims = _jnp().asarray(self.crashed)
        recall, fp = 0.0, -1
        spent = 0
        while spent < budget_ticks:
            self.advance(self.chunk)
            spent += self.chunk
            self.rejoin_committed()
            recall, fp = self._swim.mass_detection_stats(
                self.params, self.state, victims)
            recall, fp = float(recall), int(fp)
            if (not self.crashed.any() or recall >= 0.999) and fp == 0:
                return {"recall": recall, "false_positives": fp,
                        "ticks": spent}
        self.violations.append(
            f"swim: {label}: no re-convergence within {budget_ticks} "
            f"ticks (recall={recall}, believed-down live nodes={fp})")
        return {"recall": recall, "false_positives": fp, "ticks": spent}

    def digest_detail(self) -> dict:
        np = self._np
        return {
            "tick": int(self.state.tick),
            "committed_dead": np.flatnonzero(
                np.asarray(self.state.committed_dead)).tolist(),
            "incarnation_sum": int(np.asarray(
                self.state.incarnation).sum()),
        }


def _jnp():
    import jax.numpy as jnp
    return jnp


# ---------------------------------------------------------------------------
# scenarios
# ---------------------------------------------------------------------------


def _drive(h: RaftChaosHarness, seconds: float, write_every: float = 0.06,
           read_every: float = 0.17, dt: float = 0.01) -> None:
    """Step the raft harness while issuing a deterministic client
    schedule of writes + barrier reads."""
    end = h.now + seconds
    next_w = h.now + write_every
    next_r = h.now + read_every
    while h.now < end - 1e-9:
        if h.now >= next_w:
            h.do_write()
            next_w += write_every
        if h.now >= next_r:
            h.do_read()
            next_r += read_every
        h.step(dt, dt)


def _report(name: str, seed: int, violations: List[str],
            detail: dict) -> dict:
    digest = hashlib.sha256(
        json.dumps(detail, sort_keys=True, default=str).encode()
    ).hexdigest()[:16]
    return {
        "scenario": name, "seed": seed, "ok": not violations,
        "violations": violations, "digest": digest, "detail": detail,
        "repro": f"python tools/chaos_soak.py --seed {seed} "
                 f"--scenario {name}",
    }


def scenario_partition_heal(seed: int, tmp: Optional[str] = None,
                            soak: bool = False) -> dict:
    """Partition both layers, write through it, heal, reconverge.

    Raft: a 5-node cluster loses {old leader, one follower} to a
    minority partition mid-traffic; the majority elects and serves;
    heal; every acked write must survive and histories linearize.
    SWIM: 25% of the pool splits off long enough for the majority to
    COMMIT the minority's deaths; on heal the committed-but-alive
    nodes rejoin with bumped incarnations and the pool reconverges."""
    h = RaftChaosHarness(n=5, seed=seed)
    h.step(1.0)                                  # elect
    _drive(h, 1.0)
    leader = h._leader()
    minority = [leader.node_id if leader else h.ids[0]]
    minority.append(next(i for i in h.ids if i not in minority))
    majority = [i for i in h.ids if i not in minority]
    for a in minority:
        for b in majority:
            h.transport.partition(a, b)
    _drive(h, 2.0 if soak else 1.5)
    h.transport.heal()
    _drive(h, 1.5)
    h.settle()
    violations = h.violations()
    detail = {"raft": h.digest_detail(), "minority": minority}

    sw = SwimChaosHarness(seed, n=256 if soak else 128)
    sw.advance(50)                               # settle the pool
    np = sw._np
    mask = np.arange(sw.n) % 4 == 3              # deterministic 25%
    sw.partition(mask)
    p = sw.params
    # long enough for the majority to commit minority deaths: timer +
    # declare lag + the 4x coverage-capped slot lifetime, with slack
    sw.advance(p.suspicion_max_ticks + p.declare_lag_ticks
               + 6 * p.expiry_gossip_ticks)
    sw.heal_partition()
    rejoined = sw.rejoin_committed()
    rec = sw.reconverge(4000, "partition_heal")
    violations += sw.violations
    detail["swim"] = dict(sw.digest_detail(), rejoined=rejoined, **rec)
    return _report("partition_heal", seed, violations, detail)


def scenario_crash_restart(seed: int, tmp: Optional[str] = None,
                           soak: bool = False) -> dict:
    """Crash + restart-from-durable-log on raft; kill_mask + flap
    revive on SWIM (the incarnation-bump satellite path)."""
    import tempfile
    with tempfile.TemporaryDirectory(dir=tmp) as d:
        h = RaftChaosHarness(n=3, seed=seed, data_root=d)
        h.step(1.0)
        _drive(h, 1.0)
        follower = next(i for i in h.ids
                        if not h.nodes[i].is_leader())
        h.crash(follower)
        _drive(h, 1.0)
        h.restart(follower)
        _drive(h, 1.0)
        leader = h._leader()
        if leader is not None:
            h.crash(leader.node_id)
            _drive(h, 1.5)                      # re-elect + serve
            h.restart(leader.node_id)
        _drive(h, 1.0)
        h.settle()
        violations = h.violations()
        detail = {"raft": h.digest_detail()}

    sw = SwimChaosHarness(seed, n=256 if soak else 128)
    sw.advance(50)
    np = sw._np
    rng = np.random.default_rng(seed)
    victims = rng.choice(sw.n, size=10, replace=False)
    mask = np.zeros(sw.n, bool)
    mask[victims] = True
    sw.crash(mask)
    # let suspicions get airborne (timers started, rumors circulating)
    # but flap BEFORE the suspicion timeout can expire into commits —
    # one chunk (50 ticks) sits inside the ~sus_min+lag window
    sw.advance(sw.chunk)
    revived = np.zeros(sw.n, bool)
    revived[victims[:5]] = True
    sw.flap_revive(revived)
    rec = sw.reconverge(6000, "crash_restart")
    sw.check_not_committed(revived, "flap-revived nodes")
    violations += sw.violations
    detail["swim"] = dict(sw.digest_detail(), **rec)
    return _report("crash_restart", seed, violations, detail)


def scenario_loss_burst(seed: int, tmp: Optional[str] = None,
                        soak: bool = False) -> dict:
    """Symmetric lossy window on both layers.  Loss alone must never
    commit a death (Lifeguard refutation + coverage-guarded commit):
    the SWIM side asserts ZERO committed deaths throughout."""
    h = RaftChaosHarness(n=3, seed=seed)
    h.step(1.0)
    _drive(h, 0.8)
    h.injector.set_default(drop_p=0.35)
    _drive(h, 2.0 if soak else 1.2)
    h.injector.clear()
    _drive(h, 1.0)
    h.settle()
    violations = h.violations()
    detail = {"raft": h.digest_detail()}

    sw = SwimChaosHarness(seed, n=256 if soak else 128)
    sw.advance(50)
    sw.loss_burst(0.30)
    sw.advance(sw.params.suspicion_max_ticks * (2 if soak else 1))
    sw.calm()
    sw.advance(500)
    np = sw._np
    n_committed = int(np.asarray(sw.state.committed_dead).sum())
    if n_committed:
        sw.violations.append(
            f"swim: loss burst committed {n_committed} deaths with "
            f"zero crashes")
    violations += sw.violations
    detail["swim"] = dict(sw.digest_detail(), committed=n_committed)
    return _report("loss_burst", seed, violations, detail)


def scenario_asym_degradation(seed: int, tmp: Optional[str] = None,
                              soak: bool = False) -> dict:
    """Lifeguard's motivating fault: a few nodes with a degraded NIC.
    Raft: one node's OUTBOUND links drop 50% (asymmetric).  SWIM: 10%
    of nodes deliver their legs at 55% — they must neither be
    committed dead (they are up and refute) nor poison the pool."""
    h = RaftChaosHarness(n=3, seed=seed)
    h.step(1.0)
    _drive(h, 0.8)
    h.injector.set_link(h.ids[0], None, drop_p=0.5)
    _drive(h, 2.0 if soak else 1.2)
    h.injector.clear()
    _drive(h, 0.8)
    h.settle()
    violations = h.violations()
    detail = {"raft": h.digest_detail()}

    sw = SwimChaosHarness(seed, n=256 if soak else 128)
    sw.advance(50)
    np = sw._np
    degraded = np.arange(sw.n) % 10 == 5         # deterministic 10%
    sw.degrade(degraded, 0.55)
    sw.advance(sw.params.suspicion_max_ticks)
    sw.calm()
    sw.advance(800)
    sw.check_not_committed(degraded, "degraded-but-live nodes")
    n_committed = int(np.asarray(sw.state.committed_dead).sum())
    if n_committed:
        sw.violations.append(
            f"swim: degradation committed {n_committed} deaths with "
            f"zero crashes")
    violations += sw.violations
    detail["swim"] = dict(sw.digest_detail(),
                          degraded=int(degraded.sum()))
    return _report("asym_degradation", seed, violations, detail)


def scenario_clock_skew(seed: int, tmp: Optional[str] = None,
                        soak: bool = False) -> dict:
    """Per-node clock skew on the raft layer: one node runs 150 ms
    ahead, one 100 ms behind, and the offsets JUMP mid-run (an NTP
    step).  Elections churn; safety and linearizability must not."""
    h = RaftChaosHarness(n=3, seed=seed)
    h.step(1.0)
    _drive(h, 0.8)
    h.skew = {"n0": 0.15, "n1": -0.10, "n2": 0.0}
    _drive(h, 1.5 if soak else 1.0)
    # NTP step: n1 jumps > election_timeout forward (it fires an
    # immediate pre-vote), n0 steps BACKWARD (its timers stall until
    # its clock catches back up)
    h.skew = {"n0": -0.30, "n1": 0.45, "n2": 0.05}
    _drive(h, 1.5 if soak else 1.0)
    h.settle()
    violations = h.violations()
    return _report("clock_skew", seed, violations,
                   {"raft": h.digest_detail()})


def scenario_link_chaos(seed: int, tmp: Optional[str] = None,
                        soak: bool = False) -> dict:
    """Message-level chaos on every raft link: variable delays (which
    ARE reordering), duplication, and light loss, all at once."""
    h = RaftChaosHarness(n=3, seed=seed)
    h.step(1.0)
    _drive(h, 0.8)
    h.injector.set_default(drop_p=0.1, delay_p=0.5,
                           delay=(0.01, 0.06), dup_p=0.3)
    _drive(h, 2.5 if soak else 1.5)
    h.injector.clear()
    _drive(h, 0.8)
    h.settle()
    return _report("link_chaos", seed, h.violations(),
                   {"raft": h.digest_detail()})


def scenario_tcp_flaky(seed: int, tmp: Optional[str] = None,
                       soak: bool = False) -> dict:
    """Layer 2: a live socket cluster under the NetFaultSchedule —
    severed pooled connections and head-of-line delays while writes
    forward through followers.  Wall-clock (sockets + threads), so the
    INVARIANT here is end-state: every acked write is readable after
    the faults calm, and replicas agree.  The seeded schedule makes
    the fault stream reproducible; thread interleaving is the OS's."""
    import threading
    import time as wall

    from consul_tpu.rpc import FaultyTcpTransport, NetFaultSchedule
    from consul_tpu.server import Server

    faults = NetFaultSchedule(seed)
    addresses: Dict[str, Tuple[str, int]] = {}
    ids = [f"s{i}" for i in range(3)]
    servers = []
    for nid in ids:
        transport = FaultyTcpTransport(faults, addresses=addresses)
        srv = Server(nid, list(ids), transport, registry={}, seed=seed)
        srv.serve_rpc()
        servers.append(srv)
    stop = threading.Event()

    def pump():
        while not stop.is_set():
            for s in servers:
                s.tick(wall.time())
            wall.sleep(0.01)

    t = threading.Thread(target=pump, daemon=True)
    t.start()
    acked: List[str] = []
    violations: List[str] = []
    try:
        deadline = wall.time() + 20.0
        while wall.time() < deadline:
            if any(s.is_leader() for s in servers):
                break
            wall.sleep(0.05)
        else:
            violations.append("tcp: no leader elected")
        follower = next((s for s in servers if not s.is_leader()),
                        servers[0])
        for i in range(10):
            if i == 3:
                faults.drop_p, faults.sever_p, faults.delay_p = \
                    0.15, 0.1, 0.3
            if i == 7:
                faults.calm()
            try:
                ok, _ = follower.kv_set(f"chaos/{i}", f"v{i}".encode())
                if ok:
                    acked.append(f"chaos/{i}")
            except Exception:
                pass          # unacked under faults: no durability claim
        faults.calm()
        wall.sleep(0.5)
        leader = next((s for s in servers if s.is_leader()), None)
        if leader is None:
            violations.append("tcp: no leader after calm")
        else:
            for key in acked:
                row = leader.store.kv_get(key)
                if row is None:
                    violations.append(f"tcp: acked write {key} lost")
    finally:
        stop.set()
        t.join(timeout=2.0)
        for s in servers:
            s.close_rpc()
    return _report("tcp_flaky", seed, violations,
                   {"acked": len(acked)})


# ------------------------------------------------------- storage nemesis


def scenario_crash_matrix(seed: int, tmp: Optional[str] = None,
                          soak: bool = False) -> dict:
    """The exhaustive cut: crash at EVERY I/O boundary of a seeded
    write/compact/snapshot/restart trace (clean cuts — the page cache
    drains nothing extra) and prove recovery at each one."""
    res = run_crash_matrix(seed, steps=36 if soak else 18, torn=False,
                           tmp=tmp, rewrite_threshold=12)
    detail = {k: res[k] for k in ("boundaries", "cells", "op_kinds",
                                  "digest")}
    return _report("crash_matrix", seed, res["violations"], detail)


def scenario_disk_torn(seed: int, tmp: Optional[str] = None,
                       soak: bool = False) -> dict:
    """Torn writes: every crash keeps a seeded partial prefix of the
    unsynced tail.  Layer 0 runs the full boundary matrix under the
    torn model; then a raft cluster on torn disks eats a follower and
    a leader kill -9 — every acked write must survive and histories
    must linearize (fsync-before-ack is the property under test)."""
    res = run_crash_matrix(seed, steps=30 if soak else 16, torn=True,
                           tmp=tmp, rewrite_threshold=12)
    violations = list(res["violations"])
    detail: dict = {"matrix": {k: res[k] for k in
                               ("boundaries", "cells", "digest")}}
    with tempfile.TemporaryDirectory(dir=tmp) as d:
        h = RaftChaosHarness(
            n=3, seed=seed, data_root=d,
            storage_factory=lambda nid: FaultyStorage(
                seed ^ zlib.crc32(nid.encode()), torn=True))
        h.step(1.0)
        _drive(h, 0.8)
        follower = next(i for i in h.ids
                        if not h.nodes[i].is_leader())
        h.crash(follower)
        _drive(h, 0.8)
        h.restart(follower)
        _drive(h, 0.8)
        leader = h._leader()
        if leader is not None:
            h.crash(leader.node_id)
            _drive(h, 1.2)
            h.restart(leader.node_id)
        _drive(h, 0.8)
        h.settle()
        violations += h.violations()
        detail["raft"] = h.digest_detail()
    return _report("disk_torn", seed, violations, detail)


def scenario_fsync_lost(seed: int, tmp: Optional[str] = None,
                        soak: bool = False) -> dict:
    """A lying disk: fsync returns success without persisting.  No WAL
    can keep the durability promise on such hardware — what MUST still
    hold is prefix consistency: recovery yields a clean, checksummed
    prefix of the honestly-acked records (the floor from before the
    lies began), never a hole, a reorder, or garbage."""
    with tempfile.TemporaryDirectory(dir=tmp) as d:
        fs = FaultyStorage(seed, torn=True)
        model = WalModel()
        log = DurableLog(d, rewrite_threshold=999, io=fs)
        log.load()
        model.begin_meta(1, None)
        log.set_term_vote(1, None)
        model.ack_meta()
        idx = 1
        for i in range(12 if soak else 8):
            model.note_entry(idx, 1, f"v{idx}")
            log.append(idx, 1, f"v{idx}")
            idx += 1
            if i % 2:
                log.sync()
                model.ack_wal()
        log.sync()
        model.ack_wal()
        honest_floor = model.acked
        fs.lose_next_fsyncs = 10 ** 9
        for _ in range(10 if soak else 6):
            model.note_entry(idx, 2, f"v{idx}")
            log.append(idx, 2, f"v{idx}")
            idx += 1
            log.sync()          # the node believes this acked; it lied
        log.abort()
        fs.crash()
        rec = DurableLog(d)
        st = rec.load()
        rec.close()
        violations = check_wal_recovery(st, model)
        detail = {"honest_floor": honest_floor,
                  "written": len(model.records),
                  "recovered_top": max(st["entries"], default=0)
                  if st else 0,
                  "recovery": st["recovery"] if st else None}
    return _report("fsync_lost", seed, violations, detail)


def scenario_enospc(seed: int, tmp: Optional[str] = None,
                    soak: bool = False) -> dict:
    """Disk full: appends and term/vote writes fail loudly (never
    acked, never clobbering what's there), a compaction whose WAL
    rewrite hits ENOSPC mid-stream abandons the rewrite and keeps the
    old WAL complete, and after space returns everything acked — on
    both sides of the outage — survives a crash."""
    with tempfile.TemporaryDirectory(dir=tmp) as d:
        fs = FaultyStorage(seed)
        model = WalModel()
        log = DurableLog(d, rewrite_threshold=6, io=fs)
        log.load()
        model.begin_meta(1, None)
        log.set_term_vote(1, None)
        model.ack_meta()
        idx = 1
        failures = 0

        def put(n: int, term: int) -> None:
            nonlocal idx, failures
            for _ in range(n):
                model.note_entry(idx, term, f"v{idx}")
                try:
                    log.append(idx, term, f"v{idx}")
                except OSError:
                    model.rollback_record()
                    failures += 1
                    continue
                idx += 1
            log.sync()
            model.ack_wal()

        put(8, 1)
        fs.enospc = True
        put(4, 1)                       # all fail; none acked
        model.begin_meta(2, "n1")
        try:
            log.set_term_vote(2, "n1")  # must fail without clobbering
        except OSError:
            failures += 1
        else:
            model.ack_meta()
        fs.enospc = False
        put(6 if soak else 4, 1)
        # compaction whose rewrite runs out of disk mid-stream: the
        # snap + base record land (2 writes), the rewrite's first
        # write trips ENOSPC — old WAL must stay complete
        snap_idx = idx - 3
        nbase = snap_idx - 1
        data = {"log": [f"v{i}" for i in range(1, snap_idx + 1)]}
        live = {i: (1, f"v{i}", False) for i in range(nbase + 1, idx)}
        model.begin_snap(snap_idx, 1, data)
        model.note_base(nbase, 1)
        model.begin_rewrite([("base", nbase, 1)]
                            + [("e", i, *live[i]) for i in sorted(live)
                               if i > nbase])
        fs.enospc_after_writes = 2
        res = log.save_snapshot(snap_idx, 1, data, live, base=nbase,
                                base_term=1)
        rewrite_survived = not res["rewrote"]
        model.ack_snap()
        model.ack_wal()
        model.end_rewrite(res["rewrote"])
        fs.enospc = False
        fs.enospc_after_writes = None
        put(4, 1)
        log.abort()
        fs.crash()
        rec = DurableLog(d)
        st = rec.load()
        rec.close()
        violations = check_wal_recovery(st, model)
        if not failures:
            violations.append("enospc: no write ever failed — the "
                              "fault was not injected")
        if not rewrite_survived:
            violations.append("enospc: WAL rewrite claimed success "
                              "on a full disk")
        detail = {"failures": failures, "acked": model.acked,
                  "recovered_top": max(st["entries"], default=0)
                  if st else 0}
    return _report("enospc", seed, violations, detail)


def scenario_bit_rot(seed: int, tmp: Optional[str] = None,
                     soak: bool = False) -> dict:
    """One flipped bit in wal.log, snap.json, or meta.json.  The CRC
    layer must DETECT every flip (never replay rot as committed
    state): the WAL quarantines at exactly the bad frame, the checked
    files fall back one generation — and in every case recovery still
    equals a legal prefix of what was written."""
    from consul_tpu.consensus.logstore import PersistentStateCorruptError
    violations: List[str] = []
    detail: dict = {}
    for target, relax in (("wal.log", "wal"), ("snap.json", "snap"),
                          ("meta.json", "meta")):
        with tempfile.TemporaryDirectory(dir=tmp) as d:
            fs = FaultyStorage(seed ^ zlib.crc32(target.encode()),
                               corrupt_on_crash=(target,))
            model = WalModel()
            log = DurableLog(d, rewrite_threshold=999, io=fs)
            log.load()
            for t, v in ((1, None), (2, "n1")):   # meta.prev exists
                model.begin_meta(t, v)
                log.set_term_vote(t, v)
                model.ack_meta()
            idx = 1
            for _ in range(10):
                model.note_entry(idx, 2, f"v{idx}")
                log.append(idx, 2, f"v{idx}")
                idx += 1
            log.sync()
            model.ack_wal()
            # two compactions so snap.prev exists AND its fallback
            # still meets the surviving base (no applied-state hole)
            for snap_idx, nbase in ((6, 6), (8, 6)):
                data = {"log": [f"v{i}"
                                for i in range(1, snap_idx + 1)]}
                live = {i: (2, f"v{i}", False)
                        for i in range(nbase + 1, idx)}
                model.begin_snap(snap_idx, 2, data)
                model.note_base(nbase, 2)
                log.save_snapshot(snap_idx, 2, data, live, base=nbase,
                                  base_term=2)
                model.ack_snap()
                model.ack_wal()
            for _ in range(4):
                model.note_entry(idx, 2, f"v{idx}")
                log.append(idx, 2, f"v{idx}")
                idx += 1
            log.sync()
            model.ack_wal()
            log.abort()
            fs.crash()
            rec = DurableLog(d)
            refused = False
            try:
                st = rec.load()
            except PersistentStateCorruptError:
                # rotted term/vote: fail-stop IS the safe outcome —
                # rewinding a vote could elect two leaders in one term
                st = None
                refused = True
            rec.close()
            if target == "meta.json":
                detected = refused
                if not refused:
                    violations.append(
                        "[meta.json] rotted term/vote did NOT fail "
                        "stop — a rewound vote can double-vote "
                        f"(flips={fs.flips})")
            else:
                violations += [f"[{target}] {v}" for v in
                               check_wal_recovery(st, model,
                                                  lenient=frozenset(
                                                      (relax,)))]
                r = st["recovery"] if st else {}
                detected = {
                    "wal.log": r.get("corrupt_frame", 0)
                    + r.get("torn_tail", 0) >= 1,
                    "snap.json": r.get("snap_fallback")
                    or r.get("snap_lost"),
                }[target]
                if not detected:
                    violations.append(
                        f"[{target}] bit rot was NOT detected — "
                        f"corruption replayed silently "
                        f"(flips={fs.flips})")
            detail[target] = {"flips": fs.flips, "refused": refused,
                              "recovered_top": max(st["entries"],
                                                   default=0)
                              if st else 0}
    return _report("bit_rot", seed, violations, detail)


SCENARIOS = {
    "partition_heal": scenario_partition_heal,
    "crash_restart": scenario_crash_restart,
    "loss_burst": scenario_loss_burst,
    "asym_degradation": scenario_asym_degradation,
    "clock_skew": scenario_clock_skew,
    "link_chaos": scenario_link_chaos,
    "tcp_flaky": scenario_tcp_flaky,
    "crash_matrix": scenario_crash_matrix,
    "disk_torn": scenario_disk_torn,
    "fsync_lost": scenario_fsync_lost,
    "bit_rot": scenario_bit_rot,
    "enospc": scenario_enospc,
}

# the fixed-seed tier-1 smoke set: every virtual-time scenario (the
# wall-clock tcp_flaky rides the full soak, its transport is unit-
# tested in tests/test_chaos.py), plus the bounded storage-nemesis
# smoke — small traces, every boundary of them
CHECK_SCENARIOS = ("partition_heal", "crash_restart", "loss_burst",
                   "asym_degradation", "clock_skew", "link_chaos",
                   "crash_matrix", "disk_torn", "fsync_lost",
                   "bit_rot", "enospc")


def run_scenario(name: str, seed: int, tmp: Optional[str] = None,
                 soak: bool = False, recorder=None) -> dict:
    """Run one scenario under a scoped flight recorder and attach its
    timeline to the report (`"events"`: JSON lines, one row per
    injected fault / flap / election / recovery event).

    The default recorder uses a CONSTANT clock and no log fan-out, so
    every row's timestamp comes from the emitters' own virtual clocks
    (raft `now`, device tick) — a seeded run's timeline is
    byte-identical across replays, which `chaos_soak --check` asserts.
    Pass `recorder=flight.default_recorder()` to journal into the
    process ring instead (the /v1/agent/events + debug-bundle path)."""
    from consul_tpu import flight
    rec = recorder if recorder is not None else flight.FlightRecorder(
        clock=lambda: 0.0, forward_to_log=False)
    with flight.use(rec):
        row = SCENARIOS[name](seed, tmp=tmp, soak=soak)
    row["events"] = rec.dump_jsonl().decode()
    return row
