"""EventPublisher: topic-keyed event fan-out with snapshot-then-follow.

The server side of the reference's streaming read path
(agent/consul/stream/event_publisher.go:12 EventPublisher;
stream/subscription.go:32 Subscription; wiring agent/consul/server.go:637-645).
Store commits publish typed events onto topics; subscribers get a snapshot
of current state followed by the live event stream from the snapshot index,
so a materialized view (consul_tpu/submatview.py) can serve blocking reads
without re-running the full query per wakeup.

Design differences from the reference (deliberate, host-side Python):
  * topics are (topic, key) pairs — e.g. ("health", "web") — matching how
    the reference scopes Subscribe requests by Topic+Key
    (proto/pbsubscribe/subscribe.proto:14,34);
  * the per-topic buffer is a bounded deque of (index, events) batches; a
    subscriber that falls off the tail gets a NewSnapshotToFollow-style
    reset, like the reference's snapshot cache eviction;
  * no gRPC framing — in-process subscriptions are iterators; the HTTP
    layer exposes them as long-polls and the RPC layer as streamed frames.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from consul_tpu import locks

# Topic names (reference pbsubscribe topics + the memdb tables that feed
# blocking queries; state/schema.go:10).
TOPIC_KV = "kv"
TOPIC_SERVICE_HEALTH = "health"        # key = service name
TOPIC_CATALOG_NODES = "nodes"          # key = node name ("" = any)
TOPIC_CATALOG_SERVICES = "services"    # key = service name ("" = any)
TOPIC_SESSIONS = "sessions"
TOPIC_ACL = "acl"
TOPIC_INTENTIONS = "intentions"
TOPIC_CONFIG = "config"                # config entries
TOPIC_COORDINATES = "coordinates"
TOPIC_QUERIES = "queries"              # prepared queries
TOPIC_CA = "ca"                        # connect CA roots/leaf rotation


@dataclass(frozen=True)
class Event:
    """One state-change event (stream/event_publisher.go Event shape).

    `trace_id` is the PROPOSING request's trace (commit-to-visibility
    correlation, consul_tpu/visibility.py) — observability metadata,
    empty for replicated/untraced writes; never part of equality-
    relevant state."""

    topic: str
    key: str
    index: int
    payload: Any = None
    op: str = "update"          # update | delete | snapshot-end
    trace_id: str = field(default="", compare=False)


class SnapshotRequired(Exception):
    """Raised to a follower that fell off the buffer tail: re-snapshot.

    Mirrors the reference's NewSnapshotToFollow reset frame
    (stream/subscription.go forceClose on buffer eviction)."""


# a subscriber queue backing up past this many undrained batches is
# SLOW: flagged during publish, journaled (stream.subscriber.slow)
# when its consumer finally drains — the per-subscriber tripwire for
# ROADMAP item 2's 1M-watcher fan-out
SLOW_QUEUE_DEPTH = 128

# the per-subscriber buffer BOUND (ISSUE 13): a queue that reaches
# this many undrained batches IS sustained lag — the subscriber is
# evicted (closed; its consumer gets a SnapshotRequired reset and must
# re-snapshot), journaled as stream.subscriber.evicted.  Eviction
# happens strictly before the bound would silently drop a batch, so
# delivered streams are never holey — a consumer either sees every
# batch or sees the reset.  This is the contract that lets 10k wedged
# watchers cost the publisher nothing after their bound fills
# (tests/test_overload.py).
MAX_SUB_QUEUE = 1024


@dataclass
class _Sub:
    topic: str
    key: Optional[str]                 # None = all keys on the topic
    next_index: int
    cond: threading.Condition
    closed: bool = False
    # bounded by construction (the bounded-queue lint rule); the
    # publisher evicts at maxlen-1 so the deque's own drop-oldest
    # behavior is a dead backstop, never a silent data loss
    queue: deque = field(
        default_factory=lambda: deque(maxlen=MAX_SUB_QUEUE))
    slow_depth: int = 0                # max depth seen while backed up
    evicted: bool = False
    # optional SHARED wakeup: a consumer selecting over MANY subs
    # (proxycfg's per-proxy follower) attaches one Event to all of
    # them and parks on that instead of serially blocking per-sub
    wake: Optional[threading.Event] = None


class Subscription:
    """Iterator over events for one (topic, key) from a start index.

    `events(timeout)` blocks for the next batch; raises SnapshotRequired
    if the publisher evicted history the subscriber still needed."""

    def __init__(self, pub: "EventPublisher", sub: _Sub):
        self._pub = pub
        self._sub = sub

    def events(self, timeout: float = 300.0) -> List[Event]:
        s = self._sub
        with s.cond:
            if not s.queue and not s.closed:
                s.cond.wait(timeout)
            closed, evicted = s.closed, s.evicted
        if closed:
            # the reset drain is a consumer-side flush point too: an
            # eviction staged during publish must reach the flight
            # ring even when the EVICTED consumer is the only one
            # still draining (no healthy sub left to flush it)
            self._pub._flush_stats()
            raise SnapshotRequired(
                "subscriber evicted after sustained lag"
                if evicted else "subscription reset")
        with s.cond:
            out: List[Event] = []
            depth = len(s.queue)
            while s.queue:
                out.extend(s.queue.popleft())
            slow_depth, s.slow_depth = s.slow_depth, 0
        # telemetry on the CONSUMER's thread, after releasing the sub
        # condition (publish() runs under the store lock and stages
        # only; this drain is where the stream plane may emit)
        if out:
            from consul_tpu import telemetry
            telemetry.add_sample(("stream", "queue_depth"),
                                 float(depth),
                                 labels={"topic": s.topic})
            telemetry.incr_counter(("stream", "delivered"),
                                   float(len(out)),
                                   labels={"topic": s.topic})
        if slow_depth:
            from consul_tpu import flight
            flight.emit("stream.subscriber.slow",
                        labels={"topic": s.topic, "depth": slow_depth})
        self._pub._flush_stats()
        return out

    def attach_wake(self, ev: threading.Event) -> None:
        """Attach a shared wakeup Event: set by publish/evict/close so
        one consumer can select over many subscriptions.  If batches
        (or a reset) are already pending, the event is set immediately
        — no lost-wakeup window between subscribe and attach."""
        s = self._sub
        with s.cond:
            s.wake = ev
            if s.queue or s.closed:
                ev.set()

    def close(self) -> None:
        self._pub.unsubscribe(self)

    def __iter__(self) -> Iterator[List[Event]]:
        while True:
            batch = self.events()
            if batch:
                yield batch


class EventPublisher:
    """Topic buffers + subscriber registry (event_publisher.go:12).

    Thread-safe.  `publish` is called under the store's write path with the
    commit index; delivery to subscriber queues is synchronous (queues are
    unbounded, consumers drain them on their own threads)."""

    # the owning store's VisibilityTable (set by StateStore.__init__);
    # stream-side consumers (submatview) reach the commit-to-visibility
    # correlation through it
    visibility = None

    def __init__(self, buffer_len: int = 1024,
                 max_sub_queue: int = MAX_SUB_QUEUE):
        self._lock = locks.make_lock("stream.publisher")
        self._buffer_len = buffer_len
        # per-subscriber buffer bound (eviction threshold); tests
        # shrink it to exercise the eviction contract cheaply
        self._max_sub_queue = max(2, int(max_sub_queue))
        # topic -> deque[(index, [Event])]  # guarded-by: _lock
        self._buffers: Dict[str, deque] = {}
        # topic -> highest index evicted off the buffer tail (0 = nothing
        # evicted): the explicit loss marker subscribe() checks against —
        # inferring loss from the oldest buffered batch would misread
        # cross-topic index gaps as eviction  # guarded-by: _lock
        self._evicted_through: Dict[str, int] = {}
        self._subs: List[_Sub] = []     # guarded-by: _lock
        # gauges staged during publish (which runs under the STORE
        # lock) and flushed by drain/subscribe sites on their own
        # threads: topic -> last fan-out width; eviction counts
        self._stats_lock = locks.make_lock("stream.publisher.stats")
        # guarded-by: _stats_lock
        self._fanout_stats: Dict[str, int] = {}
        # guarded-by: _stats_lock
        self._evict_stats: Dict[str, int] = {}
        # staged SUBSCRIBER evictions: topic -> [count, max depth],
        # aggregated so a mass eviction journals one flight row per
        # topic per flush, not one per subscriber
        # guarded-by: _stats_lock
        self._sub_evict_stats: Dict[str, list] = {}
        locks.register_guards(self, self._lock,
                              "_buffers", "_evicted_through", "_subs")
        locks.register_guards(self, self._stats_lock, "_fanout_stats",
                              "_evict_stats", "_sub_evict_stats")

    # ----------------------------------------------------------- publishing

    def publish(self, events: List[Event]) -> None:
        if not events:
            return
        by_topic: Dict[str, List[Event]] = {}
        for e in events:
            by_topic.setdefault(e.topic, []).append(e)
        evicted = []
        with self._lock:
            for topic, evs in by_topic.items():
                buf = self._buffers.setdefault(
                    topic, deque(maxlen=self._buffer_len))
                if len(buf) == self._buffer_len:
                    self._evicted_through[topic] = buf[0][0]
                    evicted.append(topic)
                buf.append((evs[0].index, evs))
            subs = list(self._subs)
        fanout: Dict[str, int] = {t: 0 for t in by_topic}
        evicted_subs: List[_Sub] = []
        for s in subs:
            mine = [e for e in by_topic.get(s.topic, ())
                    if s.key is None or e.key == s.key]
            if not mine:
                continue
            fanout[s.topic] += 1
            with s.cond:
                if s.closed:
                    continue
                depth = len(s.queue)
                if depth >= (s.queue.maxlen or MAX_SUB_QUEUE) - 1:
                    # sustained lag: the bounded buffer filled without
                    # a single drain — EVICT rather than let the deque
                    # silently drop the oldest batch (a holey stream
                    # would be corruption; a reset is a contract).
                    # The consumer's next events() raises
                    # SnapshotRequired; materializers re-snapshot.
                    s.closed = True
                    s.evicted = True
                    s.queue.clear()
                    s.cond.notify_all()
                    if s.wake is not None:
                        s.wake.set()
                    evicted_subs.append(s)
                    continue
                s.queue.append(mine)
                depth += 1
                if depth > SLOW_QUEUE_DEPTH and depth > s.slow_depth:
                    # flag only — the consumer journals the slow event
                    # when it drains; publish may run under the store
                    # lock and must not emit
                    s.slow_depth = depth
                s.cond.notify_all()
                if s.wake is not None:
                    # Event.set is emit-free: safe under the store lock
                    s.wake.set()
        if evicted_subs:
            # drop evicted subs from the registry so the NEXT publish
            # no longer pays their fan-out cost (the whole point: 10k
            # wedged watchers cost one eviction pass, then nothing)
            with self._lock:
                for s in evicted_subs:
                    if s in self._subs:
                        self._subs.remove(s)
        with self._stats_lock:
            self._fanout_stats.update(fanout)
            for t in evicted:
                self._evict_stats[t] = self._evict_stats.get(t, 0) + 1
            for s in evicted_subs:
                row = self._sub_evict_stats.setdefault(s.topic, [0, 0])
                row[0] += 1
                row[1] = max(row[1],
                             (s.queue.maxlen or MAX_SUB_QUEUE) - 1)

    def _flush_stats(self) -> None:
        """Emit staged per-topic gauges/counters — called from
        consumer-side paths (drain, subscribe) that hold no store or
        publisher lock."""
        with self._stats_lock:
            fanout, self._fanout_stats = self._fanout_stats, {}
            evicts, self._evict_stats = self._evict_stats, {}
            sub_evicts, self._sub_evict_stats = \
                self._sub_evict_stats, {}
        if not fanout and not evicts and not sub_evicts:
            return
        from consul_tpu import telemetry
        for topic, n in fanout.items():
            telemetry.set_gauge(("stream", "fanout"), float(n),
                                labels={"topic": topic})
        for topic, n in evicts.items():
            telemetry.incr_counter(("stream", "evicted"), float(n),
                                   labels={"topic": topic})
        for topic, (n, depth) in sub_evicts.items():
            telemetry.incr_counter(
                ("stream", "subscriber", "evicted"), float(n),
                labels={"topic": topic})
            from consul_tpu import flight
            flight.emit("stream.subscriber.evicted",
                        labels={"topic": topic, "count": n,
                                "depth": depth})

    # --------------------------------------------------------- subscription

    def subscribe(self, topic: str, key: Optional[str] = None,
                  since_index: Optional[int] = 0) -> Subscription:
        """Follow `topic` (optionally one key) from `since_index`.

        Replays buffered batches newer than since_index; raises
        SnapshotRequired if the buffer no longer reaches back that far
        (caller must take a fresh snapshot and resubscribe).
        since_index=None subscribes TAIL-ONLY: no replay, no eviction
        check — for consumers that snapshot state themselves right after
        subscribing (submatview materializers)."""
        sub = _Sub(topic=topic, key=key, next_index=since_index or 0,
                   cond=locks.make_condition(name="stream.sub"),
                   queue=deque(maxlen=self._max_sub_queue))
        n = None
        try:
            with self._lock:
                buf = self._buffers.get(topic, ())
                if since_index is None:
                    self._subs.append(sub)
                    n = sum(1 for s in self._subs if s.topic == topic)
                    return Subscription(self, sub)
                evicted = self._evicted_through.get(topic, 0)
                if since_index < evicted:
                    n = None
                    raise SnapshotRequired(
                        f"events through {evicted} evicted, "
                        f"need {since_index}")
                replay = [[e for e in evs if key is None or e.key == key]
                          for idx, evs in buf if idx > since_index]
                replay = [b for b in replay if b]
                if len(replay) >= (sub.queue.maxlen or MAX_SUB_QUEUE):
                    # the backlog alone overflows the subscriber's
                    # bounded buffer: appending would silently drop
                    # its head — a fresh snapshot is the honest answer
                    raise SnapshotRequired(
                        f"replay of {len(replay)} batches exceeds the "
                        f"subscriber buffer bound")
                for b in replay:
                    sub.queue.append(b)
                self._subs.append(sub)
                n = sum(1 for s in self._subs if s.topic == topic)
            return Subscription(self, sub)
        except SnapshotRequired:
            # the follower fell off the buffer tail: journal the
            # forced re-snapshot (the reset IS the stall signal a slow
            # materializer leaves behind) — off the publisher lock
            from consul_tpu import flight
            flight.emit("stream.subscriber.reset",
                        labels={"topic": topic, "key": key or ""})
            raise
        finally:
            # subscribe runs on watcher/materializer threads (never
            # under the store lock); emit AFTER releasing the publisher
            # lock so publish() — which takes it under the store lock —
            # cannot queue behind sink I/O
            if n is not None:
                self._subscribers_gauge(topic, n)
                self._flush_stats()

    @staticmethod
    def _subscribers_gauge(topic: str, n: int) -> None:
        from consul_tpu import telemetry
        telemetry.set_gauge(("stream", "subscribers"), float(n),
                            labels={"topic": topic})

    def unsubscribe(self, subscription: Subscription) -> None:
        s = subscription._sub
        with self._lock:
            if s in self._subs:
                self._subs.remove(s)
            n = sum(1 for x in self._subs if x.topic == s.topic)
        self._subscribers_gauge(s.topic, n)
        with s.cond:
            s.closed = True
            s.cond.notify_all()
            if s.wake is not None:
                s.wake.set()

    def close_all(self) -> None:
        with self._lock:
            subs, self._subs = self._subs, []
        for s in subs:
            with s.cond:
                s.closed = True
                s.cond.notify_all()
                if s.wake is not None:
                    s.wake.set()
