"""EventPublisher: topic-keyed event fan-out with snapshot-then-follow.

The server side of the reference's streaming read path
(agent/consul/stream/event_publisher.go:12 EventPublisher;
stream/subscription.go:32 Subscription; wiring agent/consul/server.go:637-645).
Store commits publish typed events onto topics; subscribers get a snapshot
of current state followed by the live event stream from the snapshot index,
so a materialized view (consul_tpu/submatview.py) can serve blocking reads
without re-running the full query per wakeup.

Design differences from the reference (deliberate, host-side Python):
  * topics are (topic, key) pairs — e.g. ("health", "web") — matching how
    the reference scopes Subscribe requests by Topic+Key
    (proto/pbsubscribe/subscribe.proto:14,34);
  * the per-topic buffer is a bounded deque of (index, events) batches; a
    subscriber that falls off the tail gets a NewSnapshotToFollow-style
    reset, like the reference's snapshot cache eviction;
  * no gRPC framing — in-process subscriptions are iterators; the HTTP
    layer exposes them as long-polls and the RPC layer as streamed frames.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

# Topic names (reference pbsubscribe topics + the memdb tables that feed
# blocking queries; state/schema.go:10).
TOPIC_KV = "kv"
TOPIC_SERVICE_HEALTH = "health"        # key = service name
TOPIC_CATALOG_NODES = "nodes"          # key = node name ("" = any)
TOPIC_CATALOG_SERVICES = "services"    # key = service name ("" = any)
TOPIC_SESSIONS = "sessions"
TOPIC_ACL = "acl"
TOPIC_INTENTIONS = "intentions"
TOPIC_CONFIG = "config"                # config entries
TOPIC_COORDINATES = "coordinates"
TOPIC_QUERIES = "queries"              # prepared queries
TOPIC_CA = "ca"                        # connect CA roots/leaf rotation


@dataclass(frozen=True)
class Event:
    """One state-change event (stream/event_publisher.go Event shape)."""

    topic: str
    key: str
    index: int
    payload: Any = None
    op: str = "update"          # update | delete | snapshot-end


class SnapshotRequired(Exception):
    """Raised to a follower that fell off the buffer tail: re-snapshot.

    Mirrors the reference's NewSnapshotToFollow reset frame
    (stream/subscription.go forceClose on buffer eviction)."""


@dataclass
class _Sub:
    topic: str
    key: Optional[str]                 # None = all keys on the topic
    next_index: int
    cond: threading.Condition
    closed: bool = False
    queue: deque = field(default_factory=deque)


class Subscription:
    """Iterator over events for one (topic, key) from a start index.

    `events(timeout)` blocks for the next batch; raises SnapshotRequired
    if the publisher evicted history the subscriber still needed."""

    def __init__(self, pub: "EventPublisher", sub: _Sub):
        self._pub = pub
        self._sub = sub

    def events(self, timeout: float = 300.0) -> List[Event]:
        s = self._sub
        with s.cond:
            if not s.queue and not s.closed:
                s.cond.wait(timeout)
            if s.closed:
                raise SnapshotRequired("subscription reset")
            out: List[Event] = []
            while s.queue:
                out.extend(s.queue.popleft())
            return out

    def close(self) -> None:
        self._pub.unsubscribe(self)

    def __iter__(self) -> Iterator[List[Event]]:
        while True:
            batch = self.events()
            if batch:
                yield batch


class EventPublisher:
    """Topic buffers + subscriber registry (event_publisher.go:12).

    Thread-safe.  `publish` is called under the store's write path with the
    commit index; delivery to subscriber queues is synchronous (queues are
    unbounded, consumers drain them on their own threads)."""

    def __init__(self, buffer_len: int = 1024):
        self._lock = threading.Lock()
        self._buffer_len = buffer_len
        # topic -> deque[(index, [Event])]
        self._buffers: Dict[str, deque] = {}
        # topic -> highest index evicted off the buffer tail (0 = nothing
        # evicted): the explicit loss marker subscribe() checks against —
        # inferring loss from the oldest buffered batch would misread
        # cross-topic index gaps as eviction
        self._evicted_through: Dict[str, int] = {}
        self._subs: List[_Sub] = []

    # ----------------------------------------------------------- publishing

    def publish(self, events: List[Event]) -> None:
        if not events:
            return
        by_topic: Dict[str, List[Event]] = {}
        for e in events:
            by_topic.setdefault(e.topic, []).append(e)
        with self._lock:
            for topic, evs in by_topic.items():
                buf = self._buffers.setdefault(
                    topic, deque(maxlen=self._buffer_len))
                if len(buf) == self._buffer_len:
                    self._evicted_through[topic] = buf[0][0]
                buf.append((evs[0].index, evs))
            subs = list(self._subs)
        for s in subs:
            mine = [e for e in by_topic.get(s.topic, ())
                    if s.key is None or e.key == s.key]
            if not mine:
                continue
            with s.cond:
                s.queue.append(mine)
                s.cond.notify_all()

    # --------------------------------------------------------- subscription

    def subscribe(self, topic: str, key: Optional[str] = None,
                  since_index: Optional[int] = 0) -> Subscription:
        """Follow `topic` (optionally one key) from `since_index`.

        Replays buffered batches newer than since_index; raises
        SnapshotRequired if the buffer no longer reaches back that far
        (caller must take a fresh snapshot and resubscribe).
        since_index=None subscribes TAIL-ONLY: no replay, no eviction
        check — for consumers that snapshot state themselves right after
        subscribing (submatview materializers)."""
        sub = _Sub(topic=topic, key=key, next_index=since_index or 0,
                   cond=threading.Condition())
        with self._lock:
            buf = self._buffers.get(topic, ())
            if since_index is None:
                self._subs.append(sub)
                return Subscription(self, sub)
            evicted = self._evicted_through.get(topic, 0)
            if since_index < evicted:
                raise SnapshotRequired(
                    f"events through {evicted} evicted, need {since_index}")
            replay = [[e for e in evs if key is None or e.key == key]
                      for idx, evs in buf if idx > since_index]
            replay = [b for b in replay if b]
            for b in replay:
                sub.queue.append(b)
            self._subs.append(sub)
        return Subscription(self, sub)

    def unsubscribe(self, subscription: Subscription) -> None:
        s = subscription._sub
        with self._lock:
            if s in self._subs:
                self._subs.remove(s)
        with s.cond:
            s.closed = True
            s.cond.notify_all()

    def close_all(self) -> None:
        with self._lock:
            subs, self._subs = self._subs, []
        for s in subs:
            with s.cond:
                s.closed = True
                s.cond.notify_all()
