"""Event streaming: topic buffers + subscriptions over state commits.

Reference: agent/consul/stream/event_publisher.go (EventPublisher),
subscription.go (Subscription), wired to state-store commits via
changeTrackerDB (agent/consul/state/memdb.go:53) and served by the gRPC
subscribe endpoint (agent/rpc/subscribe/, proto/pbsubscribe/subscribe.proto).
"""

from consul_tpu.stream.publisher import (  # noqa: F401
    Event, EventPublisher, SnapshotRequired, Subscription,
    TOPIC_KV, TOPIC_SERVICE_HEALTH, TOPIC_CATALOG_NODES,
    TOPIC_CATALOG_SERVICES, TOPIC_SESSIONS, TOPIC_ACL, TOPIC_INTENTIONS,
    TOPIC_CONFIG, TOPIC_COORDINATES, TOPIC_QUERIES, TOPIC_CA,
)
