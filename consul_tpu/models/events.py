"""Lamport-clocked user-event broadcast with dedup — serf's event layer.

Reference behavior: custom events over serf with a ring buffer and filters
(agent/user_event.go:23-130; server event prefix `consul:event:`
agent/consul/server_serf.go:28,257; serf buffers recent events for dedup and
orders them by Lamport time).  Rebuilt as tensors:

  * a per-node Lamport clock [N] advanced on send and on first delivery;
  * an event table of E in-flight events (name/payload ids, origin ltime);
  * a [N, E] knowledge matrix riding the shared gossip kernel
    (ops/gossip.py, ring-shift peer exchange) — same infection dynamics as
    membership rumors; the whole tick is skipped via lax.cond when no
    event is in flight (the common case — saves the full [N, E] pass);
  * a per-node dedup/delivery ring: events are "delivered" the tick they
    are first learned; `deliveries` counts per event reach the oracle can
    expose (the HTTP event-fire/list API reads from this — api/event.py).

Event payloads live host-side (the device only tracks ids); the host
control plane maps id → (name, payload) like the reference's UserEvents()
ring (agent/user_event.go:207).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
from flax import struct

from consul_tpu.config import GossipConfig, SimConfig
from consul_tpu.ops import gossip as gossip_ops
from consul_tpu.ops import rolls
from consul_tpu.utils import prng


@dataclasses.dataclass(frozen=True)
class EventParams:
    n_nodes: int
    event_slots: int = 32
    gossip_nodes: int = 3
    retransmit_limit: int = 16
    expiry_ticks: int = 64
    p_loss: float = 0.0
    seed: int = 0
    # ring-exchange lowering hint (ops/rolls.py; see SimConfig)
    shard_blocks: int = 1


def make_params(gossip: GossipConfig, sim: SimConfig,
                event_slots: int = 32) -> EventParams:
    import math
    spread = max(8, 4 * math.ceil(math.log2(sim.n_nodes + 1)))
    return EventParams(
        n_nodes=sim.n_nodes,
        p_loss=sim.p_loss,
        event_slots=event_slots,
        gossip_nodes=gossip.gossip_nodes,
        retransmit_limit=gossip.retransmit_limit(sim.n_nodes),
        expiry_ticks=spread,
        seed=sim.seed ^ 0xE7E7,
        shard_blocks=sim.shard_blocks,
    )


@struct.dataclass
class EventState:
    tick: jnp.ndarray        # int32 scalar
    lamport: jnp.ndarray     # [N] int32 per-node Lamport clock
    e_active: jnp.ndarray    # [E] bool
    e_id: jnp.ndarray        # [E] int32 host-side event id (name+payload)
    e_ltime: jnp.ndarray     # [E] int32 Lamport time of the fire
    e_origin: jnp.ndarray    # [E] int32
    e_start: jnp.ndarray     # [E] int32 origin tick
    know: jnp.ndarray        # [N, E] bool
    deliver_tick: jnp.ndarray  # [N, E] int32 first-delivery tick
    sends_left: jnp.ndarray  # [N, E] int8


def init_state(params: EventParams) -> EventState:
    n, e = params.n_nodes, params.event_slots
    return EventState(
        tick=jnp.int32(0),
        lamport=jnp.zeros((n,), jnp.int32),
        e_active=jnp.zeros((e,), bool),
        e_id=jnp.zeros((e,), jnp.int32),
        e_ltime=jnp.zeros((e,), jnp.int32),
        e_origin=jnp.zeros((e,), jnp.int32),
        e_start=jnp.zeros((e,), jnp.int32),
        know=jnp.zeros((n, e), bool),
        deliver_tick=jnp.full((n, e), -1, jnp.int32),
        sends_left=jnp.zeros((n, e), jnp.int8),
    )


def fire(params: EventParams, s: EventState, origin: int | jnp.ndarray,
         event_id: int | jnp.ndarray) -> EventState:
    """Fire a user event from `origin` (UserEvent — agent/user_event.go:23).

    Allocates the lowest free slot; if the table is full the oldest slot is
    recycled (serf's event buffer also evicts by age)."""
    e = params.event_slots
    origin = jnp.asarray(origin, jnp.int32)
    ltime = s.lamport[origin] + 1
    lamport = s.lamport.at[origin].set(ltime)

    age_score = jnp.where(s.e_active, s.e_start, -(10 ** 9))
    slot = jnp.where(jnp.any(~s.e_active),
                     jnp.argmin(s.e_active),
                     jnp.argmin(-age_score)).astype(jnp.int32)
    onehot = jnp.arange(e) == slot
    origin_row = jnp.arange(params.n_nodes) == origin
    cell = origin_row[:, None] & onehot[None, :]
    return s.replace(
        lamport=lamport,
        e_active=s.e_active | onehot,
        e_id=jnp.where(onehot, event_id, s.e_id),
        e_ltime=jnp.where(onehot, ltime, s.e_ltime),
        e_origin=jnp.where(onehot, origin, s.e_origin),
        e_start=jnp.where(onehot, s.tick, s.e_start),
        know=jnp.where(onehot[None, :], cell, s.know),
        deliver_tick=jnp.where(onehot[None, :],
                               jnp.where(cell, s.tick, -1), s.deliver_tick),
        sends_left=jnp.where(onehot[None, :],
                             jnp.where(cell, jnp.int8(min(
                                 params.retransmit_limit, 127)),
                                 jnp.int8(0)),
                             s.sends_left),
    )


def step(params: EventParams, s: EventState, up: jnp.ndarray,
         member: jnp.ndarray) -> EventState:
    """One gossip tick of event dissemination; `up`/`member` come from the
    membership model so events only flow between live members.  Skipped
    entirely (tick bump only) when no event is in flight."""
    n = params.n_nodes

    def active_branch(s):
        key = prng.tick_key(params.seed, s.tick, 3)
        offs = rolls.offsets(key, n, params.gossip_nodes)
        res = gossip_ops.disseminate(offs, s.know, s.sends_left,
                                     sender_ok=up, receiver_ok=up & member,
                                     slot_active=s.e_active,
                                     retransmit_limit=min(
                                         params.retransmit_limit, 127),
                                     p_loss=params.p_loss,
                                     key=prng.tick_key(params.seed,
                                                       s.tick, 6),
                                     blocks=params.shard_blocks)
        deliver_tick = jnp.where(res.newly, s.tick, s.deliver_tick)
        # Lamport witness: clock jumps past the max ltime delivered this tick
        seen = jnp.where(res.newly, s.e_ltime[None, :], 0)
        lamport = jnp.maximum(s.lamport, jnp.max(seen, axis=1))

        done = s.e_active & (s.tick - s.e_start >= params.expiry_ticks)
        return s.replace(
            tick=s.tick + 1,
            lamport=lamport,
            e_active=s.e_active & ~done,
            know=res.know & ~done[None, :],
            deliver_tick=deliver_tick,
            sends_left=jnp.where(done[None, :], jnp.int8(0), res.sends_left),
        )

    return jax.lax.cond(jnp.any(s.e_active), active_branch,
                        lambda s: s.replace(tick=s.tick + 1), s)


def coverage(params: EventParams, s: EventState, slot: int,
             up: jnp.ndarray, member: jnp.ndarray) -> jnp.ndarray:
    """Fraction of live members that have ever received event in `slot`
    (delivery records outlive the slot's dissemination window)."""
    alive = up & member
    got = (s.deliver_tick[:, slot] >= 0) & alive
    return jnp.sum(got) / jnp.maximum(jnp.sum(alive), 1)
