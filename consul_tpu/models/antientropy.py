"""Anti-entropy: paced full/partial sync of agent state into the catalog.

Reference behavior (agent/ae/ae.go + agent/local/state.go): every agent
periodically diffs its desired services/checks against the server catalog
(`SyncFull`, staggered and interval-scaled by cluster size) and pushes
edge-triggered deltas (`SyncChanges`) in between.  The pacing constant is
`scaleFactor` (ae.go:27-40): the full-sync interval doubles for every
doubling of cluster size past 128 nodes.

Tensorized: desired and actual are id-sorted columnar tables (service id →
owner node, version); the diff is the sorted-merge kernel in
ops/reconcile.py; per-agent sync timers advance in the same tick loop as
gossip.  One step syncs *all* due agents' rows at once — the per-entry map
walk of the reference becomes two binary-search joins plus masked merges.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Tuple

import jax
import jax.numpy as jnp
from flax import struct

from consul_tpu.ops import reconcile
from consul_tpu.utils import prng


def scale_factor(n_nodes: int) -> int:
    """Reference agent/ae/ae.go:27-40: 1 for <=128 nodes, then
    ceil(log2(n) - log2(128)) + 1."""
    if n_nodes <= 128:
        return 1
    return int(math.ceil(math.log2(n_nodes) - math.log2(128.0))) + 1


@dataclasses.dataclass(frozen=True)
class AEParams:
    n_agents: int
    capacity: int               # S: service-instance table capacity
    sync_interval_ticks: int    # base full-sync interval (reference: 1m)
    stagger_frac: float = 0.1   # randomized stagger (lib/rand.go RandomStagger)
    seed: int = 0

    @property
    def scaled_interval(self) -> int:
        return self.sync_interval_ticks * scale_factor(self.n_agents)


@struct.dataclass
class AEState:
    tick: jnp.ndarray       # int32
    # desired (agent-local) table, id-sorted
    d_ids: jnp.ndarray      # [S] int32 (INVALID_ID = empty)
    d_node: jnp.ndarray     # [S] int32 owning agent
    d_ver: jnp.ndarray      # [S] int32 content version
    d_dirty: jnp.ndarray    # [S] bool: changed since last sync (edge trigger)
    # actual (catalog) table, id-sorted
    a_ids: jnp.ndarray      # [S] int32
    a_node: jnp.ndarray     # [S] int32
    a_ver: jnp.ndarray      # [S] int32
    # per-agent timers
    next_full: jnp.ndarray  # [N] int32 next full-sync tick
    n_dirty: jnp.ndarray    # [N] bool: agent has pending deletes/changes
    syncs_done: jnp.ndarray  # int32 counter (telemetry)


def init_state(params: AEParams) -> AEState:
    s_cap, n = params.capacity, params.n_agents
    key = prng.tick_key(params.seed, 0, 11)
    stagger = jax.random.randint(key, (n,), 0,
                                 max(1, params.scaled_interval), jnp.int32)
    empty = jnp.full((s_cap,), reconcile.INVALID_ID, jnp.int32)
    zeros = jnp.zeros((s_cap,), jnp.int32)
    return AEState(
        tick=jnp.int32(0),
        d_ids=empty, d_node=zeros, d_ver=zeros,
        d_dirty=jnp.zeros((s_cap,), bool),
        a_ids=empty, a_node=zeros, a_ver=zeros,
        next_full=stagger,
        n_dirty=jnp.zeros((n,), bool),
        syncs_done=jnp.int32(0),
    )


def register_desired(s: AEState, ids, nodes, vers) -> AEState:
    """Host-side: add/update desired service instances (keeps id order)."""
    d_ids = jnp.concatenate([s.d_ids, jnp.asarray(ids, jnp.int32)])
    d_node = jnp.concatenate([s.d_node, jnp.asarray(nodes, jnp.int32)])
    d_ver = jnp.concatenate([s.d_ver, jnp.asarray(vers, jnp.int32)])
    d_dirty = jnp.concatenate([s.d_dirty, jnp.ones(len(ids), bool)])
    prio = jnp.concatenate([jnp.ones_like(s.d_ids), jnp.zeros(len(ids), jnp.int32)])
    order = jnp.lexsort((prio, d_ids))
    d_ids, d_node, d_ver, d_dirty = (x[order] for x in (d_ids, d_node, d_ver, d_dirty))
    first = jnp.concatenate([jnp.array([True]), d_ids[1:] != d_ids[:-1]])
    d_ids = jnp.where(first, d_ids, reconcile.INVALID_ID)
    order2 = jnp.argsort(jnp.where(d_ids == reconcile.INVALID_ID, 1, 0), stable=True)
    cap = s.d_ids.shape[0]
    return s.replace(d_ids=d_ids[order2][:cap], d_node=d_node[order2][:cap],
                     d_ver=d_ver[order2][:cap], d_dirty=d_dirty[order2][:cap])


def deregister_desired(s: AEState, ids) -> AEState:
    ids = jnp.asarray(ids, jnp.int32)
    pos = jnp.clip(jnp.searchsorted(s.d_ids, ids), 0, s.d_ids.shape[0] - 1)
    hit = s.d_ids[pos] == ids
    gone = jnp.zeros_like(s.d_ids, bool).at[jnp.where(hit, pos, 0)].max(hit)
    # flag owners so the deletion syncs promptly (SyncChanges edge trigger)
    n_dirty = s.n_dirty.at[jnp.where(gone, s.d_node, 0)].max(gone)
    d_ids = jnp.where(gone, reconcile.INVALID_ID, s.d_ids)
    order = jnp.argsort(jnp.where(d_ids == reconcile.INVALID_ID, 1, 0), stable=True)
    return s.replace(d_ids=d_ids[order], d_node=s.d_node[order],
                     d_ver=s.d_ver[order], d_dirty=s.d_dirty[order],
                     n_dirty=n_dirty)


def step(params: AEParams, s: AEState, up: jnp.ndarray) -> AEState:
    """One tick: agents whose timer fired (or with dirty rows) sync.

    `up`: [N] bool from the membership model — down agents don't sync
    (their rows go stale until the leader reconciles them, mirroring
    reference leader.go:1332 handleFailedMember)."""
    tick = s.tick
    due_full = (tick >= s.next_full) & up                         # [N]
    # edge triggers: row-level change dirt or agent-level delete dirt
    row_dirt_owner = jnp.zeros_like(up).at[
        jnp.where(s.d_dirty, s.d_node, 0)].max(s.d_dirty)
    due = (due_full | s.n_dirty | row_dirt_owner) & up            # [N]

    diff = reconcile.diff_sorted(s.d_ids, s.d_ver, s.a_ids, s.a_ver)
    push = diff.push & due[s.d_node]
    drop = diff.drop & due[s.a_node]

    a_ids = jnp.where(drop, reconcile.INVALID_ID, s.a_ids)
    order = jnp.argsort(jnp.where(a_ids == reconcile.INVALID_ID, 1, 0), stable=True)
    a_ids, a_node, a_ver = a_ids[order], s.a_node[order], s.a_ver[order]

    a_ids, a_ver, a_node = _merge_push(s.d_ids, s.d_ver, s.d_node,
                                       a_ids, a_ver, a_node, push)

    # reset timers for agents that full-synced, with fresh stagger
    key = prng.tick_key(params.seed, tick, 12)
    jitter = jax.random.randint(
        key, (params.n_agents,), 0,
        max(1, int(params.scaled_interval * params.stagger_frac)) + 1, jnp.int32)
    next_full = jnp.where(due_full, tick + params.scaled_interval + jitter,
                          s.next_full)
    return s.replace(tick=tick + 1, a_ids=a_ids, a_node=a_node, a_ver=a_ver,
                     next_full=next_full,
                     d_dirty=s.d_dirty & ~due[s.d_node],
                     n_dirty=s.n_dirty & ~due,
                     syncs_done=s.syncs_done + jnp.sum(due_full))


def _merge_push(d_ids, d_ver, d_node, a_ids, a_ver, a_node, push):
    """Merge pushed desired rows into the actual table (id-sorted, fixed cap)."""
    cap = a_ids.shape[0]
    cand = jnp.where(push, d_ids, reconcile.INVALID_ID)
    ids = jnp.concatenate([cand, a_ids])
    ver = jnp.concatenate([d_ver, a_ver])
    node = jnp.concatenate([d_node, a_node])
    prio = jnp.concatenate([jnp.zeros_like(cand), jnp.ones_like(a_ids)])
    order = jnp.lexsort((prio, ids))
    ids, ver, node = ids[order], ver[order], node[order]
    first = jnp.concatenate([jnp.array([True]), ids[1:] != ids[:-1]])
    ids = jnp.where(first, ids, reconcile.INVALID_ID)
    order2 = jnp.argsort(jnp.where(ids == reconcile.INVALID_ID, 1, 0), stable=True)
    return ids[order2][:cap], ver[order2][:cap], node[order2][:cap]


def in_sync_fraction(s: AEState) -> jnp.ndarray:
    """Fraction of live desired rows present and current in the catalog."""
    diff = reconcile.diff_sorted(s.d_ids, s.d_ver, s.a_ids, s.a_ver)
    live = s.d_ids != reconcile.INVALID_ID
    return 1.0 - jnp.sum(diff.push & live) / jnp.maximum(jnp.sum(live), 1)
