"""The Serf layer: SWIM membership + Vivaldi coordinates in one cluster step.

This is the flagship model — the batched equivalent of a whole Consul LAN
gossip pool (reference: pool creation agent/consul/server_serf.go:36-185;
the serf library layers coordinates and events over memberlist, go.mod:58).
Each tick advances failure detection and dissemination (models/swim.py) and
feeds the round's direct probe acks to the coordinate solver
(models/vivaldi.py), mirroring serf's update-on-probe-ack coupling
(reference agent/agent.go:1629 GetLANCoordinate ← probe acks).

Lamport-clocked user events ride the same rumor table (swim.LEFT-style
dissemination) — see models/events.py.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
from flax import struct

from consul_tpu.config import GossipConfig, SimConfig
from consul_tpu.models import events, swim, vivaldi


@dataclasses.dataclass(frozen=True)
class SerfParams:
    swim: swim.SwimParams
    vivaldi: vivaldi.VivaldiParams
    events: events.EventParams

    @property
    def n_nodes(self) -> int:
        return self.swim.n_nodes


def make_params(gossip: GossipConfig | None = None,
                sim: SimConfig | None = None,
                coord_dims: int = 8, event_slots: int = 32) -> SerfParams:
    gossip = gossip or GossipConfig.lan()
    sim = sim or SimConfig()
    return SerfParams(
        swim=swim.make_params(gossip, sim),
        vivaldi=vivaldi.VivaldiParams(n_nodes=sim.n_nodes, dims=coord_dims,
                                      seed=sim.seed,
                                      shard_blocks=sim.shard_blocks),
        events=events.make_params(gossip, sim, event_slots),
    )


@struct.dataclass
class ClusterState:
    swim: swim.SwimState
    coords: vivaldi.VivaldiState
    events: events.EventState


def init_state(params: SerfParams, key=None,
               n_initial: int = 0) -> ClusterState:
    return ClusterState(swim=swim.init_state(params.swim, key,
                                             n_initial=n_initial),
                        coords=vivaldi.init_state(params.vivaldi),
                        events=events.init_state(params.events))


def step(params: SerfParams, s: ClusterState) -> ClusterState:
    """One gossip tick of the full serf pool (jit this).

    The coordinate solver only has observations on probe ticks (acked ring
    probes carry RTT samples); the whole Vivaldi update is gated out on
    gossip-only ticks via lax.cond."""
    do_probe = (s.swim.tick % params.swim.probe_period_ticks) == 0
    sw, obs = swim.step_with_obs(params.swim, s.swim)
    coords = jax.lax.cond(
        do_probe,
        lambda c: vivaldi.observe_ring(params.vivaldi, c, obs.shift,
                                       obs.rtt_ms / 1000.0, obs.acked),
        lambda c: c,
        s.coords)
    ev = events.step(params.events, s.events, up=sw.up, member=sw.member)
    return ClusterState(swim=sw, coords=coords, events=ev)


def metrics_vector(params: SerfParams, s: ClusterState) -> jnp.ndarray:
    """Device-side telemetry for the whole pool (swim.METRIC_NAMES
    order) — the consul.serf.* gauge source, one transfer per scrape."""
    return swim.metrics_vector(params.swim, s.swim)


def status_vector(params: SerfParams, s: ClusterState) -> jnp.ndarray:
    """[N] int8 member status (swim.STATUS_*) — stays on device."""
    return swim.status_vector(params.swim, s.swim)


def shard_metrics(params: SerfParams, s: ClusterState,
                  n_blocks: int) -> jnp.ndarray:
    """[B, K] per-shard gauges (swim.SHARD_METRIC_NAMES order) — the
    consul.serf.*{shard} split, one transfer per scrape."""
    return swim.shard_metrics(params.swim, s.swim, n_blocks)


def membership_counts(params: SerfParams, s: ClusterState,
                      provisioned: jnp.ndarray) -> jnp.ndarray:
    return swim.membership_counts(params.swim, s.swim, provisioned)


def membership_page(params: SerfParams, s: ClusterState, ids: jnp.ndarray):
    return swim.membership_page(params.swim, s.swim, ids)


def membership_delta(params: SerfParams, s: ClusterState,
                     prev_status: jnp.ndarray, provisioned: jnp.ndarray,
                     k: int):
    return swim.membership_delta(params.swim, s.swim, prev_status,
                                 provisioned, k)


def rtt_order(params: SerfParams, s: ClusterState, origin: jnp.ndarray,
              ids: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """?near= ordering computed ON DEVICE (agent/consul/rtt.go:196 /
    lib/rtt.go:13-43 semantics): distances from `origin` to `ids`
    ([K] int32, `valid` masks padding), invalid rows sort last.
    Returns the [K] argsort — the only transfer is O(K) indices, never
    the [N, D] coordinate tensor.

    The origin row is extracted by one-hot mask + sum and distances
    are computed for EVERY node before the [K] index step: row-indexing
    the sharded [N, D] coordinate tensor (`coords[ids]`) all-gathers it
    under GSPMD (hlo_lint gather-freedom finding, ISSUE 20), while the
    masked reduction lowers to local selects plus an all-reduce of [D]
    partials and the full-N distance field stays elementwise-sharded.
    Same arithmetic per node, so results are bit-identical."""
    c = s.coords
    n = c.coords.shape[0]
    at_origin = jnp.arange(n, dtype=jnp.int32) == origin
    ovec = jnp.sum(jnp.where(at_origin[:, None], c.coords, 0.0), axis=0)
    oh = jnp.sum(jnp.where(at_origin, c.height, 0.0))
    oadj = jnp.sum(jnp.where(at_origin, c.adjustment, 0.0))
    d_all = jnp.linalg.norm(c.coords - ovec, axis=-1) + c.height + oh
    adjusted = d_all + c.adjustment + oadj
    dist_all = jnp.where(adjusted > 0.0, adjusted, d_all)
    dist = jnp.where(valid, dist_all[ids], jnp.inf)
    return jnp.argsort(dist, stable=True)


def fire_event(params: SerfParams, s: ClusterState, origin: int,
               event_id: int) -> ClusterState:
    """Fire a user event (reference agent/user_event.go:23 UserEvent)."""
    return s.replace(events=events.fire(params.events, s.events, origin,
                                        event_id))


def run(params: SerfParams, s: ClusterState, n_ticks: int,
        monitor_subject: int | None = None) -> Tuple[ClusterState, jnp.ndarray]:
    def body(st, _):
        st = step(params, st)
        if monitor_subject is None:
            return st, jnp.float32(0)
        return st, swim.believed_down_fraction(params.swim, st.swim,
                                               monitor_subject)

    return jax.lax.scan(body, s, None, length=n_ticks)
