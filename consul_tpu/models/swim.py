"""SWIM failure detection + infection-style dissemination as a tensor kernel.

This is the TPU-native replacement for the hashicorp/memberlist engine that
Consul builds its Serf LAN/WAN pools on (reference: go.mod:53; tuning surface
agent/config/default.go:70-84; member-event consumption
agent/consul/server_serf.go:203-255; Lifeguard description
website/content/docs/architecture/gossip.mdx:45-60).  The SWIM/Lifeguard
behavior is reconstructed from the published algorithms (Das et al. 2002;
Dadgar et al., Lifeguard) — no reference code is translated.

Design (SURVEY.md §7): instead of N goroutines with per-node O(N) views
(O(N^2) state — 4TB at 1M nodes), the state is **rumor-centric**:

  * ground truth per node: up/down, member/left, incarnation      — O(N)
  * a fixed table of U active rumors (alive/suspect/dead/left)     — O(U)
  * per-(node, rumor) knowledge, learn tick, retransmit budget     — O(N·U)

One jitted `step(params, state)` advances every node one gossip tick:

  probe round (every probe_interval/gossip_interval ticks)
    → ring probe at a shared random offset (memberlist walks a shuffled
      ring for probe targets; the shift keeps exactly that one-prober-per-
      subject-per-round structure while avoiding TPU gathers — ops/rolls.py)
    → k indirect probes through ring relays, timeouts sampled from a
      factored coordinate RTT model (no N×N matrix)
    → failed probes start DENSE per-subject suspicion timers (O(N)
      sus_start/sus_confirm — detection can never be gated by rumor-slot
      pressure; memberlist's per-node state tables run every victim's
      timer concurrently) and originate/confirm `suspect` rumors
      (Lifeguard timer shortened by independent confirmations)
  suspicion expiry → first expiring holder originates a `dead` rumor;
      dense timers expire independently (_dense_suspicion_expiry), so a
      rack-scale kill detects in ONE suspicion timeout and only the
      dead-rumor DISSEMINATION contends for table capacity
  refutation      → a live suspect bumps its incarnation, originates `alive`
  dissemination   → every carrier serves its queued rumors to ring peers at
      `gossip_nodes` random offsets: rotation ops over the [N, U]
      knowledge matrix (the SpMV of SURVEY.md §2.1)
  expiry          → fully-retransmitted rumors free their slot; `dead`/`left`
      commit to the O(N) ground-truth belief baseline

All shapes are static; control flow is `lax.cond`/`lax.scan`; randomness is
counter-based (seed, tick, stream).  Per-node work avoids 1M-index gathers
and scatters entirely: peer exchange is ring rotation, and all rumor-table
lookups are one-hot compares over the tiny U axis (measured 90x faster
than the gather formulation at N=1M on v5e).  The node axis shards over a
`jax.sharding.Mesh` — see consul_tpu/parallel/mesh.py.

Known simplifications vs memberlist (documented, to refine):
  * probe/gossip peers are ring neighbors at shared random offsets rather
    than per-node-independent uniform draws (same expected fanout, same
    exponential spread; memberlist's own probe order is a shuffled ring);
  * a rumor's payload always fits the packet (U is small).

No-longer-simplifications (capabilities the kernel now has):
  * rejoin-with-higher-incarnation: `rejoin()` revives a dead subject
    when it returns with a higher incarnation (memberlist aliveNode on
    a dead entry) — tested in tests/test_swim.py;
  * rumor-slot pressure eviction: under slot exhaustion, fully-spread
    and lowest-priority rumors are evicted first, and SUSPECT slots are
    never evicted (eviction there would livelock refutation);
  * correlated-kill timing fidelity: suspicion TIMING is dense per
    subject (sus_start/sus_confirm), so V simultaneous deaths run V
    concurrent timers — validated against a real UDP pool at 96 nodes
    with simultaneous victims (LIVE_VS_SIM.json multi_victim) and
    derived against memberlist math at 1M (BENCH_correlated.json
    derivation block);
  * Lifeguard Local Health Awareness + NACK (gossip.mdx:45-60; the
    Lifeguard paper's LHA-Probe): each node carries a health score
    ([N] awareness) fed by its own probe outcomes — acked probe -1,
    failed probe charged only as far as live relays' NACKs failed to
    come back (leg-resolved indirect probes: a relay that reached the
    origin but not the target returns a NACK), having to refute a
    suspicion of itself +1.  The score stretches that node's probe
    rate and timeout by (score+1), so probers with degraded
    connectivity originate fewer and slower suspicions — measurably
    fewer false suspicions at p_loss 0.10-0.20 (tools/f1_harness.py
    --lha sweep).  awareness_max_multiplier=0 disables;
  * mass-event dissemination (kills far above U): expired subjects
    that cannot win a dead slot enter the BULK death channel
    (bulk_member/bulk_heard) — exact per node, mean-field per subject
    — where each ring contact transfers at most `packet_msgs` deaths
    (memberlist's per-packet piggyback capacity, ~1400B/40B), so V>>U
    drains at aggregate packet bandwidth, T_99.5 ~ V*ln(200)/(g*P)
    gossip ticks, instead of in ceil(V/U) slot-turnover waves (the
    reference's per-node broadcast queues are >=4096 deep,
    lib/serf/serf.go:20-24 — no wave structure exists there).  Which
    particular deaths an observer has heard is not tracked per subject
    (that matrix is the O(N*V) the design avoids); belief queries for
    bulk subjects are expectations over the uniform piggyback
    selection, and commit to the dead baseline happens at the same
    99.5% coverage bar as the slot channel.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as _np
from flax import struct

from consul_tpu.config import GossipConfig, SimConfig
from consul_tpu.ops import gossip as gossip_ops
from consul_tpu.ops import rolls
from consul_tpu.utils import prng

# Rumor kinds (serf member lifecycle, consumed by the reference's leader
# reconcile loop agent/consul/leader.go:1234-1432).
ALIVE = 0
SUSPECT = 1
DEAD = 2
LEFT = 3

# Device-side cumulative tick counters (SwimState.ctr slots).  These are
# the consul.serf.* / consul.memberlist.* instrumentation of the
# reference (serf metrics in lib/serf, memberlist probeNode/gossip
# timers) recast for the sim: accumulated INSIDE the jitted tick as
# scalar reductions, fetched only at host-sync checkpoints
# (metrics_vector) — zero extra host round-trips in the hot loop.
CTR_PROBES_SENT = 0     # direct probes attempted this tick
CTR_PROBE_ACKS = 1      # probes acked (direct or via relay)
CTR_PROBE_FAILS = 2     # probe acks lost (full round failed)
CTR_SUSPICIONS = 3      # dense suspicion timers started
CTR_GOSSIP_DELIVERED = 4  # newly-learned (node, rumor) cells
CTR_GOSSIP_SERVED = 5   # piggyback cell transmissions attempted
CTR_GOSSIP_LOST = 6     # piggyback cell transmissions lost (same units)
CTR_N = 7

_NEG = _np.int32(-1)  # host-side: keep module import free of backend init


@dataclasses.dataclass(frozen=True)
class SwimParams:
    """Static (hashable) parameters baked into the jitted step."""

    n_nodes: int
    rumor_slots: int
    gossip_nodes: int
    indirect_checks: int
    probe_period_ticks: int
    probe_timeout_ms: float
    retransmit_limit: int
    suspicion_min_ticks: int
    suspicion_max_ticks: int
    declare_lag_ticks: int     # probe-cycle completion before suspect
    confirm_k: int
    alloc_cap: int
    expiry_gossip_ticks: int   # lifetime of alive/dead/left rumors
    expiry_suspect_ticks: int  # lifetime of suspect rumors (> max timeout)
    p_loss: float
    rtt_base_ms: float
    packet_msgs: int           # piggyback msgs per UDP packet (bulk channel)
    awareness_max: int         # Lifeguard LHA score cap+1 (0 disables)
    degraded_frac: float       # fraction of nodes with degraded legs
    degraded_loss: float       # their per-leg loss (vs p_loss)
    seed: int
    # nemesis masks compiled in (chaos.py): when True the tick consults
    # the per-node chaos_grp / chaos_ok state fields on every leg.
    # Static so the default (False) build keeps the hot path untouched.
    chaos: bool = False
    # ring-exchange lowering hint (ops/rolls.py): node-axis shard count
    # so cross-shard ring traffic lowers to static collective-permutes.
    # Results are identical for any value; 1 = single-device fast path.
    shard_blocks: int = 1


def make_params(gossip: GossipConfig, sim: SimConfig) -> SwimParams:
    n = sim.n_nodes
    if sim.shard_blocks > 1 and n % sim.shard_blocks:
        raise ValueError(f"shard_blocks={sim.shard_blocks} must divide "
                         f"n_nodes={n}")
    # int8 retransmit budget: the log-scaled limit is ~28 at 1M nodes
    limit = min(gossip.retransmit_limit(n), 127)
    # A rumor is fully disseminated within ~O(log N) gossip ticks; keep the
    # slot a few multiples of that so stragglers (lossy links) still hear it.
    spread = max(8, 4 * math.ceil(math.log2(n + 1)))
    return SwimParams(
        n_nodes=n,
        rumor_slots=sim.rumor_slots,
        gossip_nodes=gossip.gossip_nodes,
        indirect_checks=gossip.indirect_checks,
        probe_period_ticks=gossip.probe_period_ticks,
        probe_timeout_ms=gossip.probe_timeout * 1000.0,
        retransmit_limit=limit,
        suspicion_min_ticks=gossip.suspicion_min_ticks(n),
        suspicion_max_ticks=gossip.suspicion_max_ticks(n),
        # memberlist's probeNode declares suspect only after the FULL
        # probe cycle — direct ping (probe_timeout) then indirect
        # probes (another probe_timeout) — not at probe start.  The
        # sim's timers are anchored at the probe tick, so the cycle
        # length is added to every suspicion timeout; without it the
        # sim ran a systematic ~probe_interval fast vs the live pool
        # (LIVE_VS_SIM r4: ratios 0.70-0.87).
        declare_lag_ticks=math.ceil(2 * gossip.probe_timeout
                                    / gossip.gossip_interval),
        confirm_k=gossip.confirm_k(),
        # clamp: top_k(k=alloc_cap) runs over [N] wants AND [U] free
        # slots — tiny pools (e.g.
        # per-segment sims) must not exceed their own node count, and
        # the free-slot top_k must not exceed the slot table
        alloc_cap=min(sim.alloc_cap, sim.n_nodes, sim.rumor_slots),
        expiry_gossip_ticks=spread,
        expiry_suspect_ticks=gossip.suspicion_max_ticks(n) + spread,
        p_loss=sim.p_loss,
        rtt_base_ms=sim.rtt_base_ms,
        packet_msgs=gossip.packet_msgs(),
        awareness_max=gossip.awareness_max_multiplier,
        degraded_frac=sim.degraded_frac,
        degraded_loss=sim.degraded_loss,
        seed=sim.seed,
        chaos=sim.chaos,
        shard_blocks=sim.shard_blocks,
    )


@struct.dataclass
class SwimState:
    """Full simulator state; a pytree of device arrays (N = nodes, U = slots)."""

    tick: jnp.ndarray            # int32 scalar
    # --- ground truth ---
    up: jnp.ndarray              # [N] bool: process actually running
    member: jnp.ndarray          # [N] bool: joined and not intentionally left
    # incarnations stay int32: refutation counts are unbounded over a pool's
    # lifetime, and the alive-map packing (inc * U + slot) needs the range
    incarnation: jnp.ndarray     # [N] int32: self incarnation number
    coords: jnp.ndarray          # [N, D] float32: latent latency-space coords (ms)
    # --- committed (post-rumor) global belief baseline ---
    committed_dead: jnp.ndarray  # [N] bool
    committed_left: jnp.ndarray  # [N] bool
    committed_inc: jnp.ndarray   # [N] int32: highest fully-disseminated alive
    #                                 incarnation (refutations outlive their
    #                                 rumor slot, like memberlist node tables)
    # --- rumor table ---
    r_active: jnp.ndarray        # [U] bool
    r_kind: jnp.ndarray          # [U] int8 (ALIVE/SUSPECT/DEAD/LEFT)
    r_subject: jnp.ndarray       # [U] int32
    r_inc: jnp.ndarray           # [U] int32
    r_start: jnp.ndarray         # [U] int32: origin tick
    r_confirm: jnp.ndarray       # [U] int8: independent suspicion
    #                                 confirmations (clamped <= 64)
    r_coverage: jnp.ndarray      # [U] float32: live-member coverage of each
    #                                 slot, refreshed by the probe-tick expiry
    #                                 pass (metrics read it instead of paying
    #                                 their own [N, U] reduction; <= one
    #                                 probe period stale)
    # --- per (node, rumor) ---
    know: jnp.ndarray            # [N, U] bool
    # learn_tick is the WRAPPING low 16 bits of the learn tick: it is only
    # ever consumed as an age (tick - learn_tick) while its slot is active,
    # and slots live <= 4*expiry_suspect_ticks << 2^15 ticks, so int16
    # modular subtraction (_age) is exact — and the [N, U] int32 buffer was
    # the single biggest HBM tenant of the hot loop (128 MB at 1M x 32).
    learn_tick: jnp.ndarray      # [N, U] int16 (wrapping; see _age)
    sends_left: jnp.ndarray      # [N, U] int8
    # --- dense per-subject suspicion (detection path) ---
    # Suspicion TIMING lives here, O(N), so detection can never be
    # gated by rumor-slot pressure: in memberlist every dead node's
    # prober runs its own suspicion timer concurrently (per-node state
    # tables), so a rack-scale kill is detected in ONE suspicion
    # timeout, not in table-sized waves.  The slot table still carries
    # suspicion/death to other nodes (belief + refutation); this pair
    # only guarantees when the first holder declares death.
    sus_start: jnp.ndarray       # [N] int32: first failed-probe tick, -1=none
    sus_confirm: jnp.ndarray     # [N] int8: independent confirmations
    #                                 (clamped <= 64)
    # --- bulk death channel (mass-event dissemination) ---
    # When V suspicion-expired subjects exceed free rumor slots, the
    # overflow disseminates here: exact per NODE, mean-field per SUBJECT.
    # bulk_heard[i] = how many of the current bulk deaths node i has
    # heard; per ring contact a sender transfers at most `packet_msgs`
    # of them (memberlist's per-packet piggyback capacity), so V >> U
    # drains at aggregate packet bandwidth — no ceil(V/U) wave
    # structure (per-node broadcast queues are >=4096 deep in the
    # reference, lib/serf/serf.go:20-24).
    bulk_member: jnp.ndarray     # [N] bool: subject is in the bulk channel
    bulk_heard: jnp.ndarray      # [N] float32: expected bulk deaths heard
    bulk_cov: jnp.ndarray        # [N] float32: per-SUBJECT coverage estimate
    # --- Lifeguard Local Health Awareness (gossip.mdx:45-60) ---
    # Each node judges its OWN health from probe outcomes: failed
    # probes whose relays did not NACK (our receive path is suspect)
    # raise the score; acked probes lower it; refuting a suspicion of
    # ourselves raises it.  The score stretches the node's probe rate
    # and timeout by (score+1), so a degraded prober originates fewer
    # (and slower-declared) suspicions — the false-positive damper.
    awareness: jnp.ndarray       # [N] int32 health score, [0, max-1]
    sus_count: jnp.ndarray       # [N] int32: suspicion starts per subject
    #                               (diagnostic: false-suspicion counting)
    # --- nemesis fault masks (consul_tpu/chaos.py) ---
    # Evolved on a HOST-side schedule between device scans (plain
    # state fields, so updating them never recompiles the tick) and
    # consumed only when params.chaos is set.  chaos_grp partitions
    # the pool: a leg delivers only between same-group endpoints
    # (group 0 = everyone, the healed default).  chaos_ok is a
    # per-node delivery-rate multiplier in [0, 1] (1 = healthy): a leg
    # between i and j delivers with ok_i * ok_j on top of the baseline
    # loss — loss bursts set it globally, asymmetric degradation sets
    # it per node.
    chaos_grp: jnp.ndarray       # [N] int16 partition group id
    chaos_ok: jnp.ndarray        # [N] float32 delivery multiplier
    # --- device-side telemetry counters (CTR_* slots above) ---
    # Cumulative f32 — tiny [CTR_N] vector, replicated under sharding
    # (parallel/mesh.py _node_shardable rejects it), read back only at
    # host-sync checkpoints.  float32 gives ~7 significant digits:
    # past 2^24 a counter is accurate RELATIVELY (~1e-7 — adds below
    # that fraction of the running total round away), which is the
    # operator-telemetry contract (go-metrics sinks are float32 too);
    # int32 would overflow outright at 1M-node gossip volumes and x64
    # is disabled in this rig.
    ctr: jnp.ndarray             # [CTR_N] float32


def init_state(params: SwimParams, key=None,
               n_initial: int = 0) -> SwimState:
    """`n_initial` > 0 starts the pool sparsely populated: ids beyond
    it are unprovisioned (not members, not up) until `rejoin` brings
    them in — elastic membership over a fixed device allocation
    (SURVEY §5.3: joins/leaves at runtime).

    Sizing guidance: the probe ring is drawn over ALL N slots, so a
    probe landing on an unprovisioned slot is a skipped round and
    detection latency inflates by roughly n_nodes/members.  Sparse
    pools are for growth HEADROOM (e.g. 50-90% full), not for running
    1k members in a 1M-slot pool; size n_nodes near expected peak
    membership.  (A member-prefix ring would fix this but costs the
    gather the ring-rotation design exists to avoid.)"""
    n, u = params.n_nodes, params.rumor_slots
    if n_initial < 0 or n_initial > n:
        raise ValueError(f"n_initial={n_initial} outside [0, {n}]")
    if key is None:
        key = jax.random.PRNGKey(params.seed ^ 0x5EEDF00D)
    coords = jax.random.uniform(key, (n, 2), jnp.float32) * 30.0
    present = jnp.ones((n,), bool) if not n_initial \
        else jnp.arange(n) < n_initial
    return SwimState(
        tick=jnp.int32(0),
        up=present,
        # DISTINCT buffer from `up`: a donated first call (bench scan,
        # chaos build) flattens the state pytree into executable args,
        # and XLA rejects donating the same buffer twice — aliased
        # leaves made init_state's output donation-unsafe on every
        # backend that honors donation (hlo_lint finding, ISSUE 20)
        member=present.copy(),
        incarnation=jnp.zeros((n,), jnp.int32),
        coords=coords,
        committed_dead=jnp.zeros((n,), bool),
        committed_left=jnp.zeros((n,), bool),
        committed_inc=jnp.zeros((n,), jnp.int32),
        r_active=jnp.zeros((u,), bool),
        r_kind=jnp.zeros((u,), jnp.int8),
        r_subject=jnp.zeros((u,), jnp.int32),
        r_inc=jnp.zeros((u,), jnp.int32),
        r_start=jnp.zeros((u,), jnp.int32),
        r_confirm=jnp.zeros((u,), jnp.int8),
        r_coverage=jnp.zeros((u,), jnp.float32),
        know=jnp.zeros((n, u), bool),
        learn_tick=jnp.zeros((n, u), jnp.int16),
        sends_left=jnp.zeros((n, u), jnp.int8),
        sus_start=jnp.full((n,), -1, jnp.int32),
        sus_confirm=jnp.zeros((n,), jnp.int8),
        bulk_member=jnp.zeros((n,), bool),
        bulk_heard=jnp.zeros((n,), jnp.float32),
        bulk_cov=jnp.zeros((n,), jnp.float32),
        awareness=jnp.zeros((n,), jnp.int8),
        sus_count=jnp.zeros((n,), jnp.int32),
        chaos_grp=jnp.zeros((n,), jnp.int16),
        chaos_ok=jnp.ones((n,), jnp.float32),
        ctr=jnp.zeros((CTR_N,), jnp.float32),
    )


# ---------------------------------------------------------------------------
# derived per-subject maps + small-table lookups
# ---------------------------------------------------------------------------

def _subject_map(params: SwimParams, s: SwimState, kind: int, values) -> jnp.ndarray:
    """Scatter rumor-table `values` into a dense [N] subject-indexed map.

    Inactive/other-kind slots write -1; result is -1 where no rumor exists.
    (A [U]-index scatter — U is tiny, this is cheap.)
    """
    mask = s.r_active & (s.r_kind == kind)
    subj = jnp.where(mask, s.r_subject, 0)
    val = jnp.where(mask, values, _NEG)
    return jnp.full((params.n_nodes,), -1, jnp.int32).at[subj].max(val)


def _maps(params: SwimParams, s: SwimState):
    """Build the four [N] subject-indexed maps.

    Built ONCE per probe tick (step_with_obs) and THREADED through the
    probe/suspicion/dense passes with incremental [A]-sized updates
    (_maps_add / _maps_convert) instead of being rebuilt from scratch in
    every pass — four map builds per tick instead of sixteen.  The
    threaded maps can run stale against pressure EVICTION (a freed
    dead/left slot still appears): every eviction also COMMITS its
    belief (coverage >= 0.995 implies the 0.5 commit bar), so all
    downstream consumers are guarded by committed_dead/committed_left
    and the staleness is unobservable."""
    u = params.rumor_slots
    slots = jnp.arange(u, dtype=jnp.int32)
    suspect_of = _subject_map(params, s, SUSPECT, slots)
    dead_of = _subject_map(params, s, DEAD, slots)
    left_of = _subject_map(params, s, LEFT, slots)
    # alive map keeps the highest-incarnation alive rumor: value = inc*U + slot
    alive_val = _subject_map(params, s, ALIVE, s.r_inc * u + slots)
    return suspect_of, dead_of, left_of, alive_val


def _map_add(map_n: jnp.ndarray, subjects: jnp.ndarray,
             slots: jnp.ndarray, ok: jnp.ndarray) -> jnp.ndarray:
    """Record <=A freshly allocated (subject, slot) pairs in an [N] map —
    an [A]-scatter, not a rebuild."""
    return map_n.at[jnp.where(ok, subjects, 0)].max(
        jnp.where(ok, slots, _NEG))


def _maps_convert(maps, s: SwimState, convert: jnp.ndarray):
    """Move suspect slots that converted to DEAD (convert: [U] mask)
    from suspect_of to dead_of.  Subjects never hold two suspect slots
    (_originate's `fresh` gate), so clearing the converted subject's
    suspect entry is exact."""
    suspect_of, dead_of, left_of, alive_val = maps
    u = s.r_active.shape[0]
    subj = jnp.where(convert, s.r_subject, 0)
    suspect_of = suspect_of.at[subj].min(
        jnp.where(convert, _NEG, jnp.int32(1 << 30)))
    dead_of = dead_of.at[subj].max(
        jnp.where(convert, jnp.arange(u, dtype=jnp.int32), _NEG))
    return suspect_of, dead_of, left_of, alive_val


def _row_gather(mat: jnp.ndarray, cols: jnp.ndarray):
    """mat[i, cols[i]] with cols possibly -1 (returns False/0 there).

    Formulated as a one-hot compare+reduce over the small minor axis: a
    per-row gather on a tiny minor dim lowers to a degenerate (serialized)
    TPU gather — the [N, U] compare is ~15x faster at N=1M."""
    u = mat.shape[1]
    onehot = cols[:, None] == jnp.arange(u, dtype=jnp.int32)[None, :]
    if mat.dtype == jnp.bool_:
        return jnp.any(mat & onehot, axis=1)
    return jnp.sum(jnp.where(onehot, mat, 0), axis=1)


def _table_lookup(vec_u: jnp.ndarray, cols: jnp.ndarray):
    """vec_u[cols] for a tiny [U] table and [N] cols — one-hot compare,
    no gather.  cols=-1 yields 0."""
    u = vec_u.shape[0]
    onehot = cols[:, None] == jnp.arange(u, dtype=jnp.int32)[None, :]
    return jnp.sum(jnp.where(onehot, vec_u[None, :], 0), axis=1)


def _age(tick: jnp.ndarray, learn_tick: jnp.ndarray) -> jnp.ndarray:
    """Age in ticks of a WRAPPING int16 learn stamp (SwimState.learn_tick).

    int16 modular subtraction is exact while the true age is < 2^15
    ticks; every consumer compares ages against suspicion/expiry windows
    that are orders of magnitude shorter than that, and a slot never
    outlives 4x its expiry window, so the wrap can never be observed.
    Stays int16 — compare against `_t16(timeout)`, never widen the
    [N, U] buffer back to int32 (the widening pass was measurably the
    cost the narrowing removed)."""
    return tick.astype(jnp.int16) - learn_tick


def _t16(timeout: jnp.ndarray) -> jnp.ndarray:
    """Timeout windows cast to the int16 age domain (values are
    O(suspicion_max + lag) ≪ 2^15, see _age)."""
    return timeout.astype(jnp.int16)


def _suspicion_timeout_ticks(params: SwimParams, confirm: jnp.ndarray) -> jnp.ndarray:
    """Lifeguard: timer decays from max to min as confirmations arrive.

    timeout = max - (max - min) * log(c+1)/log(k+1), floored at min,
    plus the probe-cycle declare lag (timers here anchor at the probe
    tick; memberlist's suspect state begins a full probe cycle later).
    """
    mn = jnp.float32(params.suspicion_min_ticks)
    mx = jnp.float32(params.suspicion_max_ticks)
    frac = jnp.log(confirm.astype(jnp.float32) + 1.0) / math.log(params.confirm_k + 1.0)
    t = mx - (mx - mn) * jnp.clip(frac, 0.0, 1.0)
    return jnp.ceil(jnp.maximum(t, mn)).astype(jnp.int32) \
        + params.declare_lag_ticks


# ---------------------------------------------------------------------------
# belief queries (used by probe target filtering and by metrics)
# ---------------------------------------------------------------------------

def _believes_down_shift(params: SwimParams, s: SwimState, maps,
                         shift, tick: jnp.ndarray) -> jnp.ndarray:
    """[N] bool: does node i believe its ring peer (i + shift) % N is dead
    or left?  All subject-side lookups are rotations (no gathers).

    A node believes a subject down when it (a) is committed dead/left,
    (b) knows a dead/left rumor for it, or (c) holds an expired, unrefuted
    suspicion for it.  Mirrors memberlist state precedence: alive with a
    higher incarnation refutes suspect; dead is terminal.
    """
    suspect_of, dead_of, left_of, alive_val = maps
    u = params.rumor_slots
    down = rolls.pull(s.committed_dead | s.committed_left, shift, blocks=params.shard_blocks)
    down |= _row_gather(s.know, rolls.pull(dead_of, shift, blocks=params.shard_blocks))
    down |= _row_gather(s.know, rolls.pull(left_of, shift, blocks=params.shard_blocks))
    # expired unrefuted suspicion
    ss = rolls.pull(suspect_of, shift, blocks=params.shard_blocks)
    know_s = _row_gather(s.know, ss)
    learn = _row_gather(s.learn_tick, ss)
    conf = _table_lookup(s.r_confirm, ss)
    expired = know_s & (_age(tick, learn)
                        >= _t16(_suspicion_timeout_ticks(params, conf)))
    av = rolls.pull(alive_val, shift, blocks=params.shard_blocks)
    a_slot = jnp.where(av >= 0, av % u, -1)
    a_inc = jnp.where(av >= 0, av // u, -1)
    s_inc = _table_lookup(s.r_inc, ss)
    refuted = (av >= 0) & (a_inc > s_inc) & _row_gather(s.know, a_slot)
    refuted |= s_inc < rolls.pull(s.committed_inc, shift, blocks=params.shard_blocks)
    down |= expired & ~refuted
    # bulk-channel subjects are past their suspicion timeout and
    # awaiting only dissemination — probers skip them (memberlist nodes
    # that marked X dead stop probing X; here the skip is global one
    # detection-latency ahead of per-observer hearing, documented)
    down |= rolls.pull(s.bulk_member, shift, blocks=params.shard_blocks)
    return down


def believed_down_fraction(params: SwimParams, s: SwimState, subject: int) -> jnp.ndarray:
    """Fraction of live members (excluding the subject) that believe `subject`
    is down.  The convergence metric for the north-star benchmark.

    Single-subject formulation: rumor-table masks over the tiny U axis —
    no [N] subject maps, no gathers (this runs inside the bench scan)."""
    n, u = params.n_nodes, params.rumor_slots
    is_dl = s.r_active & ((s.r_kind == DEAD) | (s.r_kind == LEFT)) \
        & (s.r_subject == subject)
    is_s = s.r_active & (s.r_kind == SUSPECT) & (s.r_subject == subject)
    is_a = s.r_active & (s.r_kind == ALIVE) & (s.r_subject == subject)

    down = s.committed_dead[subject] | s.committed_left[subject]   # scalar
    down_i = jnp.any(s.know & is_dl[None, :], axis=1) | down       # [N]

    # expired, unrefuted suspicion
    timeout = _suspicion_timeout_ticks(params, s.r_confirm)        # [U]
    age_ok = _age(s.tick, s.learn_tick) >= _t16(timeout)[None, :]  # [N, U]
    a_inc_known = jnp.max(
        jnp.where(is_a[None, :] & s.know, s.r_inc[None, :], -1), axis=1)  # [N]
    refuted = (a_inc_known[:, None] > s.r_inc[None, :]) \
        | (s.r_inc[None, :] < s.committed_inc[subject])            # [N, U]
    down_i |= jnp.any(s.know & is_s[None, :] & age_ok & ~refuted, axis=1)

    observer = s.up & s.member & (jnp.arange(n) != subject)
    frac = jnp.sum(down_i & observer) / jnp.maximum(jnp.sum(observer), 1)
    # bulk-channel subject: its own mean-field coverage estimate is the
    # expected fraction of observers that heard its death
    return jnp.maximum(frac, jnp.where(s.bulk_member[subject],
                                       s.bulk_cov[subject], 0.0))


# ---------------------------------------------------------------------------
# rumor allocation / origination
# ---------------------------------------------------------------------------

def _top_k_sharded(x: jnp.ndarray, k: int, blocks: int):
    """lax.top_k over a node-sharded [N] vector without a full gather:
    per-block top-k (local to each shard), then top-k over the tiny
    [blocks * k] candidate set (replicated).  RESULT-identical to flat
    lax.top_k for any `blocks` — including tie-breaks: top_k prefers
    the earlier index among equals, candidates are emitted in global
    index order within each value, and a candidate-position tie-break
    therefore picks the same global index the flat sort would."""
    n = x.shape[0]
    if blocks <= 1 or n % blocks or k > n // blocks:
        return jax.lax.top_k(x, k)
    ell = n // blocks
    xb = x.reshape(blocks, ell)
    # per-block selection by k rounds of (max, argmax, one-hot mask):
    # row-wise reductions and elementwise selects partition cleanly
    # where lax.top_k's sort lowering all-gathers its index operand
    lo = jnp.iinfo(x.dtype).min if jnp.issubdtype(x.dtype, jnp.integer) \
        else jnp.finfo(x.dtype).min
    cols = jnp.arange(ell, dtype=jnp.int32)[None, :]
    vs, is_ = [], []
    cur = xb
    for _ in range(k):
        v = jnp.max(cur, axis=1)                         # [B]
        i = jnp.argmax(cur, axis=1).astype(jnp.int32)    # first max
        vs.append(v)
        is_.append(i)
        cur = jnp.where(cols == i[:, None], lo, cur)
    v = jnp.stack(vs, axis=1)                            # [B, k]
    gi = jnp.stack(is_, axis=1) \
        + (jnp.arange(blocks, dtype=jnp.int32) * ell)[:, None]
    # candidate round over the tiny replicated [B * k] set; ties keep
    # global index order because candidates are emitted block-major
    v2, j = jax.lax.top_k(v.reshape(-1), k)
    return v2, gi.reshape(-1)[j]


def _originate(params: SwimParams, s: SwimState, want_score: jnp.ndarray,
               kind: int, inc_of_subject: jnp.ndarray,
               row_subject: jnp.ndarray):
    """Allocate up to `alloc_cap` rumor slots for subjects with want_score > 0.

    `inc_of_subject`: [N] int32 incarnation to record per subject.
    `row_subject`: [N] int32 — the subject node i originates/knows a rumor
    about at birth (-1 = none).  All table updates are [U]-space scatters;
    knowledge seeding matches row subjects against the <=alloc_cap freshly
    allocated subjects with an [N, A] compare (no [N]-index gathers — this
    runs inside the per-tick hot loop at N=1M).

    Returns (state, (subjects, slots, ok)): the <=A allocated (subject,
    slot) pairs with their validity mask, so callers can patch the
    threaded subject maps (_map_add) instead of rebuilding them.
    """
    a = params.alloc_cap
    u = params.rumor_slots
    # Pressure eviction (memberlist's broadcast queue drops the most-
    # retransmitted broadcasts on overflow, lib/serf/serf.go:20-24):
    # when demand exceeds the free slots, release slots that are
    # already fully disseminated (>=99.5% of live members carry them)
    # ahead of their nominal lifetime.  Commit bookkeeping runs
    # exactly as at natural expiry.  SUSPECT slots are NEVER evicted:
    # a suspicion must live out its timeout to convert to dead —
    # evicting a fully-covered suspect would reset its per-holder
    # timers on reallocation and livelock the whole table.
    demand = jnp.sum(want_score > 0)
    free = jnp.sum(~s.r_active)

    def evict(st):
        live = st.up & st.member
        n_live = jnp.maximum(jnp.sum(live), 1)
        coverage = jnp.sum(st.know & live[:, None],
                           axis=0).astype(jnp.float32) / n_live
        done = st.r_active & (coverage >= 0.995) \
            & (st.r_kind != SUSPECT)
        return _release(st, done, coverage)

    s = jax.lax.cond(demand > free, evict, lambda st: st, s)
    score, subjects = _top_k_sharded(want_score, a, params.shard_blocks)
    free_score, slots = jax.lax.top_k(jnp.where(s.r_active, 0, 1) *
                                      (u - jnp.arange(u, dtype=jnp.int32)), a)
    ok = (score > 0) & (free_score > 0)
    oob = jnp.where(ok, slots, u)                              # drop if !ok

    r_active = s.r_active.at[oob].set(True, mode="drop")
    r_kind = s.r_kind.at[oob].set(jnp.int8(kind), mode="drop")
    r_subject = s.r_subject.at[oob].set(subjects, mode="drop")
    r_inc = s.r_inc.at[oob].set(inc_of_subject[subjects], mode="drop")
    r_start = s.r_start.at[oob].set(s.tick, mode="drop")
    r_confirm = s.r_confirm.at[oob].set(jnp.int8(1), mode="drop")

    # row i knows the rumor whose subject matches row_subject[i]: compare
    # against the A allocated (subject, slot) pairs, then one-hot the slot
    match_subj = jnp.where(ok, subjects, -2)                   # [A]
    match = row_subject[:, None] == match_subj[None, :]        # [N, A]
    slot_row = jnp.max(jnp.where(match, slots[None, :], -1), axis=1)  # [N]
    cell = (slot_row[:, None] == jnp.arange(u)[None, :]) \
        & (slot_row >= 0)[:, None]
    know = s.know | cell
    learn_tick = jnp.where(cell, s.tick.astype(jnp.int16), s.learn_tick)
    sends_left = jnp.where(cell, jnp.int8(params.retransmit_limit),
                           s.sends_left)
    s = s.replace(r_active=r_active, r_kind=r_kind, r_subject=r_subject,
                  r_inc=r_inc, r_start=r_start, r_confirm=r_confirm,
                  know=know, learn_tick=learn_tick, sends_left=sends_left)
    return s, (subjects, slots, ok)


# ---------------------------------------------------------------------------
# step phases
# ---------------------------------------------------------------------------

@struct.dataclass
class ProbeObs:
    """Per-node probe measurements from one probe round; acked direct probes
    carry an RTT sample (the serf coordinate client updates on every probe
    ack — reference agent/agent.go:1629).  The probe target of node i is
    its ring peer (i + shift) % N."""

    shift: jnp.ndarray    # int32 scalar ring offset (0 = no probe round)
    rtt_ms: jnp.ndarray   # [N] float32
    acked: jnp.ndarray    # [N] bool (direct ack — RTT sample is meaningful)


def _empty_obs(params: SwimParams) -> ProbeObs:
    n = params.n_nodes
    return ProbeObs(shift=jnp.int32(0),
                    rtt_ms=jnp.ones((n,), jnp.float32),
                    acked=jnp.zeros((n,), bool))


def _probe_round(params: SwimParams, s: SwimState, maps):
    """One SWIM probe round: ring probe + k indirect probes + suspicion.

    Reference behavior: memberlist probe loop (probe_interval /
    probe_timeout / indirect_checks — options.mdx:1509-1532); probe order
    is memberlist's shuffled ring, realized as a shared random offset.

    `maps` is the tick's threaded subject-map tuple (_maps); returns
    (state, obs, maps) with the freshly allocated suspect slots patched
    in, so downstream passes reuse it instead of rebuilding.
    """
    n = params.n_nodes
    tick = s.tick
    kt = prng.tick_key(params.seed, tick, 1)
    k_off, k_direct, k_leg, k_rtt, k_lha = jax.random.split(kt, 5)
    offs = rolls.offsets(k_off, n, 1 + params.indirect_checks)
    d = offs[0]

    live = s.up & s.member
    # Lifeguard LHA: a node with health score h probes at 1/(h+1) of
    # the base rate and waits (h+1)x the base timeout (memberlist
    # scales its probe ticker and timeout by the awareness score).
    # The rate stretch is realized probabilistically per round —
    # same expected rate, no cross-node phase alignment.
    if params.awareness_max > 0:
        score = jnp.clip(s.awareness, 0, params.awareness_max - 1)
        mult = (score + 1).astype(jnp.float32)
        lha_go = jax.random.uniform(k_lha, (n,)) * mult < 1.0
    else:
        mult = jnp.ones((n,), jnp.float32)
        lha_go = jnp.ones((n,), bool)
    prober = live & lha_go
    skip = _believes_down_shift(params, s, maps, d, tick)
    t_up = rolls.pull(live, d, blocks=params.shard_blocks)

    # per-node leg delivery rate: a degraded node (Lifeguard's bad-NIC
    # scenario) loses each of ITS legs at degraded_loss; a leg between
    # i and j delivers at the WORSE endpoint's rate, min(ok_i, ok_j) —
    # normal-normal legs keep exactly the baseline p_loss semantics
    if params.degraded_frac > 0.0:
        h = (jnp.arange(n, dtype=jnp.uint32) * jnp.uint32(2654435761)
             + jnp.uint32(params.seed))
        degraded = (h.astype(jnp.float32) / jnp.float32(2 ** 32)) \
            < params.degraded_frac
        ok_node = jnp.where(degraded, 1.0 - params.degraded_loss,
                            1.0 - params.p_loss)
    else:
        ok_node = jnp.full((n,), 1.0 - params.p_loss, jnp.float32)
    if params.chaos:
        # nemesis: per-node delivery multiplier folds into the leg
        # rate; partition groups gate each leg pairwise (a leg only
        # exists between same-group endpoints)
        ok_node = ok_node * s.chaos_ok
        grp = s.chaos_grp
        same_t = grp == rolls.pull(grp, d, blocks=params.shard_blocks)          # origin <-> target

    # direct probe: two UDP legs + RTT under the (LHA-scaled) timeout
    rtt = jnp.linalg.norm(s.coords - rolls.pull(s.coords, d, blocks=params.shard_blocks), axis=-1) \
        + params.rtt_base_ms
    rtt = rtt * (1.0 + jax.random.exponential(k_rtt, (n,)) * 0.1)
    ok_t = rolls.pull(ok_node, d, blocks=params.shard_blocks)
    legs_ok = jax.random.uniform(k_direct, (n,)) \
        < jnp.minimum(ok_node, ok_t) ** 2
    if params.chaos:
        legs_ok &= same_t
    direct_ack = t_up & legs_ok & (2.0 * rtt < params.probe_timeout_ms * mult)

    # k indirect probes through ring relays, leg-resolved so relays
    # can NACK (Lifeguard): origin->relay (l1), relay<->target (l23),
    # relay->origin return (l4 — carries the ack, or the NACK when the
    # relay reached the origin but could not reach the target).
    # indirect_checks=0 is a valid memberlist tuning: no relays, no
    # NACK channel — direct acks only.
    if params.indirect_checks > 0:
        kA, kB, kC = jax.random.split(k_leg, 3)
        shape = (n, params.indirect_checks)
        ok_r = jnp.stack([rolls.pull(ok_node, offs[1 + k], blocks=params.shard_blocks)
                          for k in range(params.indirect_checks)], axis=-1)
        uA = jax.random.uniform(kA, shape)
        uB = jax.random.uniform(kB, shape)
        uC = jax.random.uniform(kC, shape)
        l1 = uA < jnp.minimum(ok_node[:, None], ok_r)
        l23 = uB < jnp.minimum(ok_r, ok_t[:, None]) ** 2
        l4 = uC < jnp.minimum(ok_r, ok_node[:, None])
        if params.chaos:
            # partition gating per leg: origin<->relay and
            # relay<->target must each be same-group
            rgrp = jnp.stack([rolls.pull(grp, offs[1 + k], blocks=params.shard_blocks)
                              for k in range(params.indirect_checks)],
                             axis=-1)
            same_r = rgrp == grp[:, None]
            same_rt = rgrp == rolls.pull(grp, d, blocks=params.shard_blocks)[:, None]
            l1 &= same_r
            l4 &= same_r
            l23 &= same_rt
        relay_ok = jnp.stack([rolls.pull(live, offs[1 + k], blocks=params.shard_blocks)
                              for k in range(params.indirect_checks)],
                             axis=-1)
        ind_ack = relay_ok & l1 & (t_up[:, None] & l23) & l4
        nacked = relay_ok & l1 & ~(t_up[:, None] & l23) & l4
        ack = direct_ack | jnp.any(ind_ack, axis=-1)
    else:
        nacked = jnp.zeros((n, 0), bool)
        ack = direct_ack

    # a target outside the membership (never provisioned, or left) is
    # not probed at all — memberlist only probes its member list; without
    # this gate a sparse pool suspects and eventually commits phantom
    # deaths for every free slot, saturating the rumor table
    t_member = rolls.pull(s.member, d, blocks=params.shard_blocks)
    failed = prober & ~skip & ~ack & t_member
    # Lifeguard self-awareness update (memberlist probeNode): an acked
    # probe is evidence of our own health (-1); a failed probe is
    # charged to US as far as the k expected relay NACKs did not come
    # back — when every relay NACKed, the target (not the prober) is
    # the problem and the delta is 0.  ALL k sent indirect probes
    # count as NACK-expected: the prober cannot tell a dead relay from
    # its own lost legs, so either raises its score (exactly
    # memberlist's expectedNacks accounting).  With indirect_checks=0
    # no NACKs were ever expected, so a failed probe carries no
    # self-evidence at all and the delta is 0 (ADVICE r5: memberlist's
    # expectedNacks accounting, not a flat +1).
    if params.awareness_max > 0:
        probed = prober & ~skip & t_member
        k = params.indirect_checks
        nack_count = jnp.sum(nacked, axis=-1).astype(jnp.int32)
        delta_fail = (k - nack_count) if k > 0 else 0
        delta = jnp.where(probed & ack, -1,
                          jnp.where(failed, delta_fail, 0))
        s = s.replace(awareness=jnp.clip(
            s.awareness.astype(jnp.int32) + delta, 0,
            params.awareness_max - 1).astype(jnp.int8))
    # per-subject suspector count: the shift is a bijection — exactly one
    # prober per subject per round (cnt in {0,1}), like memberlist's ring
    cnt = rolls.push(failed, d, blocks=params.shard_blocks).astype(jnp.int32)
    suspect_of, dead_of, left_of, _ = maps

    # (a) confirm existing suspicions (Lifeguard): each independent suspector
    # this round shortens the timer; they also start carrying the rumor.
    r_confirm = s.r_confirm.astype(jnp.int32) + jnp.where(
        s.r_active & (s.r_kind == SUSPECT), jnp.minimum(cnt[s.r_subject], 8), 0)
    r_confirm = jnp.minimum(r_confirm, 64).astype(jnp.int8)
    es = rolls.pull(suspect_of, d, blocks=params.shard_blocks)                              # [N] existing slot
    joiner = failed & (es >= 0)
    cell = (es[:, None] == jnp.arange(params.rumor_slots)[None, :]) \
        & joiner[:, None]
    know = s.know | cell
    learn_tick = jnp.where(cell & ~s.know, tick.astype(jnp.int16),
                           s.learn_tick)
    sends_left = jnp.where(cell & ~s.know,
                           jnp.int8(params.retransmit_limit), s.sends_left)
    s = s.replace(r_confirm=r_confirm, know=know, learn_tick=learn_tick,
                  sends_left=sends_left)

    # (b) dense suspicion timers (detection): start/confirm per
    # SUBJECT, independent of slot availability — every victim of a
    # correlated kill starts its timer THIS round, exactly like the
    # per-node tables in memberlist (suspicion timeout math
    # options.mdx:1509-1532)
    suspected = cnt > 0
    start_new = suspected & (s.sus_start < 0) \
        & ~s.committed_dead & ~s.committed_left & s.member
    sus_start = jnp.where(start_new, tick, s.sus_start)
    sus_confirm = jnp.where(
        start_new, 1,
        jnp.where(suspected & (s.sus_start >= 0),
                  jnp.minimum(s.sus_confirm.astype(jnp.int32) + cnt, 64),
                  s.sus_confirm.astype(jnp.int32))).astype(jnp.int8)
    s = s.replace(sus_start=sus_start, sus_confirm=sus_confirm,
                  sus_count=s.sus_count + start_new.astype(jnp.int32))

    # device-side probe counters (consul.serf.probe.* / memberlist
    # probeNode): scalar reductions folded into the jitted round
    probed = prober & ~skip & t_member
    f32 = jnp.float32
    s = s.replace(ctr=s.ctr
                  .at[CTR_PROBES_SENT].add(jnp.sum(probed).astype(f32))
                  .at[CTR_PROBE_ACKS].add(
                      jnp.sum(probed & ack).astype(f32))
                  .at[CTR_PROBE_FAILS].add(jnp.sum(failed).astype(f32))
                  .at[CTR_SUSPICIONS].add(
                      jnp.sum(start_new).astype(f32)))

    # (c) originate new suspect rumors for subjects with no existing
    # rumor (belief spread + refutation channel; timing no longer
    # depends on winning a slot)
    fresh = (cnt > 0) & (suspect_of < 0) & (dead_of < 0) & (left_of < 0) \
        & ~s.committed_dead & ~s.committed_left
    want = jnp.where(fresh, cnt, 0)

    target = (jnp.arange(n, dtype=jnp.int32) + d) % n
    row_subject = jnp.where(failed, target, -1)
    s, alloc = _originate(params, s, want, SUSPECT, s.incarnation,
                          row_subject)
    # patch the threaded maps with this round's suspect allocations
    suspect_of = _map_add(suspect_of, *alloc)
    maps = (suspect_of, dead_of, left_of, maps[3])
    obs = ProbeObs(shift=d, rtt_ms=2.0 * rtt,
                   acked=prober & ~skip & direct_ack)
    return s, obs, maps


def _suspicion_expiry(params: SwimParams, s: SwimState):
    """Holders whose suspicion timer expired declare the subject dead; the
    first expiry originates a `dead` rumor (memberlist: suspicion timeout
    → markDead + broadcast).

    All per-subject lookups here (highest alive incarnation, dead-rumor
    existence) index FROM the rumor table, so they are [U, U] same-subject
    compares — no [N] subject maps are built or consumed (the fused tick
    threads the [N] maps only through the passes that index by dense
    node id).  Returns (state, convert): the [U] mask of suspect slots
    converted to DEAD this tick, for patching the threaded maps."""
    n, u = params.n_nodes, params.rumor_slots
    tick = s.tick
    is_suspect = s.r_active & (s.r_kind == SUSPECT)
    timeout = _suspicion_timeout_ticks(params, s.r_confirm)      # [U]
    age = _age(tick, s.learn_tick)                               # [N, U]
    # refutation: an alive rumor for the same subject with higher
    # incarnation — same-subject max over the table, [U, U]
    u_ids = jnp.arange(u, dtype=jnp.int32)
    same = s.r_subject[:, None] == s.r_subject[None, :]          # [U, U]
    is_alive = s.r_active & (s.r_kind == ALIVE)
    av = jnp.max(jnp.where(same & is_alive[None, :],
                           s.r_inc[None, :] * u + u_ids[None, :],
                           _NEG), axis=1)                        # [U]
    a_slot = jnp.where(av >= 0, av % u, 0)
    a_inc = jnp.where(av >= 0, av // u, -1)
    refutable = (av >= 0) & (a_inc > s.r_inc)                    # [U]
    # know[:, a_slot[j]] for each slot j — [U,U] one-hot through the MXU
    # (a minor-axis take with traced indices serializes on TPU)
    col_onehot = (u_ids[:, None] == a_slot[None, :])             # [U, U]
    know_alive = jnp.einsum("nu,uv->nv", s.know.astype(jnp.int32),
                            col_onehot.astype(jnp.int32)) > 0    # [N, U]
    refuted = refutable[None, :] & know_alive
    refuted |= (s.r_inc < s.committed_inc[s.r_subject])[None, :]
    observer = (s.up & s.member)[:, None]
    expired = s.know & is_suspect[None, :] \
        & (age >= _t16(timeout)[None, :]) \
        & ~refuted & observer                                    # [N, U]
    any_exp = jnp.any(expired, axis=0)                           # [U]

    # Convert each expired suspect slot into its dead rumor IN PLACE (no
    # allocation, so conversion can't be starved under slot pressure).
    # Fidelity: the dead rumor's initial carriers are ONLY the holders
    # whose own timer expired (memberlist nodes mark dead independently);
    # unexpired and refuted carriers drop off the slot and must re-learn
    # the death through dissemination like any other receiver.  Skip when
    # a dead rumor already exists or the death is committed.
    is_dead = s.r_active & (s.r_kind == DEAD)
    dead_exists = jnp.any(same & is_dead[None, :], axis=1)       # [U]
    convert = any_exp & ~dead_exists & ~s.committed_dead[s.r_subject]
    know = jnp.where(convert[None, :], expired, s.know)
    s = s.replace(
        r_kind=jnp.where(convert, DEAD, s.r_kind),
        r_start=jnp.where(convert, tick, s.r_start),
        know=know,
        learn_tick=jnp.where(convert[None, :] & expired,
                             tick.astype(jnp.int16), s.learn_tick),
        sends_left=jnp.where(convert[None, :],
                             jnp.where(expired,
                                       jnp.int8(params.retransmit_limit),
                                       jnp.int8(0)),
                             s.sends_left))
    return s, convert


def _dense_suspicion_expiry(params: SwimParams, s: SwimState,
                            shift: jnp.ndarray, maps) -> SwimState:
    """Expire dense per-subject suspicion timers into dead rumors.

    This is the fidelity fix for correlated kills (VERDICT r3 weak #1):
    in memberlist, V simultaneous deaths run V concurrent suspicion
    timers — detection completes in ONE timeout for all of them, and
    only the dissemination of the V dead broadcasts contends for
    bandwidth.  Here:

      refute  a subject that is up auto-clears after one probe period
              (a live node hears its suspicion and broadcasts alive
              within ~1 round — the same window the slot-path
              refutation note documents);
      expire  a timed-out subject with no dead rumor yet wants a DEAD
              slot; subjects that lose the top-k retry every round
              with their elapsed timer INTACT, so slot pressure delays
              only the rumor's broadcast, never restarts its clock;
      clear   once a dead rumor exists (slot path or dense) or the
              death committed, the dense pair resets.

    The slot path (_suspicion_expiry) still converts suspect slots in
    place; this phase only originates for subjects whose suspicion
    never won a suspect slot — the pressure case.

    `maps` is the tick's threaded subject-map tuple, already patched
    with this tick's suspect allocations and dead conversions."""
    n = params.n_nodes
    tick = s.tick
    active = s.sus_start >= 0
    # refute: live subjects clear their own dense suspicion
    refute = active & s.up & s.member \
        & (tick - s.sus_start >= params.probe_period_ticks)
    timeout = _suspicion_timeout_ticks(params, s.sus_confirm)     # [N]
    expired = active & ~refute & (tick - s.sus_start >= timeout) \
        & s.member
    suspect_of, dead_of, left_of, _ = maps

    # (a) expired subjects that HOLD a suspect slot convert it in
    # place NOW: the dense timer is the original suspector's clock, so
    # a slot won late (after waiting out table pressure) must not
    # restart the wait — that restart is exactly the wave artifact.
    # Existing knowers become the dead rumor's carriers (~1 tick early
    # vs hearing the dead broadcast; documented approximation).
    is_suspect = s.r_active & (s.r_kind == SUSPECT)
    exp_u = is_suspect & expired[s.r_subject] \
        & (dead_of[s.r_subject] < 0) \
        & ~s.committed_dead[s.r_subject]                          # [U]
    s = s.replace(
        r_kind=jnp.where(exp_u, DEAD, s.r_kind),
        r_start=jnp.where(exp_u, tick, s.r_start),
        learn_tick=jnp.where(exp_u[None, :] & s.know,
                             tick.astype(jnp.int16), s.learn_tick),
        sends_left=jnp.where(exp_u[None, :] & s.know,
                             jnp.int8(params.retransmit_limit),
                             s.sends_left))
    # patch the threaded maps with (a)'s in-place conversions
    suspect_of, dead_of, left_of, _ = _maps_convert(
        (suspect_of, dead_of, left_of, None), s, exp_u)
    # subjects already owned by the slot path convert there at the
    # same timeout; dense originates only where no suspect slot exists.
    # The seeding carrier is this round's prober — require it live, or
    # the rumor would allocate with zero live carriers and rot in its
    # slot (the subject is re-probed by a DIFFERENT ring prober next
    # round, so a dead prober only defers one round)
    prober_live = rolls.push(s.up & s.member, shift, blocks=params.shard_blocks)              # [N]
    want = jnp.where(expired & (dead_of < 0) & (left_of < 0)
                     & (suspect_of < 0) & ~s.committed_dead
                     & ~s.bulk_member & prober_live, 1, 0)
    target = (jnp.arange(n, dtype=jnp.int32) + shift) % n
    # row i's probe target this round is (i+shift)%N: seed the dead
    # rumor at the prober rows whose subject wants one (pull = ring
    # rotation, no gather)
    row_subject = jnp.where(rolls.pull(want, shift, blocks=params.shard_blocks) > 0, target, -1)
    s, alloc = _originate(params, s, want, DEAD, s.incarnation,
                          row_subject)
    # overflow: expired subjects that could not win a dead slot THIS
    # round enter the bulk channel immediately — their timer already
    # ran out; making them wait for slot turnover is exactly the wave
    # artifact (memberlist enqueues every dead broadcast at once).
    # Seed: this round's prober is the first knower.
    dead_of2 = _map_add(dead_of, *alloc)   # patched, not rebuilt
    left_of2 = left_of                     # nothing adds LEFT this tick
    overflow = (want > 0) & (dead_of2 < 0)
    if params.chaos:
        # Nemesis builds disable the bulk overflow: its subject
        # marginal is a MEAN-FIELD coverage estimate that is not
        # partition-aware (a death seeded inside one partition group
        # would estimate its way to the commit bar even though the
        # other group can never hear it — exactly the false commit the
        # invariant checkers exist to catch).  Expired subjects retry
        # for dead slots each round with their timer intact (slot
        # turnover + pressure eviction carries them); chaos runs are
        # moderate-N correctness checks, and mass-event DISSEMINATION
        # fidelity stays the default build's concern.
        overflow = jnp.zeros_like(overflow)
    bulk_member = s.bulk_member | overflow
    # row i probes (i+shift)%N, and want>0 already requires the prober
    # live, so the pulled overflow mask IS the live seeding rows.
    # Clamp stale heard mass first: after the previous event fully
    # committed (or a revive withdrew the last subject) the channel is
    # empty and heard counts must restart from zero.
    v_prev = jnp.sum(s.bulk_member).astype(jnp.float32)
    seeded = rolls.pull(overflow, shift, blocks=params.shard_blocks)
    bulk_heard = jnp.minimum(
        jnp.minimum(s.bulk_heard, v_prev) + seeded.astype(jnp.float32),
        jnp.sum(bulk_member).astype(jnp.float32))
    # per-subject coverage starts at one knower (the prober)
    n_live_f = jnp.maximum(jnp.sum(s.up & s.member), 1).astype(jnp.float32)
    bulk_cov = jnp.where(overflow, 1.0 / n_live_f, s.bulk_cov)
    s = s.replace(bulk_member=bulk_member, bulk_heard=bulk_heard,
                  bulk_cov=bulk_cov)
    # clear: refuted, or a dead rumor now exists / death committed /
    # subject handed to the bulk channel
    done = refute | s.committed_dead | s.committed_left \
        | (dead_of2 >= 0) | (left_of2 >= 0) | ~s.member | bulk_member
    return s.replace(
        sus_start=jnp.where(done, -1, s.sus_start),
        sus_confirm=jnp.where(done, 0, s.sus_confirm))


def _refutation(params: SwimParams, s: SwimState) -> SwimState:
    """A live subject that hears it is suspected bumps its incarnation and
    broadcasts alive (SWIM refutation; memberlist aliveNode).

    The refutation TRANSFORMS the suspect slot in place into the alive
    broadcast — no slot allocation.  The allocate-a-new-slot formulation
    silently failed under slot exhaustion, letting false suspicions of
    live nodes expire unrefuted and commit as deaths under loss (the
    round-1 F1 gap); in-place conversion can never be starved.

    Known approximation: holders whose timer had ALREADY expired flip
    back to not-down immediately when the slot converts, where memberlist
    would correct them only when the alive(inc+1) reaches them (~log N
    ticks).  Refutation normally lands within ~1 probe round of the
    subject hearing the suspicion — two orders of magnitude inside the
    suspicion timeout — so the affected population is the rare holder
    that expired during that window.  All index work is [U]-space.

    DEAD rumors refute the same way (memberlist aliveNode on a dead
    entry: a node that learns it has been declared dead rejoins with a
    higher incarnation).  This is the partition-heal path the nemesis
    exercises: a suspicion that expired INSIDE a partition converts to
    a dead rumor the moment the partition heals, and without dead-
    refutation the rumor would spread to full coverage and commit a
    live, reachable node's death — the subject refutes it within ~1
    gossip round of hearing it instead."""
    u = params.rumor_slots
    n = params.n_nodes
    refutable = s.r_active & ((s.r_kind == SUSPECT)
                              | (s.r_kind == DEAD))
    subj = s.r_subject
    subject_knows = s.know[subj, jnp.arange(u)]                  # [U]
    need = refutable & subject_knows & s.up[subj] & s.member[subj] \
        & (s.r_inc >= s.incarnation[subj])
    # bump incarnation above the suspected one
    inc = s.incarnation.at[jnp.where(need, subj, 0)].max(
        jnp.where(need, s.r_inc + 1, _NEG))
    # Lifeguard: having to refute means our liveness was in doubt —
    # the refuter charges its own health score +1 (memberlist
    # suspectNode on self)
    awareness = s.awareness
    if params.awareness_max > 0:
        awareness = jnp.clip(
            awareness.at[jnp.where(need, subj, 0)].add(
                need.astype(jnp.int8)),
            0, params.awareness_max - 1)
    s = s.replace(awareness=awareness)
    # convert the suspect slot: alive(inc+1) broadcast seeded at the
    # subject, full retransmit budget
    onehot_subj = (jnp.arange(n)[:, None] == subj[None, :])      # [N, U]
    cell_new = need[None, :] & onehot_subj
    return s.replace(
        incarnation=inc,
        r_kind=jnp.where(need, ALIVE, s.r_kind),
        r_inc=jnp.where(need, inc[subj], s.r_inc),
        r_start=jnp.where(need, s.tick, s.r_start),
        know=jnp.where(need[None, :], cell_new, s.know),
        learn_tick=jnp.where(cell_new, s.tick.astype(jnp.int16),
                             s.learn_tick),
        sends_left=jnp.where(need[None, :],
                             jnp.where(cell_new,
                                       jnp.int8(params.retransmit_limit),
                                       jnp.int8(0)),
                             s.sends_left))


def _disseminate(params: SwimParams, s: SwimState) -> SwimState:
    """Piggyback gossip: every live carrier with budget serves its queued
    rumors to ring peers at `gossip_nodes` random offsets (memberlist
    gossip interval / gossip_nodes — options.mdx:1498-1508)."""
    n = params.n_nodes
    tick = s.tick
    key = prng.tick_key(params.seed, tick, 2)
    offs = rolls.offsets(key, n, params.gossip_nodes)
    # Senders need only be up (a gracefully-left node keeps gossiping its
    # leave intent — serf LeavePropagateDelay, lib/serf/serf.go:26-30);
    # receivers must be live members.
    res = gossip_ops.disseminate(offs, s.know, s.sends_left,
                                 sender_ok=s.up,
                                 receiver_ok=s.up & s.member,
                                 slot_active=s.r_active,
                                 retransmit_limit=params.retransmit_limit,
                                 p_loss=params.p_loss,
                                 key=prng.tick_key(params.seed, tick, 5),
                                 group=s.chaos_grp if params.chaos else None,
                                 node_ok=s.chaos_ok if params.chaos
                                 else None,
                                 blocks=params.shard_blocks)
    learn_tick = jnp.where(res.newly, tick.astype(jnp.int16), s.learn_tick)
    # consul.serf.gossip.* device counters (memberlist gossip timer's
    # accounting): the op already computed the reductions
    ctr = (s.ctr.at[CTR_GOSSIP_DELIVERED].add(res.delivered)
           .at[CTR_GOSSIP_SERVED].add(res.served)
           .at[CTR_GOSSIP_LOST].add(res.lost))
    return s.replace(know=res.know, learn_tick=learn_tick,
                     sends_left=res.sends_left, ctr=ctr)


def _bulk_disseminate(params: SwimParams, s: SwimState) -> SwimState:
    """Advance the bulk death channel one gossip tick.

    Two coupled marginals of the (untracked) node x subject knowledge
    matrix evolve:

    NODE marginal `bulk_heard[i]` (exact ring contacts): per contact a
    live sender piggybacks at most `packet_msgs` bulk deaths into the
    packet (memberlist packs its broadcast queue least-retransmitted-
    first into each 1400-byte UDP packet, so from the receiver's view
    the selection is ~uniform over the V in flight); the receiver's
    expected novel messages per packet are supply * (1 - heard/V) —
    the hypergeometric mean — discounted by packet loss.

    SUBJECT marginal `bulk_cov[j]` (mean-field logistic): a non-knower
    learns death j this tick with probability
    1 - (1 - cov_j * sel * p_ok)^g, where sel = min(1, P/mean_supply)
    is the chance j fits in a packet and g the contacts per tick.
    While carriers are scarce (supply < P) sel=1 — the epidemic ramp;
    once supply saturates, sel = P/V and the drain integrates to the
    aggregate packet-capacity estimate T_99.5 ~ V*ln(200)/(g*P)
    gossip ticks — the memberlist math in BENCH_correlated.json.
    Tracking coverage PER SUBJECT is what lets stragglers that enter
    late carry their own clock instead of inheriting the aggregate's
    (commit and detection would otherwise fire the tick they enter).

    Per-rumor retransmit-limit exhaustion is not modeled (limit *
    carriers >> V*N deliveries; queues are >=4096 deep)."""
    n = params.n_nodes
    key = prng.tick_key(params.seed, s.tick, 4)
    offs = rolls.offsets(key, n, params.gossip_nodes)
    v = jnp.maximum(jnp.sum(s.bulk_member).astype(jnp.float32), 1.0)
    cap = jnp.float32(params.packet_msgs)
    p_ok = jnp.float32(1.0 - params.p_loss)
    recv = s.up & s.member
    # clamp: a revive() withdrawal mid-flight shrinks V below already-
    # accumulated heard counts (mean-field has no per-subject deduction)
    heard = jnp.minimum(s.bulk_heard, v)
    supply_src = jnp.where(s.up, heard, 0.0)
    n_up = jnp.maximum(jnp.sum(s.up), 1).astype(jnp.float32)
    mean_supply = jnp.sum(supply_src) / n_up
    views = rolls.pull_multi(supply_src, offs, blocks=params.shard_blocks)     # one doubled buffer
    if params.chaos:
        # nemesis: cross-group contacts carry nothing; degraded
        # endpoints scale the transfer by the pairwise delivery rate
        gviews = rolls.pull_multi(s.chaos_grp, offs, blocks=params.shard_blocks)
        okviews = rolls.pull_multi(s.chaos_ok, offs, blocks=params.shard_blocks)
        views = [jnp.where(gv == s.chaos_grp, v * ov * s.chaos_ok, 0.0)
                 for v, gv, ov in zip(views, gviews, okviews)]
    for view in views:
        supply = jnp.minimum(view, cap)
        novelty = 1.0 - heard / v
        heard = jnp.where(recv,
                          jnp.minimum(heard + supply * novelty * p_ok, v),
                          heard)
    # subject marginal: g contacts, each carrying j w.p. cov*sel*p_ok
    sel = jnp.minimum(1.0, cap / jnp.maximum(mean_supply, 1.0))
    cov = s.bulk_cov
    p_learn = 1.0 - (1.0 - jnp.clip(cov * sel * p_ok, 0.0, 1.0)) \
        ** params.gossip_nodes
    cov = jnp.where(s.bulk_member,
                    jnp.clip(cov + (1.0 - cov) * p_learn, 0.0, 1.0),
                    0.0)
    return s.replace(bulk_heard=heard, bulk_cov=cov)


def _bulk_commit(params: SwimParams, s: SwimState) -> SwimState:
    """Commit bulk subjects whose OWN coverage estimate reached the
    same 99.5% bar the slot channel uses, deduct their mass from the
    node marginal, and free their entries.  Per-subject coverage makes
    this a rolling commit: stragglers keep their own clock, and
    sustained churn can never starve fully-disseminated deaths."""
    done = s.bulk_member & (s.bulk_cov >= 0.995)
    removed = jnp.sum(jnp.where(done, s.bulk_cov, 0.0))
    v_new = jnp.sum(s.bulk_member & ~done).astype(jnp.float32)
    heard = jnp.clip(s.bulk_heard - removed, 0.0, v_new)
    return s.replace(
        committed_dead=s.committed_dead | done,
        bulk_member=s.bulk_member & ~done,
        bulk_heard=heard,
        bulk_cov=jnp.where(done, 0.0, s.bulk_cov))


def _expire(params: SwimParams, s: SwimState) -> SwimState:
    """Free slots whose dissemination window has passed; commit dead/left
    into the O(N) baseline.

    Commit is coverage-guarded (VERDICT r1 weak #7): a timer alone could
    commit a belief most nodes never heard under heavy loss.  A slot holds
    past its nominal lifetime until >=99.5% of live members carry it (or a
    4x hard cap); at expiry the belief only commits when a majority heard
    it — a rumor that failed to spread ages out without poisoning the
    baseline, like memberlist state that was never disseminated."""
    tick = s.tick
    life = jnp.where(s.r_kind == SUSPECT,
                     params.expiry_suspect_ticks, params.expiry_gossip_ticks)
    age = tick - s.r_start
    live = s.up & s.member
    n_live = jnp.maximum(jnp.sum(live), 1)
    coverage = jnp.sum(s.know & live[:, None],
                       axis=0).astype(jnp.float32) / n_live      # [U]
    done = s.r_active & (age >= life) \
        & ((coverage >= 0.995) | (age >= 4 * life))
    return _release(s, done, coverage)


def _release(s: SwimState, done: jnp.ndarray,
             coverage: jnp.ndarray) -> SwimState:
    """Free the `done` slots, committing beliefs a majority heard
    (shared by natural expiry and pressure eviction — the commit rules
    must be identical on both paths).  The freshly computed coverage is
    cached on the state (r_coverage) so metrics scrapes reuse it instead
    of paying their own [N, U] reduction."""
    commit_ok = coverage >= 0.5
    commit_dead = done & (s.r_kind == DEAD) & commit_ok
    commit_left = done & (s.r_kind == LEFT) & commit_ok
    commit_alive = done & (s.r_kind == ALIVE) & commit_ok
    committed_dead = s.committed_dead.at[
        jnp.where(commit_dead, s.r_subject, 0)].max(commit_dead)
    committed_left = s.committed_left.at[
        jnp.where(commit_left, s.r_subject, 0)].max(commit_left)
    committed_inc = s.committed_inc.at[
        jnp.where(commit_alive, s.r_subject, 0)].max(
        jnp.where(commit_alive, s.r_inc, 0))
    keep = ~done
    return s.replace(
        r_active=s.r_active & keep,
        committed_dead=committed_dead,
        committed_left=committed_left,
        committed_inc=committed_inc,
        know=s.know & keep[None, :],
        sends_left=jnp.where(keep[None, :], s.sends_left, jnp.int8(0)),
        r_coverage=jnp.where(keep, coverage, 0.0),
    )


def step_with_obs(params: SwimParams, s: SwimState) -> Tuple[SwimState, ProbeObs]:
    """Advance the whole cluster one gossip tick, returning this tick's probe
    measurements (for the Vivaldi solver — see models/serf.py).

    Dissemination runs every tick (gossip interval); the detector machinery
    (probe round, suspicion expiry, refutation, rumor expiry) runs on probe
    ticks only — timers quantize to the probe interval (≤0.8 s at LAN
    defaults), which is inside memberlist's own timer jitter, and the
    off-tick work drops to the gossip rotations."""
    do_probe = (s.tick % params.probe_period_ticks) == 0

    def probe_branch(st):
        # fused detector pipeline: the [N] subject maps are built ONCE
        # here and threaded through the passes, patched incrementally
        # after each table mutation (allocation / in-place conversion)
        # instead of rebuilt — see _maps for the staleness argument.
        maps = _maps(params, st)
        st, obs, maps = _probe_round(params, st, maps)
        st, convert = _suspicion_expiry(params, st)
        maps = _maps_convert(maps, st, convert)
        st = _dense_suspicion_expiry(params, st, obs.shift, maps)
        st = _refutation(params, st)
        st = _expire(params, st)
        return st, obs

    s, obs = jax.lax.cond(do_probe, probe_branch,
                          lambda st: (st, _empty_obs(params)), s)
    s = _disseminate(params, s)
    # bulk channel: active only during mass events — skip its ring
    # pulls and reductions entirely in the steady state
    s = jax.lax.cond(
        jnp.any(s.bulk_member),
        lambda st: _bulk_commit(params, _bulk_disseminate(params, st)),
        lambda st: st, s)
    return s.replace(tick=s.tick + 1), obs


def step(params: SwimParams, s: SwimState) -> SwimState:
    """Advance the whole cluster one gossip tick (jit this)."""
    return step_with_obs(params, s)[0]


def run(params: SwimParams, s: SwimState, n_ticks: int,
        monitor_subject: int | None = None) -> Tuple[SwimState, jnp.ndarray]:
    """Run `n_ticks` steps under lax.scan; optionally trace the believed-down
    fraction of one subject per tick (for convergence curves)."""

    def body(st, _):
        st = step(params, st)
        if monitor_subject is None:
            return st, jnp.float32(0)
        return st, believed_down_fraction(params, st, monitor_subject)

    return jax.lax.scan(body, s, None, length=n_ticks)


# ---------------------------------------------------------------------------
# device-side metrics summary (host-sync checkpoint surface)
# ---------------------------------------------------------------------------

# Order matches metrics_vector's stack.  Cumulative counters come from
# SwimState.ctr; the rest are instantaneous gauges derived on device so
# ONE host transfer serves the whole scrape.
METRIC_NAMES = (
    "probe.sent", "probe.acked", "probe.failed", "suspicion.started",
    "gossip.delivered", "gossip.served", "gossip.lost",
    "queue.alive", "queue.suspect", "queue.dead", "queue.left",
    "queue.depth", "slot.utilization", "convergence.fraction",
    "members.alive", "members.failed_committed", "members.left_committed",
    "bulk.pending", "bulk.coverage", "awareness.mean", "tick",
)


def metrics_vector(params: SwimParams, s: SwimState) -> jnp.ndarray:
    """One [len(METRIC_NAMES)] f32 vector of sim telemetry (jit this).

    Called only at host-sync checkpoints (a metrics scrape, a bench
    readback) — NEVER per tick: the per-tick accumulation lives in
    SwimState.ctr, and the gauges here are reductions over state the
    device already holds, so the scrape costs one small transfer."""
    f32 = jnp.float32
    live = s.up & s.member
    n_live = jnp.maximum(jnp.sum(live), 1).astype(f32)
    active = s.r_active
    n_active = jnp.maximum(jnp.sum(active), 1).astype(f32)
    live_cells = n_live * n_active
    know_live = s.know & live[:, None] & active[None, :]
    # piggyback-slot utilization: fraction of (live member, active
    # rumor) cells still queued for transmit (sends budget left)
    util = jnp.sum(know_live & (s.sends_left > 0)).astype(f32) / live_cells
    # convergence: mean coverage of the active rumor table — read from
    # the cache the probe-tick expiry pass already computes (r_coverage,
    # <= one probe period stale) instead of paying a second full [N, U]
    # reduction at scrape time
    conv = jnp.sum(jnp.where(active, s.r_coverage, 0.0)) / n_active
    n_bulk = jnp.sum(s.bulk_member).astype(f32)
    bulk_cov = jnp.sum(jnp.where(s.bulk_member, s.bulk_cov, 0.0)) \
        / jnp.maximum(n_bulk, 1.0)
    gauges = jnp.stack([
        jnp.sum(active & (s.r_kind == ALIVE)).astype(f32),
        jnp.sum(active & (s.r_kind == SUSPECT)).astype(f32),
        jnp.sum(active & (s.r_kind == DEAD)).astype(f32),
        jnp.sum(active & (s.r_kind == LEFT)).astype(f32),
        jnp.sum(active).astype(f32),
        util,
        conv,
        jnp.sum(live).astype(f32),
        jnp.sum(s.committed_dead).astype(f32),
        jnp.sum(s.committed_left).astype(f32),
        n_bulk,
        bulk_cov,
        jnp.sum(jnp.where(live, s.awareness.astype(jnp.int32), 0))
        .astype(f32) / n_live,
        s.tick.astype(f32),
    ])
    return jnp.concatenate([s.ctr, gauges])


# Per-shard split of the pool gauges (flight-recorder telemetry): the
# node axis reshapes into `n_blocks` contiguous blocks — exactly the
# mesh shards under `SimConfig.shard_blocks` — and each gauge reduces
# per block.  Under a node-sharded mesh every block's reduction is
# device-local; only the tiny [B, K] table replicates and transfers.
SHARD_METRIC_NAMES = (
    "members.alive", "members.failed_committed",
    "members.left_committed", "awareness.mean",
)


def shard_metrics(params: SwimParams, s: SwimState,
                  n_blocks: int) -> jnp.ndarray:
    """[n_blocks, len(SHARD_METRIC_NAMES)] f32 per-shard gauges (jit
    with n_blocks static).  Same checkpoint discipline as
    metrics_vector: reductions over state the device already holds,
    one small transfer per scrape."""
    f32 = jnp.float32

    def blk(x):
        return x.reshape(n_blocks, -1)

    live = blk(s.up & s.member)
    alive = jnp.sum(live, axis=1).astype(f32)
    n_live = jnp.maximum(alive, 1.0)
    failed = jnp.sum(blk(s.committed_dead), axis=1).astype(f32)
    left = jnp.sum(blk(s.committed_left), axis=1).astype(f32)
    aware = jnp.sum(
        blk(jnp.where(s.up & s.member,
                      s.awareness.astype(jnp.int32), 0)),
        axis=1).astype(f32) / n_live
    return jnp.stack([alive, failed, left, aware], axis=1)


# ---------------------------------------------------------------------------
# oracle read path: device-side membership reductions (gather-free)
# ---------------------------------------------------------------------------
# The oracle must answer members()/status() against SHARDED state without
# pulling the whole node axis to host (ROADMAP item 5's delta contract,
# linted by gather_discipline).  Everything here is elementwise or a
# bounded-output reduction over [N] leaves: under a node-sharded mesh the
# [N] intermediates stay sharded and only the tiny [K]-bounded outputs
# replicate and transfer.

STATUS_ALIVE = 0
STATUS_FAILED = 1
STATUS_LEFT = 2


def status_vector(params: SwimParams, s: SwimState) -> jnp.ndarray:
    """[N] int8 serf member status (0 alive, 1 failed, 2 left), the
    oracle's view: failed = committed dead OR an active dead rumor;
    left = committed left OR never a member; left wins over failed
    (serf precedence).  Stays on device — callers page or reduce it."""
    is_dead = s.r_active & (s.r_kind == DEAD)
    dead_rumor = jnp.zeros_like(s.committed_dead).at[
        jnp.where(is_dead, s.r_subject, 0)].max(is_dead)
    failed = s.committed_dead | dead_rumor
    left = s.committed_left | ~s.member
    return jnp.where(left, STATUS_LEFT,
                     jnp.where(failed, STATUS_FAILED,
                               STATUS_ALIVE)).astype(jnp.int8)


def membership_counts(params: SwimParams, s: SwimState,
                      provisioned: jnp.ndarray) -> jnp.ndarray:
    """[4] int32 (alive, failed, left, total) over provisioned slots —
    the members_summary() source: a full device-side reduction whose
    transfer is 16 bytes regardless of N."""
    st = status_vector(params, s)
    i32 = jnp.int32
    return jnp.stack([
        jnp.sum(provisioned & (st == STATUS_ALIVE)).astype(i32),
        jnp.sum(provisioned & (st == STATUS_FAILED)).astype(i32),
        jnp.sum(provisioned & (st == STATUS_LEFT)).astype(i32),
        jnp.sum(provisioned).astype(i32),
    ])


def membership_page(params: SwimParams, s: SwimState,
                    ids: jnp.ndarray):
    """Gather one page of member rows: (status, incarnation, up) at
    `ids` ([K] int32, padded with 0 — callers mask).  Transfers O(K)."""
    st = status_vector(params, s)
    return st[ids], s.incarnation[ids], s.up[ids]


def membership_delta(params: SwimParams, s: SwimState,
                     prev_status: jnp.ndarray, provisioned: jnp.ndarray,
                     k: int):
    """Changed PROVISIONED members since a status checkpoint:
    (new_status [N], n_changed scalar, idx [k] int32 padded -1,
    state [k] int8).  Unprovisioned slots never count — a sparse pool's
    first delta reports its members, not its empty slots.

    The incremental device→control-plane seam (ROADMAP item 5): a pool
    with F flaps since the checkpoint moves min(F, k) rows to host, not
    a full gather — callers re-checkpoint with the returned vector and
    fall back to paged listing when n_changed > k.

    The first-k changed indices come from _top_k_sharded over the
    binary changed mask, NOT `jnp.where(..., size=k)`: the where/
    nonzero lowering all-gathers the full [N] mask under a node-sharded
    mesh (hlo_lint gather-freedom finding, ISSUE 20), while per-block
    top-k stays local.  Equal scores break ties toward the earlier
    global index, so the k ones selected are exactly where's ascending
    first-k.  (When k exceeds N/shard_blocks the helper falls back to
    flat top_k — a near-full listing is O(N) transfer by request.)"""
    st = status_vector(params, s)
    changed = (st != prev_status) & provisioned
    n = changed.shape[0]
    # top_k caps k at N where the old where(size=k) padded past it; a
    # k > N request still returns [k] rows, tail forced to the pad
    kk = min(k, n)
    vals, idx = _top_k_sharded(changed.astype(jnp.int32), kk,
                               params.shard_blocks)
    idx = jnp.where(vals > 0, idx, jnp.int32(-1))
    if kk < k:
        idx = jnp.concatenate(
            [idx, jnp.full((k - kk,), -1, jnp.int32)])
    return st, jnp.sum(changed).astype(jnp.int32), idx, \
        st[jnp.maximum(idx, 0)]


# ---------------------------------------------------------------------------
# fault injection / membership control (ground truth)
# ---------------------------------------------------------------------------

def kill_mask(s: SwimState, mask: jnp.ndarray) -> SwimState:
    """Correlated failure: every node in `mask` ([N] bool) crashes in
    the same tick — the rack-scale event that pressures the rumor
    table (SURVEY §5.3; a single kill() never exercises slot
    contention)."""
    return s.replace(up=s.up & ~mask)


def mass_detection_stats(params: SwimParams, s: SwimState,
                         victim_mask: jnp.ndarray):
    """(recall, false_positives) for a correlated-failure experiment.

    A subject counts as cluster-detected when its death is committed
    OR an active dead/left rumor for it reaches >=99% of live members
    — the same thresholds the convergence bench uses, but evaluated
    for EVERY victim at once in rumor space (an [N, V] belief matrix
    would be O(N^2) at 1M nodes).

      recall          fraction of victims cluster-detected
      false_positives live members the cluster believes down
    """
    n = params.n_nodes
    live = s.up & s.member
    n_live = jnp.maximum(jnp.sum(live), 1)
    coverage = jnp.sum(s.know & live[:, None],
                       axis=0).astype(jnp.float32) / n_live       # [U]
    dead_sl = s.r_active & ((s.r_kind == DEAD) | (s.r_kind == LEFT)) \
        & (coverage >= 0.99)
    rumor_detected = jnp.zeros((n,), bool).at[
        jnp.where(dead_sl, s.r_subject, 0)].max(dead_sl)
    # bulk-channel subjects: detected once their OWN coverage estimate
    # reaches the same 99% bar
    believed_down = s.committed_dead | s.committed_left \
        | rumor_detected | (s.bulk_member & (s.bulk_cov >= 0.99))
    victims = victim_mask & s.member
    recall = jnp.sum(believed_down & victims) / \
        jnp.maximum(jnp.sum(victims), 1)
    false_pos = jnp.sum(believed_down & live)
    return recall, false_pos


def kill(s: SwimState, node: int) -> SwimState:
    """Crash a node (fail-stop).  The detector must discover this."""
    return s.replace(up=s.up.at[node].set(False))


def revive_mask(s: SwimState, mask: jnp.ndarray) -> SwimState:
    """Flap restart: every node in `mask` ([N] bool) comes back up with
    a bumped incarnation when stale suspect/dead rumors about it are
    still in flight, so those rumors can neither expire into a
    committed death nor re-suspect it at the old incarnation
    (memberlist aliveNode on a suspect/dead entry: the returning node
    refutes with inc+1).  The in-flight stale slots are withdrawn here
    — the state-surgery equivalent of the refutation the live node
    would broadcast within ~1 probe round, exercised by the
    kill_mask-then-revive flap path (chaos.py crash/restart nemesis).
    A COMMITTED death still requires `rejoin` (it must re-originate an
    alive rumor cluster-wide); dense suspicion timers and bulk-channel
    entries for revived nodes reset (mean-field has no per-subject
    refutation)."""
    mask = jnp.asarray(mask, bool)
    stale = s.r_active & mask[s.r_subject] \
        & ((s.r_kind == SUSPECT) | (s.r_kind == DEAD))
    # rejoin incarnation: strictly above every stale rumor's, so the
    # next suspicion of this node starts a FRESH refutable lifecycle
    bump = jnp.zeros_like(s.incarnation).at[
        jnp.where(stale, s.r_subject, 0)].max(
        jnp.where(stale, s.r_inc + 1, 0))
    return s.replace(
        up=s.up | mask,
        incarnation=jnp.maximum(s.incarnation, bump),
        r_active=s.r_active & ~stale,
        know=s.know & ~stale[None, :],
        sends_left=jnp.where(stale[None, :], jnp.int8(0), s.sends_left),
        sus_start=jnp.where(mask, -1, s.sus_start),
        sus_confirm=jnp.where(mask, jnp.int8(0), s.sus_confirm),
        bulk_member=s.bulk_member & ~mask,
        bulk_cov=jnp.where(mask, 0.0, s.bulk_cov))


def revive(s: SwimState, node: int) -> SwimState:
    """Bring the process back up after a flap (kill/kill_mask then
    restart inside the suspicion/dissemination window): the node
    rejoins with a bumped incarnation whenever stale death rumors are
    in flight — see revive_mask.  A node the cluster already declared
    dead (committed) must `rejoin` instead."""
    n = s.up.shape[0]
    return revive_mask(s, jnp.arange(n) == node)


def rejoin(params: SwimParams, s: SwimState, node: int) -> SwimState:
    """Restart + rejoin after a committed death (memberlist's
    rejoin-with-higher-incarnation; serf snapshot rejoin
    agent/consul/server_serf.go:169-172): the node comes back with a
    bumped incarnation, its committed dead/left state clears, lingering
    dead/left rumors about it deactivate (they would recommit the death
    on expiry), and it originates an alive rumor that refutes the stale
    belief cluster-wide."""
    inc = s.incarnation.at[node].add(1)
    stale = s.r_active & (s.r_subject == node) & \
        ((s.r_kind == DEAD) | (s.r_kind == LEFT) | (s.r_kind == SUSPECT))
    s = s.replace(
        up=s.up.at[node].set(True),
        member=s.member.at[node].set(True),
        committed_dead=s.committed_dead.at[node].set(False),
        committed_left=s.committed_left.at[node].set(False),
        incarnation=inc,
        r_active=s.r_active & ~stale,
        # the deactivated slots' knowledge cells must clear with them:
        # a later _originate reusing the slot ORs new cells into know,
        # and stale set bits would hand the fresh rumor phantom
        # carriers (and phantom coverage at commit time)
        know=s.know & ~stale[None, :],
        sends_left=jnp.where(stale[None, :], jnp.int8(0), s.sends_left),
        bulk_member=s.bulk_member.at[node].set(False),
        bulk_cov=s.bulk_cov.at[node].set(0.0),
    )
    want = jnp.zeros((params.n_nodes,), jnp.int32).at[node].set(1)
    row_subject = jnp.where(jnp.arange(params.n_nodes) == node, node,
                            _NEG)
    return _originate(params, s, want, ALIVE, inc, row_subject)[0]


def leave(params: SwimParams, s: SwimState, node: int) -> SwimState:
    """Graceful leave: the node broadcasts `left` before shutting down
    (serf intent; consumed at reference agent/consul/leader.go:1390)."""
    want = jnp.zeros((params.n_nodes,), jnp.int32).at[node].set(1)
    row_subject = jnp.where(jnp.arange(params.n_nodes) == node, node, -1)
    s, _ = _originate(params, s, want, LEFT, s.incarnation, row_subject)
    return s.replace(member=s.member.at[node].set(False))


def inject_suspicion(params: SwimParams, s: SwimState, subject: int,
                     origin: int) -> SwimState:
    """Testing hook: make `origin` suspect `subject` right now."""
    want = jnp.zeros((params.n_nodes,), jnp.int32).at[subject].set(1)
    row_subject = jnp.where(jnp.arange(params.n_nodes) == origin, subject, -1)
    return _originate(params, s, want, SUSPECT, s.incarnation,
                      row_subject)[0]
