"""Multi-datacenter federation: per-DC LAN pools + one WAN server pool.

Consul's cross-DC architecture (SURVEY.md §2.2): every DC runs its own LAN
gossip pool with every agent; the *servers* of all DCs additionally join a
single WAN pool with slower timers (reference: setupSerf WAN
agent/consul/server_serf.go:36-185 with `gossip_wan` defaults; Flood
pushes LAN servers into WAN agent/consul/flood.go:12-27; cross-DC routing
by WAN coordinates agent/router/router.go:534 GetDatacentersByDistance).

Tensorization: the D LAN pools are a vmapped batch of serf cluster models
(identical static shape per DC — one compiled step advances every DC at
once); the WAN pool is one more serf model over the D·S servers.  User
events bridge DCs through servers the way Consul replicates across
federation: an event fired in DC d spreads over d's LAN, reaches a server,
crosses the WAN pool, and each remote server re-fires it into its own LAN
(cap: one inject per DC per tick per direction — events are rare next to
the gossip tick rate).

Node numbering: LAN node ids 0..S-1 of each DC are its servers; WAN node
id = dc·S + server_index.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
from flax import struct

from consul_tpu.config import GossipConfig, SimConfig
from consul_tpu.models import events, serf, swim


@dataclasses.dataclass(frozen=True)
class WanParams:
    n_dcs: int
    servers_per_dc: int
    lan: serf.SerfParams        # per-DC pool (same shape each DC)
    wan: serf.SerfParams        # pool of n_dcs * servers_per_dc servers


def make_params(n_dcs: int = 3, nodes_per_dc: int = 1024,
                servers_per_dc: int = 5, p_loss: float = 0.01,
                seed: int = 0, rumor_slots: int = 16,
                event_slots: int = 16,
                shard_blocks: int = 1) -> WanParams:
    # `shard_blocks` = per-DC node-axis shard count under a 2-D
    # make_wan_mesh (devices / n_dcs): threads the ops/rolls.py
    # ring-collective lowering hint into every LAN pool so its pulls
    # never all-gather the [N, ...] leaves (pure lowering hint, results
    # identical — see SimConfig).  The WAN pool stays at 1: its
    # [D*S]-sized buffers are tiny and the doubled-buffer path is fine.
    lan = serf.make_params(
        GossipConfig.lan(),
        SimConfig(n_nodes=nodes_per_dc, rumor_slots=rumor_slots,
                  p_loss=p_loss, seed=seed, shard_blocks=shard_blocks),
        event_slots=event_slots)
    wan = serf.make_params(
        GossipConfig.wan(),
        SimConfig(n_nodes=n_dcs * servers_per_dc, rumor_slots=rumor_slots,
                  p_loss=p_loss, seed=seed ^ 0xBAD5EED),
        event_slots=event_slots)
    return WanParams(n_dcs=n_dcs, servers_per_dc=servers_per_dc,
                     lan=lan, wan=wan)


BRIDGE_RING = 4                 # x event_slots: per-DC bridged-id memory


@struct.dataclass
class WanState:
    lan: serf.ClusterState      # batched: leading axis D on every leaf
    wan: serf.ClusterState      # flat WAN pool
    bridged: jnp.ndarray        # [D, B] int32 event ids already bridged (-1 empty)
    bridged_ptr: jnp.ndarray    # [D] int32 ring cursor


def init_state(params: WanParams) -> WanState:
    keys = jax.random.split(jax.random.PRNGKey(params.lan.swim.seed ^ 0xD0),
                            params.n_dcs)
    lan = jax.vmap(lambda k: serf.init_state(params.lan, k))(keys)
    wan = serf.init_state(params.wan)
    b = BRIDGE_RING * params.lan.events.event_slots
    return WanState(lan=lan, wan=wan,
                    bridged=jnp.full((params.n_dcs, b), -1, jnp.int32),
                    bridged_ptr=jnp.zeros((params.n_dcs,), jnp.int32))


def _active_ids(e_active, e_id):
    """Active-slot ids with -1 for inactive slots (0 is a valid event id;
    multiplying by the mask would make id 0 look ever-present)."""
    return jnp.where(e_active, e_id, -1)


def _first_active_candidate(e_active, known_mask, e_id, other_ids, seen):
    """Pick the first active event known to a bridge node whose id is not
    in the destination's active slots NOR in this DC's bridged-id ring;
    returns (found, slot).  The ring is the re-fire guard: LAN and WAN
    slots expire on different schedules, so table presence alone would let
    an event ping-pong between pools forever."""
    present = jnp.any(e_id[:, None] == other_ids[None, :], axis=1)
    already = jnp.any(e_id[:, None] == seen[None, :], axis=1)
    cand = e_active & known_mask & ~present & ~already
    slot = jnp.argmax(cand)
    return jnp.any(cand), slot


def _ring_push(bridged_row, ptr, value, enable):
    """Record `value` in the ring when `enable` (jit-safe)."""
    b = bridged_row.shape[0]
    row = jnp.where(enable,
                    bridged_row.at[ptr % b].set(value), bridged_row)
    return row, ptr + jnp.where(enable, 1, 0)


def step(params: WanParams, s: WanState) -> WanState:
    """One gossip tick of the whole federation.

    The WAN pool uses its own (slower) timers: its serf model steps every
    tick of *this* function as well — callers that want exact wall-clock
    alignment can step the WAN model every lan_gossip/wan_gossip ticks;
    here both advance together and the WAN config's probe_period (10
    ticks at WAN defaults vs 5 LAN) preserves the relative cadence."""
    lan = jax.vmap(lambda st: serf.step(params.lan, st))(s.lan)
    wan = serf.step(params.wan, s.wan)
    s = s.replace(lan=lan, wan=wan)
    s = _bridge_events(params, s)
    return s


def _bridge_events(params: WanParams, s: WanState) -> WanState:
    """Sharding-safe bridge: under the 2-D dc x nodes mesh
    (parallel/mesh.make_wan_mesh) the batched LAN leaves must never be
    sliced at a dc index and restacked — GSPMD lowers that
    slice/where/stack round-trip of a sharded batch axis to unreduced
    partial sums (observed: tick multiplied by the nodes-axis replica
    count every step).  Instead the per-DC decisions are computed from
    small REPLICATED tables (wan_state_sharding keeps every [D, small]
    leaf replicated) plus mask-based reductions over the sharded node
    axis, and the one write into the big [D, N, E] leaves goes through
    a vmapped `events.fire` — the same batched formulation as the
    vmapped `serf.step`, which GSPMD partitions correctly."""
    d, sp = params.n_dcs, params.servers_per_dc
    lan_ev, wan_ev = s.lan.events, s.wan.events
    bridged, bridged_ptr = s.bridged, s.bridged_ptr

    # batched server views via row masks — no slicing of the (possibly
    # node-sharded) row axis; reductions over it lower to all-reduces
    srv = jnp.arange(params.lan.events.n_nodes) < sp            # [N]
    served = jnp.any(lan_ev.know & srv[None, :, None], axis=1)  # [D, E]
    srv_any = jnp.any(lan_ev.know, axis=2) & srv[None, :]       # [D, N]
    # first server row that knows any event (0 when none, like the
    # original argmax over an all-False server slice)
    lan_origin = jnp.argmax(srv_any, axis=1).astype(jnp.int32)  # [D]

    # ---- LAN -> WAN: a server that knows a local event injects it.
    # Sequential over dc by design (each injection changes the WAN
    # candidate set the next dc checks); everything touched is a small
    # replicated table, so the python loop stays GSPMD-local.
    for dc in range(d):
        found, slot = _first_active_candidate(
            lan_ev.e_active[dc], served[dc], lan_ev.e_id[dc],
            _active_ids(wan_ev.e_active, wan_ev.e_id), bridged[dc])
        eid = lan_ev.e_id[dc, slot]
        origin_server = dc * sp + lan_origin[dc]
        wan_ev = jax.tree_util.tree_map(
            lambda new, old: jnp.where(found, new, old),
            events.fire(params.wan.events, wan_ev, origin_server, eid),
            wan_ev)
        row, ptr = _ring_push(bridged[dc], bridged_ptr[dc], eid, found)
        bridged = bridged.at[dc].set(row)
        bridged_ptr = bridged_ptr.at[dc].set(ptr)

    # ---- WAN -> LAN: a server that knows a WAN event fires it locally.
    # Decisions first (small replicated wan tables), then ONE vmapped
    # fire applies every DC's write to the batched lan events tree.
    founds, eids, origins = [], [], []
    for dc in range(d):
        my_servers = wan_ev.know[dc * sp:(dc + 1) * sp, :]  # [S, E]
        known_here = jnp.any(my_servers, axis=0)            # [E]
        found, slot = _first_active_candidate(
            wan_ev.e_active, known_here, wan_ev.e_id,
            _active_ids(lan_ev.e_active[dc], lan_ev.e_id[dc]),
            bridged[dc])
        eid = wan_ev.e_id[slot]
        founds.append(found)
        eids.append(eid)
        origins.append(jnp.argmax(jnp.any(my_servers, axis=1))
                       .astype(jnp.int32))
        row, ptr = _ring_push(bridged[dc], bridged_ptr[dc], eid, found)
        bridged = bridged.at[dc].set(row)
        bridged_ptr = bridged_ptr.at[dc].set(ptr)

    def apply_fire(ev, found, origin, eid):
        fired = events.fire(params.lan.events, ev, origin, eid)
        return jax.tree_util.tree_map(
            lambda new, old: jnp.where(found, new, old), fired, ev)

    lan_ev = jax.vmap(apply_fire)(lan_ev, jnp.stack(founds),
                                  jnp.stack(origins), jnp.stack(eids))
    return s.replace(lan=s.lan.replace(events=lan_ev),
                     wan=s.wan.replace(events=wan_ev),
                     bridged=bridged, bridged_ptr=bridged_ptr)


def run(params: WanParams, s: WanState, n_ticks: int) -> WanState:
    def body(st, _):
        return step(params, st), 0

    return jax.lax.scan(body, s, None, length=n_ticks)[0]


# ------------------------------------------------------------------- helpers

def fire_event(params: WanParams, s: WanState, dc: int, origin: int,
               event_id: int) -> WanState:
    ev = jax.tree_util.tree_map(lambda x: x[dc], s.lan.events)
    fired = events.fire(params.lan.events, ev, origin, event_id)
    lan_ev = jax.tree_util.tree_map(
        lambda full, one: full.at[dc].set(one), s.lan.events, fired)
    return s.replace(lan=s.lan.replace(events=lan_ev))


def event_coverage_by_dc(params: WanParams, s: WanState,
                         event_id: int) -> jnp.ndarray:
    """[D] fraction of live members in each DC that received the event."""
    def per_dc(cluster_events, up, member):
        hit = jnp.any((cluster_events.e_id[None, :] == event_id)
                      & (cluster_events.deliver_tick >= 0), axis=1)
        alive = up & member
        return jnp.sum(hit & alive) / jnp.maximum(jnp.sum(alive), 1)

    return jax.vmap(per_dc)(s.lan.events, s.lan.swim.up, s.lan.swim.member)


def dc_distance_matrix(params: WanParams, s: WanState) -> jnp.ndarray:
    """[D, D] median server-to-server estimated RTT — the WAN-coordinate
    DC ranking (reference agent/router/router.go:534).  Uses the canonical
    vivaldi.estimate_rtt (incl. its adjustment positivity floor) on all
    server pairs rather than re-deriving the metric."""
    from consul_tpu.models import vivaldi
    d, sp = params.n_dcs, params.servers_per_dc
    n = d * sp
    ii, jj = jnp.meshgrid(jnp.arange(n, dtype=jnp.int32),
                          jnp.arange(n, dtype=jnp.int32), indexing="ij")
    dist = vivaldi.estimate_rtt(s.wan.coords, ii.ravel(),
                                jj.ravel()).reshape(d, sp, d, sp)
    return jnp.median(dist, axis=(1, 3))


def wan_kill_dc(params: WanParams, s: WanState, dc: int) -> WanState:
    """Partition a whole DC: crash its servers in the WAN pool (the other
    DCs' routers should mark the DC unreachable)."""
    sp = params.servers_per_dc
    sw = s.wan.swim
    ids = jnp.arange(sw.up.shape[0])
    mask = (ids >= dc * sp) & (ids < (dc + 1) * sp)
    return s.replace(wan=s.wan.replace(swim=sw.replace(up=sw.up & ~mask)))


def dc_reachable(params: WanParams, s: WanState) -> jnp.ndarray:
    """[D] — a DC is reachable while any of its servers is WAN-alive
    (committed view)."""
    sp = params.servers_per_dc
    alive = s.wan.swim.up & s.wan.swim.member & ~s.wan.swim.committed_dead
    return jnp.any(alive.reshape(params.n_dcs, sp), axis=1)
