"""Vivaldi network coordinates as vectorized spring relaxation.

TPU-native replacement for the serf coordinate client Consul consumes
(reference: coordinate updates staged/batched at
agent/consul/coordinate_endpoint.go:20-130; distance math `ComputeDistance`
lib/rtt.go:13-43; RTT-sorted query results agent/consul/rtt.go:196; client
send loop agent/agent.go:1635-1688).  The algorithm follows the published
Vivaldi paper (Dabek et al., SIGCOMM'04) with serf's documented extensions —
height vector, adaptive error, gravity, and a latency-adjustment window
(website/content/docs/architecture/coordinates.mdx) — re-derived, not
translated.

In the reference every probe ack yields one coordinate update on one node.
Here a whole cluster's worth of observations applies in one batched tick:
`observe(state, src, dst, rtt)` updates every source row at once, so the
100k-node config of BASELINE.json is a handful of fused [N, D] ops per tick
on the VPU instead of 100k goroutine callbacks.

Units: seconds (like the reference's coordinate package).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from flax import struct

from consul_tpu.ops import rolls
from consul_tpu.utils import prng


@dataclasses.dataclass(frozen=True)
class VivaldiParams:
    """serf coordinate tuning surface (documented defaults)."""

    n_nodes: int
    dims: int = 8
    vivaldi_error_max: float = 1.5
    vivaldi_ce: float = 0.25          # error-estimate smoothing
    vivaldi_cc: float = 0.25          # spring-force gain
    adjustment_window: int = 20       # rolling latency-adjustment samples
    height_min: float = 10.0e-6       # seconds
    gravity_rho: float = 150.0        # pull toward origin per second of radius
    seed: int = 0
    # ring-exchange lowering hint (ops/rolls.py; see SimConfig)
    shard_blocks: int = 1


@struct.dataclass
class VivaldiState:
    coords: jnp.ndarray      # [N, D] float32, seconds
    height: jnp.ndarray      # [N] float32, seconds (access-link latency)
    error: jnp.ndarray       # [N] float32, confidence (lower is better)
    adj_window: jnp.ndarray  # [N, W] float32: last W (rtt - predicted) samples
    adj_index: jnp.ndarray   # int32 scalar: ring cursor
    adjustment: jnp.ndarray  # [N] float32: current additive adjustment


def init_state(params: VivaldiParams) -> VivaldiState:
    n, d = params.n_nodes, params.dims
    return VivaldiState(
        coords=jnp.zeros((n, d), jnp.float32),
        height=jnp.full((n,), params.height_min, jnp.float32),
        error=jnp.full((n,), params.vivaldi_error_max, jnp.float32),
        adj_window=jnp.zeros((n, params.adjustment_window), jnp.float32),
        adj_index=jnp.int32(0),
        adjustment=jnp.zeros((n,), jnp.float32),
    )


def raw_distance(s: VivaldiState, src: jnp.ndarray, dst: jnp.ndarray) -> jnp.ndarray:
    """Euclidean + height distance between node rows src and dst ([K] ids)."""
    diff = s.coords[src] - s.coords[dst]
    return jnp.linalg.norm(diff, axis=-1) + s.height[src] + s.height[dst]


def estimate_rtt(s: VivaldiState, src: jnp.ndarray, dst: jnp.ndarray) -> jnp.ndarray:
    """Predicted RTT with adjustment terms, floored like the reference
    (lib/rtt.go:13-43 ComputeDistance semantics)."""
    d = raw_distance(s, src, dst)
    adjusted = d + s.adjustment[src] + s.adjustment[dst]
    return jnp.where(adjusted > 0.0, adjusted, d)


def observe(params: VivaldiParams, s: VivaldiState, src: jnp.ndarray | None,
            dst: jnp.ndarray, rtt: jnp.ndarray,
            mask: jnp.ndarray | None = None) -> VivaldiState:
    """Apply one RTT observation per source node, batched.

    src: [N] int32 node ids or None for the row-aligned fast path (node i
    observes dst[i] — the common case; avoids TPU scatters entirely);
    dst: [N] int32; rtt: [N] float32 seconds; mask: [N] bool (False rows
    are no-ops).  Rows of `src` must be distinct.
    """
    aligned = src is None
    if aligned:
        src = jnp.arange(s.coords.shape[0], dtype=jnp.int32)
    if mask is None:
        mask = jnp.ones(src.shape, bool)
    rtt = jnp.maximum(rtt, 1.0e-6)

    ci = s.coords if aligned else s.coords[src]
    hi = s.height if aligned else s.height[src]
    ei = s.error if aligned else s.error[src]
    cj, hj, ej = s.coords[dst], s.height[dst], s.error[dst]

    diff = ci - cj
    norm = jnp.linalg.norm(diff, axis=-1)
    dist = norm + hi + hj

    # sample weight balances confidence between the two nodes
    w = ei / jnp.maximum(ei + ej, 1.0e-9)
    err_sample = jnp.abs(dist - rtt) / rtt
    new_err = err_sample * params.vivaldi_ce * w + ei * (1.0 - params.vivaldi_ce * w)
    new_err = jnp.clip(new_err, 1.0e-6, params.vivaldi_error_max)

    # spring force along the unit vector (random direction if colocated)
    key = prng.tick_key(params.seed, s.adj_index, 7)
    rand_dir = jax.random.normal(key, ci.shape, jnp.float32)
    unit = jnp.where((norm > 1.0e-9)[:, None], diff / jnp.maximum(norm, 1.0e-9)[:, None],
                     rand_dir / jnp.linalg.norm(rand_dir, axis=-1, keepdims=True))
    force = params.vivaldi_cc * w * (rtt - dist)
    new_ci = ci + unit * force[:, None]
    new_hi = jnp.maximum(hi + (hi / jnp.maximum(dist, 1.0e-9)) * force,
                         params.height_min)

    m = mask
    if aligned:
        coords = jnp.where(m[:, None], new_ci, s.coords)
        height = jnp.where(m, new_hi, s.height)
        error = jnp.where(m, new_err, s.error)
    else:
        coords = s.coords.at[src].set(jnp.where(m[:, None], new_ci, ci))
        height = s.height.at[src].set(jnp.where(m, new_hi, hi))
        error = s.error.at[src].set(jnp.where(m, new_err, ei))

    # gravity: keep the constellation centered so coordinates stay comparable
    norms = jnp.linalg.norm(coords, axis=-1, keepdims=True)
    grav = (norms / params.gravity_rho) ** 2
    coords = coords * jnp.maximum(1.0 - grav, 0.0)

    # latency adjustment ring: mean of last W (rtt - raw distance) residuals.
    # (sample rows are src-ordered; scatter them into node-id order first)
    col = (s.adj_index % params.adjustment_window).astype(jnp.int32)
    old_col = jax.lax.dynamic_slice_in_dim(s.adj_window, col, 1, axis=1)[:, 0]
    if aligned:
        new_col = jnp.where(m, (rtt - dist) / 2.0, old_col)
    else:
        new_col = old_col.at[src].set(
            jnp.where(m, (rtt - dist) / 2.0, old_col[src]))
    adj_window = jax.lax.dynamic_update_slice_in_dim(
        s.adj_window, new_col[:, None], col, axis=1)
    adjustment = jnp.mean(adj_window, axis=1)

    return VivaldiState(coords=coords, height=height, error=error,
                        adj_window=adj_window, adj_index=s.adj_index + 1,
                        adjustment=adjustment)


def observe_ring(params: VivaldiParams, s: VivaldiState, shift,
                 rtt: jnp.ndarray, mask: jnp.ndarray) -> VivaldiState:
    """Row-aligned `observe` where node i's peer is (i + shift) % N — the
    SWIM ring-probe coupling (models/swim.py ProbeObs.shift).  All peer
    lookups are rotations; no gathers, no scatters (hot-loop path)."""
    n = s.coords.shape[0]
    rtt = jnp.maximum(rtt, 1.0e-6)
    ci, hi, ei = s.coords, s.height, s.error
    cj = rolls.pull(s.coords, shift, blocks=params.shard_blocks)
    hj = rolls.pull(s.height, shift, blocks=params.shard_blocks)
    ej = rolls.pull(s.error, shift, blocks=params.shard_blocks)

    diff = ci - cj
    norm = jnp.linalg.norm(diff, axis=-1)
    dist = norm + hi + hj

    w = ei / jnp.maximum(ei + ej, 1.0e-9)
    err_sample = jnp.abs(dist - rtt) / rtt
    new_err = err_sample * params.vivaldi_ce * w + ei * (1.0 - params.vivaldi_ce * w)
    new_err = jnp.clip(new_err, 1.0e-6, params.vivaldi_error_max)

    key = prng.tick_key(params.seed, s.adj_index, 7)
    rand_dir = jax.random.normal(key, ci.shape, jnp.float32)
    unit = jnp.where((norm > 1.0e-9)[:, None], diff / jnp.maximum(norm, 1.0e-9)[:, None],
                     rand_dir / jnp.linalg.norm(rand_dir, axis=-1, keepdims=True))
    force = params.vivaldi_cc * w * (rtt - dist)
    new_ci = ci + unit * force[:, None]
    new_hi = jnp.maximum(hi + (hi / jnp.maximum(dist, 1.0e-9)) * force,
                         params.height_min)

    m = mask
    coords = jnp.where(m[:, None], new_ci, s.coords)
    height = jnp.where(m, new_hi, s.height)
    error = jnp.where(m, new_err, s.error)

    norms = jnp.linalg.norm(coords, axis=-1, keepdims=True)
    grav = (norms / params.gravity_rho) ** 2
    coords = coords * jnp.maximum(1.0 - grav, 0.0)

    col = (s.adj_index % params.adjustment_window).astype(jnp.int32)
    old_col = jax.lax.dynamic_slice_in_dim(s.adj_window, col, 1, axis=1)[:, 0]
    new_col = jnp.where(m, (rtt - dist) / 2.0, old_col)
    adj_window = jax.lax.dynamic_update_slice_in_dim(
        s.adj_window, new_col[:, None], col, axis=1)
    adjustment = jnp.mean(adj_window, axis=1)

    return VivaldiState(coords=coords, height=height, error=error,
                        adj_window=adj_window, adj_index=s.adj_index + 1,
                        adjustment=adjustment)


def sort_by_distance(s: VivaldiState, origin: int) -> jnp.ndarray:
    """Node ids sorted by estimated RTT from `origin` — the `?near=` query
    path (reference agent/consul/rtt.go:196 sortNodesByDistanceFrom)."""
    n = s.coords.shape[0]
    all_ids = jnp.arange(n, dtype=jnp.int32)
    d = estimate_rtt(s, jnp.full((n,), origin, jnp.int32), all_ids)
    return jnp.argsort(d)


# ---------------------------------------------------------------------------
# standalone convergence sim (BASELINE.json config #3: 100k nodes)
# ---------------------------------------------------------------------------

def synthetic_rtt(true_coords: jnp.ndarray, src, dst, key,
                  jitter: float = 0.02) -> jnp.ndarray:
    """Ground-truth RTT (seconds) from latent coordinates with noise."""
    base = jnp.linalg.norm(true_coords[src] - true_coords[dst], axis=-1)
    noise = 1.0 + jitter * jax.random.normal(key, base.shape)
    return jnp.maximum(base * noise, 1.0e-6)


def sim_step(params: VivaldiParams, true_coords: jnp.ndarray,
             s: VivaldiState, tick) -> VivaldiState:
    """One relaxation tick: every node measures one random peer."""
    n = params.n_nodes
    key = prng.tick_key(params.seed, tick, 8)
    k1, k2 = jax.random.split(key)
    src = jnp.arange(n, dtype=jnp.int32)
    dst = prng.other_nodes(k1, n, (n,))
    rtt = synthetic_rtt(true_coords, src, dst, k2)
    return observe(params, s, src, dst, rtt)


def relative_error(params: VivaldiParams, true_coords: jnp.ndarray,
                   s: VivaldiState, tick, n_pairs_per_node: int = 1):
    """Median |predicted - true| / true over random pairs (convergence metric)."""
    n = params.n_nodes
    key = prng.tick_key(params.seed, tick, 9)
    k1, k2 = jax.random.split(key)
    src = jnp.arange(n, dtype=jnp.int32)
    dst = prng.other_nodes(k1, n, (n,))
    true_rtt = synthetic_rtt(true_coords, src, dst, k2, jitter=0.0)
    est = estimate_rtt(s, src, dst)
    return jnp.median(jnp.abs(est - true_rtt) / true_rtt)
