from consul_tpu.models import swim

__all__ = ["swim"]
