from consul_tpu.models import events, serf, swim, vivaldi

__all__ = ["events", "serf", "swim", "vivaldi"]
