"""WAN router: cross-DC request forwarding + coordinate-ranked DC lists.

Host side of Consul's multi-DC story (SURVEY.md §2.2): each DC is its own
raft/catalog domain; requests carrying `?dc=` forward to that DC's
servers (agent/consul/rpc.go:658 forwardDC), and failover/ranking orders
DCs by WAN Vivaldi distance (agent/router/router.go:534
GetDatacentersByDistance).

The router holds one handle per known DC.  In-process handles wrap the
remote DC's store directly (the reference's connection-pool RPC collapses
to a method call); a socket-backed handle can forward over
consul_tpu/rpc the same way.  WAN distances come from a pluggable
`distance_fn(dc_a, dc_b) -> seconds` — wire it to the WAN federation
model's dc_distance_matrix (models/wan.py:206) or to live telemetry.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional


class NoPathError(Exception):
    """Unknown / unreachable datacenter (structs.ErrNoDCPath)."""


class DcHandle:
    """One datacenter's serving surface as seen by remote DCs."""

    def __init__(self, name: str, store, query_executor=None):
        self.name = name
        self.store = store
        self.query_executor = query_executor


class WanRouter:
    def __init__(self, local_dc: str,
                 distance_fn: Optional[Callable[[str, str], float]] = None):
        self.local_dc = local_dc
        self.distance_fn = distance_fn
        self._dcs: Dict[str, DcHandle] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------ registry

    def register(self, handle: DcHandle) -> None:
        with self._lock:
            self._dcs[handle.name] = handle

    def deregister(self, name: str) -> None:
        with self._lock:
            self._dcs.pop(name, None)

    def datacenters(self) -> List[str]:
        """All known DCs, local first, remainder by WAN distance
        (GetDatacentersByDistance ordering)."""
        with self._lock:
            names = list(self._dcs)
        if self.local_dc not in names:
            names.append(self.local_dc)
        remote = [d for d in names if d != self.local_dc]
        if self.distance_fn is not None:
            remote.sort(key=lambda d: (self.distance_fn(self.local_dc, d),
                                       d))
        else:
            remote.sort()
        return [self.local_dc] + remote

    def handle(self, dc: str) -> DcHandle:
        with self._lock:
            h = self._dcs.get(dc)
        if h is None:
            raise NoPathError(f"No path to datacenter: {dc!r}")
        return h

    # ---------------------------------------------------------- forwarding

    def store_for(self, dc: Optional[str]):
        """The store serving `dc` (None/local → local store), for read and
        write forwarding (rpc.go:658 forwardDC)."""
        if dc in (None, "", self.local_dc):
            return self.handle(self.local_dc).store
        return self.handle(dc).store

    def execute_query(self, dc: str, query: dict) -> List[dict]:
        """Cross-DC prepared-query execution (ExecuteRemote,
        prepared_query_endpoint.go:477): run the already-resolved query's
        service lookup against the remote DC."""
        h = self.handle(dc)
        if h.query_executor is not None:
            res = h.query_executor.execute_resolved(query)
            return res
        return []
