from consul_tpu.checks.runner import (
    CheckAlias, CheckDocker, CheckGRPC, CheckH2PING, CheckHTTP, CheckMonitor,
    CheckManager, CheckTCP, CheckTTL,
)

__all__ = ["CheckAlias", "CheckDocker", "CheckGRPC", "CheckH2PING",
           "CheckHTTP", "CheckMonitor", "CheckManager", "CheckTCP",
           "CheckTTL"]
