"""Health-check runners — the agent/checks/ package equivalent.

The reference ships 8 runner types (agent/checks/check.go): interval exec
(CheckMonitor :65), TTL (CheckTTL :233), HTTP (CheckHTTP :335), HTTP/2
ping (CheckH2PING :509), TCP (CheckTCP :610), Docker exec (CheckDocker
:693), gRPC health (CheckGRPC :821) and alias (alias.go:23).  Each runs on
its own interval with random initial stagger and reports status through a
notifier callback — here `notify(check_id, status, output)`, the
equivalent of the reference's CheckNotifier (local state).

Statuses: "passing" | "warning" | "critical" (api.Health* constants).
Output is truncated to BufSize=4K like the reference (checks/check.go
CheckBufSize).
"""

from __future__ import annotations

import random
import shutil
import socket
import ssl
import struct
import subprocess
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Optional

PASSING, WARNING, CRITICAL = "passing", "warning", "critical"
OUTPUT_MAX = 4096

Notify = Callable[[str, str, str], None]   # (check_id, status, output)


class _IntervalRunner:
    """Base: fire `check()` every `interval` seconds with initial stagger
    (lib.RandomStagger — checks start spread to avoid thundering herd)."""

    def __init__(self, check_id: str, notify: Notify, interval: float,
                 timeout: float = 10.0):
        self.check_id = check_id
        self.notify = notify
        self.interval = interval
        self.timeout = timeout
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)

    def _loop(self) -> None:
        # initial stagger in [0, interval)
        if self._stop.wait(random.random() * min(self.interval, 1.0)):
            return
        while not self._stop.is_set():
            try:
                status, output = self.check()
            except Exception as e:  # runner bugs surface as critical
                status, output = CRITICAL, f"check raised: {e}"
            self.notify(self.check_id, status, output[:OUTPUT_MAX])
            if self._stop.wait(self.interval):
                return

    def check(self):  # pragma: no cover - abstract
        raise NotImplementedError


class CheckTTL:
    """TTL check (check.go:233): the application pushes status via the
    agent API; silence past the TTL flips it critical."""

    def __init__(self, check_id: str, notify: Notify, ttl: float):
        self.check_id = check_id
        self.notify = notify
        self.ttl = ttl
        self._deadline = time.time() + ttl
        self._expired = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)

    def set_status(self, status: str, output: str = "") -> None:
        """App heartbeat (agent/check/pass|warn|fail → SetStatus)."""
        with self._lock:
            self._deadline = time.time() + self.ttl
            self._expired = False
        self.notify(self.check_id, status, output[:OUTPUT_MAX])

    def _loop(self) -> None:
        while not self._stop.wait(min(self.ttl / 4, 0.25)):
            with self._lock:
                expired = time.time() >= self._deadline and not self._expired
                if expired:
                    self._expired = True
            if expired:
                self.notify(self.check_id, CRITICAL,
                            f"TTL expired ({self.ttl}s)")


class CheckHTTP(_IntervalRunner):
    """HTTP GET: 2xx passing, 429 warning, anything else critical
    (check.go:335 CheckHTTP.check)."""

    def __init__(self, check_id: str, notify: Notify, url: str,
                 interval: float, timeout: float = 10.0,
                 method: str = "GET", header: dict | None = None,
                 tls_skip_verify: bool = False):
        super().__init__(check_id, notify, interval, timeout)
        self.url = url
        self.method = method
        self.header = header or {}
        # TLSSkipVerify parity (check.go honors it for self-signed targets)
        self.tls_skip_verify = tls_skip_verify

    def check(self):
        req = urllib.request.Request(self.url, method=self.method)
        req.add_header("User-Agent", "Consul Health Check")
        req.add_header("Accept", "text/plain, text/*, */*")
        for k, v in self.header.items():
            req.add_header(k, v)
        ctx = None
        if self.tls_skip_verify:
            import ssl
            ctx = ssl.create_default_context()
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        try:
            with urllib.request.urlopen(req, timeout=self.timeout,
                                        context=ctx) as resp:
                body = resp.read(OUTPUT_MAX).decode(errors="replace")
                return PASSING, f"HTTP {self.method} {self.url}: " \
                                f"{resp.status}  Output: {body}"
        except urllib.error.HTTPError as e:
            body = e.read(OUTPUT_MAX).decode(errors="replace")
            status = WARNING if e.code == 429 else CRITICAL
            return status, f"HTTP {self.method} {self.url}: {e.code}  " \
                           f"Output: {body}"
        except Exception as e:
            return CRITICAL, f"HTTP {self.method} {self.url}: {e}"


class CheckTCP(_IntervalRunner):
    """TCP connect probe (check.go:610)."""

    def __init__(self, check_id: str, notify: Notify, tcp: str,
                 interval: float, timeout: float = 10.0):
        super().__init__(check_id, notify, interval, timeout)
        host, _, port = tcp.rpartition(":")
        self.addr = (host or "127.0.0.1", int(port))

    def check(self):
        try:
            with socket.create_connection(self.addr, timeout=self.timeout):
                return PASSING, f"TCP connect {self.addr[0]}:" \
                                f"{self.addr[1]}: Success"
        except OSError as e:
            return CRITICAL, f"TCP connect {self.addr[0]}:" \
                             f"{self.addr[1]}: {e}"


class CheckMonitor(_IntervalRunner):
    """Interval exec check (check.go:65): exit 0 passing, 1 warning,
    other critical; stdout+stderr captured as output."""

    def __init__(self, check_id: str, notify: Notify, args: list[str],
                 interval: float, timeout: float = 30.0):
        super().__init__(check_id, notify, interval, timeout)
        self.args = args

    def check(self):
        try:
            proc = subprocess.run(self.args, capture_output=True,
                                  timeout=self.timeout)
        except subprocess.TimeoutExpired:
            return CRITICAL, f"exec timed out after {self.timeout}s"
        except OSError as e:
            return CRITICAL, f"exec failed: {e}"
        output = (proc.stdout + proc.stderr).decode(errors="replace")
        status = {0: PASSING, 1: WARNING}.get(proc.returncode, CRITICAL)
        return status, output


class CheckH2PING(_IntervalRunner):
    """HTTP/2 ping (check.go:509): client preface + SETTINGS, then a PING
    frame; a PING ack within the timeout is passing.  Hand-rolled h2
    framing — 9-byte frame header (len, type, flags, stream id)."""

    _PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"
    _PING_TYPE = 0x6

    def __init__(self, check_id: str, notify: Notify, h2ping: str,
                 interval: float, timeout: float = 10.0,
                 use_tls: bool = False):
        super().__init__(check_id, notify, interval, timeout)
        host, _, port = h2ping.rpartition(":")
        self.addr = (host or "127.0.0.1", int(port))
        self.use_tls = use_tls

    def _frame(self, ftype: int, flags: int, payload: bytes) -> bytes:
        return struct.pack(">I", len(payload))[1:] + \
            bytes([ftype, flags]) + b"\x00\x00\x00\x00" + payload

    def check(self):
        try:
            sock = socket.create_connection(self.addr, timeout=self.timeout)
            if self.use_tls:
                ctx = ssl.create_default_context()
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
                ctx.set_alpn_protocols(["h2"])
                sock = ctx.wrap_socket(sock, server_hostname=self.addr[0])
            with sock:
                sock.sendall(self._PREFACE + self._frame(0x4, 0, b""))
                opaque = struct.pack(">Q", 0x7075736870696e67)  # "pushping"
                sock.sendall(self._frame(self._PING_TYPE, 0, opaque))
                deadline = time.time() + self.timeout
                buf = b""
                while time.time() < deadline:
                    sock.settimeout(max(0.05, deadline - time.time()))
                    chunk = sock.recv(4096)
                    if not chunk:
                        break
                    buf += chunk
                    while len(buf) >= 9:
                        ln = int.from_bytes(b"\x00" + buf[:3], "big")
                        if len(buf) < 9 + ln:
                            break
                        ftype, flags = buf[3], buf[4]
                        payload = buf[9:9 + ln]
                        buf = buf[9 + ln:]
                        if ftype == self._PING_TYPE and flags & 0x1 \
                                and payload == opaque:
                            return PASSING, "HTTP2 ping acked"
                return CRITICAL, "no HTTP2 ping ack before timeout"
        except OSError as e:
            return CRITICAL, f"h2ping {self.addr[0]}:{self.addr[1]}: {e}"


class CheckGRPC(_IntervalRunner):
    """gRPC health-v1 probe (check.go:821).  Uses grpcio when installed;
    otherwise reports critical with an explicit unsupported message (the
    environment gates optional deps rather than pip-installing)."""

    def __init__(self, check_id: str, notify: Notify, grpc_target: str,
                 interval: float, timeout: float = 10.0):
        super().__init__(check_id, notify, interval, timeout)
        self.target = grpc_target

    def check(self):
        try:
            import grpc  # noqa: F401  (optional)
            from grpc_health.v1 import health_pb2, health_pb2_grpc
        except ImportError:
            return CRITICAL, "grpc check unsupported: grpcio not installed"
        channel = grpc.insecure_channel(self.target)
        try:
            stub = health_pb2_grpc.HealthStub(channel)
            resp = stub.Check(health_pb2.HealthCheckRequest(service=""),
                              timeout=self.timeout)
            if resp.status == health_pb2.HealthCheckResponse.SERVING:
                return PASSING, "gRPC SERVING"
            return CRITICAL, f"gRPC status {resp.status}"
        except Exception as e:
            return CRITICAL, f"gRPC check failed: {e}"
        finally:
            channel.close()


class CheckDocker(_IntervalRunner):
    """Docker exec check (check.go:693) via the docker CLI; critical with
    an explicit message when no docker binary is present."""

    def __init__(self, check_id: str, notify: Notify, container: str,
                 args: list[str], interval: float, timeout: float = 30.0):
        super().__init__(check_id, notify, interval, timeout)
        self.container = container
        self.args = args

    def check(self):
        if shutil.which("docker") is None:
            return CRITICAL, "docker check unsupported: no docker binary"
        try:
            proc = subprocess.run(
                ["docker", "exec", self.container, *self.args],
                capture_output=True, timeout=self.timeout)
        except subprocess.TimeoutExpired:
            return CRITICAL, f"docker exec timed out after {self.timeout}s"
        output = (proc.stdout + proc.stderr).decode(errors="replace")
        status = {0: PASSING, 1: WARNING}.get(proc.returncode, CRITICAL)
        return status, output


class CheckAlias(_IntervalRunner):
    """Alias check (alias.go:23): mirrors the aggregate status of another
    service's checks read from a store-shaped source (worst status wins;
    no checks at all is passing, like the reference's empty-checks rule)."""

    def __init__(self, check_id: str, notify: Notify, store,
                 node: str, service_id: str, interval: float = 0.5):
        super().__init__(check_id, notify, interval)
        self.store = store
        self.node = node
        self.service_id = service_id

    def check(self):
        checks = [c for c in self.store.node_checks(self.node)
                  if not self.service_id
                  or c["service_id"] in ("", self.service_id)]
        checks = [c for c in checks if c["check_id"] != self.check_id]
        if any(c["status"] == CRITICAL for c in checks):
            return CRITICAL, "aliased target critical"
        if any(c["status"] == WARNING for c in checks):
            return WARNING, "aliased target warning"
        return PASSING, "All checks passing"


class CheckManager:
    """Owns runner lifecycle per check id (the agent's checkMonitors /
    checkTTLs / checkHTTPs maps, agent/agent.go:2405 region)."""

    def __init__(self, notify: Notify):
        self.notify = notify
        self._runners: dict[str, object] = {}
        self._lock = threading.Lock()
        # check_id -> definition dict, for persistence/restart re-arming
        # (the reference persists the full CheckType, agent/agent.go:533)
        self.definitions: dict[str, dict] = {}

    def add(self, runner) -> None:
        with self._lock:
            old = self._runners.pop(runner.check_id, None)
            self._runners[runner.check_id] = runner
        if old is not None:
            old.stop()
        runner.start()

    def remove(self, check_id: str) -> None:
        with self._lock:
            runner = self._runners.pop(check_id, None)
        if runner is not None:
            runner.stop()

    def ttl(self, check_id: str) -> Optional[CheckTTL]:
        with self._lock:
            r = self._runners.get(check_id)
        return r if isinstance(r, CheckTTL) else None

    def stop_all(self) -> None:
        with self._lock:
            runners = list(self._runners.values())
            self._runners.clear()
        for r in runners:
            r.stop()

    def from_definition(self, check_id: str, defn: dict):
        """Build a runner from an HTTP-API check definition (the
        reference's structs.CheckType dispatch, agent/agent.go:2403)."""
        self.definitions[check_id] = dict(defn)
        interval = defn.get("interval", 10.0)
        timeout = defn.get("timeout", 10.0)
        if defn.get("ttl"):
            return CheckTTL(check_id, self.notify, defn["ttl"])
        if defn.get("http"):
            return CheckHTTP(check_id, self.notify, defn["http"], interval,
                             timeout, method=defn.get("method", "GET"),
                             header=defn.get("header"),
                             tls_skip_verify=defn.get("tls_skip_verify",
                                                      False))
        if defn.get("tcp"):
            return CheckTCP(check_id, self.notify, defn["tcp"], interval,
                            timeout)
        if defn.get("args"):
            return CheckMonitor(check_id, self.notify, defn["args"],
                                interval, timeout)
        if defn.get("h2ping"):
            return CheckH2PING(check_id, self.notify, defn["h2ping"],
                               interval, timeout)
        if defn.get("grpc"):
            return CheckGRPC(check_id, self.notify, defn["grpc"], interval,
                             timeout)
        if defn.get("docker_container_id"):
            return CheckDocker(check_id, self.notify,
                               defn["docker_container_id"],
                               defn.get("shell_args", ["true"]), interval,
                               timeout)
        return None
