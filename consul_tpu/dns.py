"""DNS interface — the agent/dns.go equivalent over (StateStore, oracle).

Serves the reference's DNS surface (agent/dns.go:111 DNSServer;
dispatch :644) from the host state store and the TPU membership oracle:

    <node>.node.<domain>                      A / AAAA / ANY
    [<tag>.]<service>.service.<domain>        A (healthy instances)
    _<service>._<tag>.service.<domain>        SRV (RFC 2782, :1805)
    <query>.query.<domain>                    prepared query execute
    <reversed>.in-addr.arpa                   PTR (node by address)
    <domain> SOA/NS                           zone records

Health filtering drops critical instances (only_passing drops warning
too — lookupServiceNodes :1218); results are RTT-sorted from this agent
via the oracle's Vivaldi coordinates when available, else shuffled for
load spread.  UDP answers overflowing the client budget set TC and
truncate (:  the reference trims + sets Truncated, dns.go:1432 region);
the same port serves TCP for the retry.

Wire format is hand-rolled (header/question/RR encode-decode, RFC 1035)
— no external dns library, mirroring how the reference carries miekg/dns
rather than a resolver.
"""

from __future__ import annotations

import random
import socket
import socketserver
import struct
import threading
from typing import Callable, List, Optional, Tuple

# record types
A, NS, CNAME, SOA, PTR, TXT, AAAA, SRV, ANY = \
    1, 2, 5, 6, 12, 16, 28, 33, 255
IN = 1
NOERROR, FORMERR, SERVFAIL, NXDOMAIN, NOTIMP, REFUSED = 0, 1, 2, 3, 4, 5


# ------------------------------------------------------------- wire codec

def encode_name(name: str) -> bytes:
    out = b""
    for label in name.rstrip(".").split("."):
        if not label:
            continue
        raw = label.encode()
        if len(raw) > 63:
            raise ValueError("label too long")
        out += bytes([len(raw)]) + raw
    return out + b"\x00"


def decode_name(data: bytes, off: int) -> Tuple[str, int]:
    labels = []
    jumps = 0
    pos = off
    end = None
    while True:
        if pos >= len(data):
            raise ValueError("truncated name")
        ln = data[pos]
        if ln & 0xC0 == 0xC0:            # compression pointer
            if end is None:
                end = pos + 2
            pos = ((ln & 0x3F) << 8) | data[pos + 1]
            jumps += 1
            if jumps > 32:
                raise ValueError("compression loop")
            continue
        pos += 1
        if ln == 0:
            break
        labels.append(data[pos:pos + ln].decode(errors="replace"))
        pos += ln
    return ".".join(labels), (end if end is not None else pos)


def parse_query(data: bytes) -> Tuple[int, int, str, int]:
    """Returns (txn_id, flags, qname_lowercase, qtype); first question
    only, like the reference handler."""
    if len(data) < 12:
        raise ValueError("short packet")
    txn_id, flags, qd, _, _, _ = struct.unpack(">HHHHHH", data[:12])
    if qd < 1:
        raise ValueError("no question")
    name, off = decode_name(data, 12)
    qtype, _qclass = struct.unpack(">HH", data[off:off + 4])
    return txn_id, flags, name.lower(), qtype


OPT = 41


def edns_udp_size(data: bytes) -> Optional[int]:
    """The EDNS0 advertised UDP payload size from the query's OPT
    pseudo-record, or None when the client sent none (RFC 6891; the
    reference honors it via miekg/dns SetEdns0 — truncation budgets
    scale to what the resolver can actually receive)."""
    try:
        txn_id, flags, qd, an, ns, ar = struct.unpack(">HHHHHH",
                                                      data[:12])
        if ar < 1:
            return None
        off = 12
        for _ in range(qd):
            _, off = decode_name(data, off)
            off += 4
        for _ in range(an + ns):
            _, off = decode_name(data, off)
            _t, _c, _ttl, rdlen = struct.unpack(
                ">HHIH", data[off:off + 10])
            off += 10 + rdlen
        for _ in range(ar):
            _, off = decode_name(data, off)
            rtype, klass, _ttl, rdlen = struct.unpack(
                ">HHIH", data[off:off + 10])
            off += 10 + rdlen
            if rtype == OPT:
                # CLASS field carries the payload size for OPT
                return max(512, min(int(klass), 65535))
    except (struct.error, ValueError):
        return None
    return None


def parse_recursor(addr: str) -> Tuple[str, int]:
    """'1.2.3.4', 'host:53', '::1', '[::1]:53' → (host, port); default
    port 53 (agent/dns.go:251 recursor address normalization)."""
    addr = addr.strip()
    if addr.startswith("["):
        host, _, rest = addr[1:].partition("]")
        p = rest.lstrip(":")
        return host, int(p) if p else 53
    if addr.count(":") > 1:          # bare IPv6 literal
        return addr, 53
    host, _, p = addr.partition(":")
    return host, int(p) if p else 53


class RR:
    def __init__(self, name: str, rtype: int, rdata: bytes, ttl: int = 0):
        self.name = name
        self.rtype = rtype
        self.rdata = rdata
        self.ttl = ttl

    def pack(self) -> bytes:
        return encode_name(self.name) + struct.pack(
            ">HHIH", self.rtype, IN, self.ttl, len(self.rdata)) + self.rdata


def a_rdata(ip: str) -> bytes:
    return socket.inet_aton(ip)


def aaaa_rdata(ip6: str) -> bytes:
    return socket.inet_pton(socket.AF_INET6, ip6)


def srv_rdata(priority: int, weight: int, port: int, target: str) -> bytes:
    return struct.pack(">HHH", priority, weight, port) + encode_name(target)


def ptr_rdata(target: str) -> bytes:
    return encode_name(target)


def soa_rdata(mname: str, rname: str, serial: int) -> bytes:
    return encode_name(mname) + encode_name(rname) + struct.pack(
        ">IIIII", serial, 3600, 600, 86400, 0)


def txt_rdata(text: str) -> bytes:
    raw = text.encode()[:255]
    return bytes([len(raw)]) + raw


def build_response(txn_id: int, qname: str, qtype: int,
                   answers: List[RR], authority: List[RR] | None = None,
                   rcode: int = NOERROR, aa: bool = True,
                   tc: bool = False, rd: bool = False) -> bytes:
    flags = 0x8000 | (0x0400 if aa else 0) | (0x0200 if tc else 0) \
        | (0x0100 if rd else 0) | rcode
    authority = authority or []
    head = struct.pack(">HHHHHH", txn_id, flags, 1, len(answers),
                       len(authority), 0)
    q = encode_name(qname) + struct.pack(">HH", qtype, IN)
    body = b"".join(r.pack() for r in answers) + \
        b"".join(r.pack() for r in authority)
    return head + q + body


# ------------------------------------------------------------- the server

UDP_BUDGET = 512     # pre-EDNS answer budget (dns.go maxUDPAnswerLimit)


class DNSServer:
    """UDP+TCP DNS frontend (agent/dns.go:111).  `query_executor` is an
    optional hook for <name>.query.<domain> lookups (prepared queries) —
    returns health-service-shaped rows."""

    def __init__(self, store, oracle=None, node_name: str = "node0",
                 domain: str = "consul.", host: str = "127.0.0.1",
                 port: int = 0, only_passing: bool = False,
                 node_ttl: int = 0, service_ttl: int = 0,
                 query_executor: Optional[Callable[[str], list]] = None,
                 authz: Optional[Callable[[], object]] = None,
                 recursors: Optional[List[str]] = None,
                 recursor_timeout: float = 2.0):
        self.store = store
        self.oracle = oracle
        self.node_name = node_name
        self.domain = domain.rstrip(".").lower()
        self.only_passing = only_passing
        self.node_ttl = node_ttl
        self.service_ttl = service_ttl
        self.query_executor = query_executor
        # DNS queries carry no token: lookups run under the agent's token
        # like the reference (DNS rides the RPC/ACL path with the agent
        # token) — `authz` returns that resolved Authorizer per query
        self.authz = authz
        # Upstream recursors for out-of-zone names (agent/dns.go:251
        # validation, :437 handleRecurse): "host" or "host:port" strings,
        # tried in order; first well-formed reply wins.
        self.recursors: List[Tuple[str, int]] = [
            parse_recursor(r) for r in recursors or []]
        self.recursor_timeout = recursor_timeout
        self._tls = threading.local()

        outer = self

        class UdpHandler(socketserver.BaseRequestHandler):
            def handle(self):
                data, sock = self.request
                resp = outer.handle_packet(data, udp=True)
                if resp is not None:
                    sock.sendto(resp, self.client_address)

        class TcpHandler(socketserver.BaseRequestHandler):
            def handle(self):
                raw = self.request.recv(2)
                if len(raw) < 2:
                    return
                (ln,) = struct.unpack(">H", raw)
                data = b""
                while len(data) < ln:
                    chunk = self.request.recv(ln - len(data))
                    if not chunk:
                        return
                    data += chunk
                resp = outer.handle_packet(data, udp=False)
                if resp is not None:
                    self.request.sendall(struct.pack(">H", len(resp)) + resp)

        # DNS convention: UDP and TCP share one port.  With port=0 the
        # kernel picks the UDP port first and the matching TCP bind can
        # lose a race to another process on a busy box — retry with a
        # fresh ephemeral pair instead of failing agent startup.
        last_err: Optional[OSError] = None
        for _ in range(8):
            self.udp = socketserver.ThreadingUDPServer((host, port),
                                                       UdpHandler)
            self.port = self.udp.server_address[1]
            try:
                self.tcp = socketserver.ThreadingTCPServer(
                    (host, self.port), TcpHandler,
                    bind_and_activate=False)
                self.tcp.allow_reuse_address = True
                self.tcp.server_bind()
                self.tcp.server_activate()
                break
            except OSError as e:
                # the UDP socket must not leak even when the TCP
                # CONSTRUCTOR itself fails (e.g. fd exhaustion)
                last_err = e
                if getattr(self, "tcp", None) is not None:
                    self.tcp.server_close()
                self.udp.server_close()
                if port != 0:
                    raise        # a FIXED port conflict is fatal
        else:
            raise last_err       # eight ephemeral pairs taken: give up
        self._threads: List[threading.Thread] = []

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        for srv in (self.udp, self.tcp):
            t = threading.Thread(target=srv.serve_forever, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        for srv in (self.udp, self.tcp):
            # shutdown() parks forever unless serve_forever is running
            if self._threads:
                srv.shutdown()
            srv.server_close()
        for t in self._threads:
            t.join(timeout=2.0)

    # ------------------------------------------------------------- dispatch

    def handle_packet(self, data: bytes, udp: bool) -> Optional[bytes]:
        self._tls.authz = None  # fresh authorizer per query
        try:
            txn_id, flags, qname, qtype = parse_query(data)
        except ValueError:
            return None
        # Out-of-zone names go to the configured recursors verbatim
        # (agent/dns.go:437 handleRecurse); with none configured the
        # resolver falls through to REFUSED below.
        name = qname.rstrip(".")
        arpa = name.endswith(".in-addr.arpa") or name.endswith(".ip6.arpa")
        in_zone = (name == self.domain
                   or name.endswith("." + self.domain) or arpa)
        if not in_zone and self.recursors:
            return self._recurse(data, txn_id, qname, qtype, udp)
        try:
            answers, rcode = self.resolve(qname, qtype)
        except Exception:
            return build_response(0xFFFF & txn_id, qname, qtype, [],
                                  rcode=SERVFAIL)
        if arpa and rcode == NXDOMAIN and not answers and self.recursors:
            # unknown reverse names also recurse (dns.go handlePtr tail)
            return self._recurse(data, txn_id, qname, qtype, udp)
        tc = False
        if udp and answers:
            # EDNS0: a client advertising a bigger receive buffer gets
            # a bigger truncation budget (agent/dns.go setEDNS role)
            budget = edns_udp_size(data) or UDP_BUDGET
            kept = list(answers)
            while kept and 12 + len(encode_name(qname)) + 4 + sum(
                    len(r.pack()) for r in kept) > budget:
                kept.pop()
                tc = True
            answers = kept
        authority = []
        if rcode == NXDOMAIN or not answers:
            authority = [self.soa()]
        return build_response(txn_id, qname, qtype, answers,
                              authority=authority, rcode=rcode, tc=tc)

    def _recurse(self, packet: bytes, txn_id: int, qname: str, qtype: int,
                 udp: bool) -> bytes:
        """Forward the original packet to each recursor in order and
        relay the first reply whose id matches; all-fail answers
        SERVFAIL with RA set (agent/dns.go:437-500)."""
        for host, port in self.recursors:
            try:
                if udp:
                    s = socket.socket(socket.AF_INET6 if ":" in host
                                      else socket.AF_INET,
                                      socket.SOCK_DGRAM)
                    try:
                        s.settimeout(self.recursor_timeout)
                        # connect() so the kernel filters datagrams by
                        # peer address — an off-path reply spoofed from
                        # another source can't be relayed (miekg/dns
                        # clients connect the same way)
                        s.connect((host, port))
                        s.send(packet)
                        resp = s.recv(4096)
                    finally:
                        s.close()
                else:
                    with socket.create_connection(
                            (host, port),
                            timeout=self.recursor_timeout) as s:
                        s.sendall(struct.pack(">H", len(packet)) + packet)
                        raw = s.recv(2)
                        if len(raw) < 2:
                            continue
                        (ln,) = struct.unpack(">H", raw)
                        resp = b""
                        while len(resp) < ln:
                            chunk = s.recv(ln - len(resp))
                            if not chunk:
                                break
                            resp += chunk
                        if len(resp) < ln:
                            continue   # truncated mid-body: next recursor
                if len(resp) >= 12 and resp[:2] == packet[:2]:
                    out = bytearray(resp)
                    out[2] |= 0x80   # QR: this is a response
                    out[3] |= 0x80   # RA: recursion was available
                    return bytes(out)
            except OSError:
                continue
        return build_response(txn_id, qname, qtype, [], rcode=SERVFAIL,
                              aa=False, rd=True)

    def soa(self) -> RR:
        idx = getattr(self.store, "index", 0)
        return RR(self.domain, SOA,
                  soa_rdata(f"ns.{self.domain}",
                            f"hostmaster.{self.domain}", idx))

    # -------------------------------------------------------------- resolve

    def resolve(self, qname: str, qtype: int) -> Tuple[List[RR], int]:
        """The dispatch tree (agent/dns.go:644)."""
        name = qname.rstrip(".").lower()
        if name.endswith(".in-addr.arpa"):
            return self._ptr(name)
        if name == self.domain:
            if qtype in (SOA, ANY):
                return [self.soa()], NOERROR
            if qtype == NS:
                ns = f"ns.{self.domain}"
                return [RR(self.domain, NS, ptr_rdata(ns))], NOERROR
            return [], NOERROR
        if not name.endswith("." + self.domain):
            return [], REFUSED    # not our zone; no recursors configured
        rest = name[: -(len(self.domain) + 1)]
        labels = rest.split(".")
        # strip optional datacenter label: <...>.<dc>.<domain> — accept and
        # ignore (single-dc view), mirroring parseDatacenter
        if len(labels) >= 2 and labels[-1] not in ("node", "service",
                                                   "query", "addr"):
            labels = labels[:-1]
        if len(labels) < 2:
            return [], NXDOMAIN
        kind = labels[-1]
        if kind == "node":
            return self._node(".".join(labels[:-1]), qtype)
        if kind == "service":
            return self._service(labels[:-1], qtype)
        if kind == "query":
            return self._query(".".join(labels[:-1]), qtype)
        if kind == "addr":
            return self._addr(labels[0], qtype)
        return [], NXDOMAIN

    # ------------------------------------------------------------- handlers

    def _authorizer(self):
        """Resolve once per query (handle_packet caches on a thread local)
        — per-row resolution was O(catalog) authorizer builds per PTR."""
        if self.authz is None:
            return None
        cached = getattr(self._tls, "authz", None)
        if cached is None:
            cached = self.authz()
            self._tls.authz = cached
        return cached

    def _node_readable(self, node: str) -> bool:
        a = self._authorizer()
        return a is None or a.node_read(node)

    def _service_readable(self, service: str) -> bool:
        a = self._authorizer()
        return a is None or a.service_read(service)

    def _node_address(self, node: str) -> Optional[str]:
        if not self._node_readable(node):
            return None  # denied reads answer NXDOMAIN, not a leak
        rec = next((n for n in self.store.nodes() if n["node"] == node),
                   None)
        return rec["address"] if rec else None

    def _node(self, node: str, qtype: int) -> Tuple[List[RR], int]:
        addr = self._node_address(node)
        if addr is None:
            return [], NXDOMAIN
        fqdn = f"{node}.node.{self.domain}"
        return self._addr_rrs(fqdn, addr, qtype, self.node_ttl), NOERROR

    def _addr_rrs(self, fqdn: str, addr: str, qtype: int,
                  ttl: int) -> List[RR]:
        try:
            if ":" in addr:
                if qtype in (AAAA, ANY):
                    return [RR(fqdn, AAAA, aaaa_rdata(addr), ttl)]
                return []
            if qtype in (A, ANY, SRV):
                return [RR(fqdn, A, a_rdata(addr), ttl)]
        except OSError:
            # non-IP address (hostname): answer with TXT like the
            # reference's CNAME fallback stance for non-IP addresses
            return [RR(fqdn, TXT, txt_rdata(addr), ttl)]
        return []

    def _healthy_instances(self, service: str, tag: Optional[str]) -> list:
        if not self._service_readable(service):
            return []
        rows = self.store.health_service_nodes(service, tag=tag)
        out = []
        for r in rows:
            statuses = [c["status"] for c in r["checks"]]
            if any(s == "critical" for s in statuses):
                continue
            if self.only_passing and any(s == "warning" for s in statuses):
                continue
            if not self._node_readable(r["service"]["node"]):
                continue
            out.append(r["service"])
        return out

    def _rtt_order(self, instances: list) -> list:
        if self.oracle is not None:
            try:
                order = self.oracle.sort_by_rtt(
                    self.node_name, [s["node"] for s in instances])
                pos = {n: i for i, n in enumerate(order)}
                return sorted(instances,
                              key=lambda s: pos.get(s["node"], 1 << 30))
            except (KeyError, IndexError):
                pass
        instances = list(instances)
        random.shuffle(instances)
        return instances

    def _service(self, labels: List[str],
                 qtype: int) -> Tuple[List[RR], int]:
        # RFC 2782 form: _<service>._<tag|tcp|udp>
        if len(labels) == 2 and labels[0].startswith("_") \
                and labels[1].startswith("_"):
            service = labels[0][1:]
            tag = labels[1][1:]
            if tag in ("tcp", "udp"):
                tag = None
            return self._service_records(service, tag, qtype, srv_form=True)
        # [tag.]<service>
        service = labels[-1]
        tag = labels[0] if len(labels) == 2 else None
        if len(labels) > 2:
            return [], NXDOMAIN
        return self._service_records(service, tag, qtype, srv_form=False)

    def _service_records(self, service: str, tag: Optional[str], qtype: int,
                         srv_form: bool) -> Tuple[List[RR], int]:
        instances = self._healthy_instances(service, tag)
        if not instances:
            return [], NXDOMAIN
        instances = self._rtt_order(instances)
        fqdn = f"{service}.service.{self.domain}"
        out: List[RR] = []
        if qtype == SRV or (srv_form and qtype == ANY):
            for s in instances:
                target = f"{s['node']}.node.{self.domain}"
                out.append(RR(fqdn, SRV,
                              srv_rdata(1, 1, s["port"], target),
                              self.service_ttl))
            # additional A records ride authority-free in the answer
            # section for simplicity (the reference puts them in Extra)
            for s in instances:
                addr = s["service_address"] or s["address"]
                out.extend(self._addr_rrs(
                    f"{s['node']}.node.{self.domain}", addr, A,
                    self.service_ttl))
            return out, NOERROR
        for s in instances:
            addr = s["service_address"] or s["address"]
            out.extend(self._addr_rrs(fqdn, addr, qtype, self.service_ttl))
        return out, NOERROR

    def _query(self, name: str, qtype: int) -> Tuple[List[RR], int]:
        if self.query_executor is None:
            return [], NXDOMAIN
        rows = self.query_executor(name)
        if rows is None:
            return [], NXDOMAIN
        instances = [r["service"] for r in rows]
        if not instances:
            return [], NXDOMAIN
        fqdn = f"{name}.query.{self.domain}"
        out: List[RR] = []
        if qtype == SRV:
            for s in instances:
                target = f"{s['node']}.node.{self.domain}"
                out.append(RR(fqdn, SRV,
                              srv_rdata(1, 1, s["port"], target),
                              self.service_ttl))
            return out, NOERROR
        for s in instances:
            addr = s["service_address"] or s["address"]
            out.extend(self._addr_rrs(fqdn, addr, qtype, self.service_ttl))
        return out, NOERROR

    def _addr(self, hexip: str, qtype: int) -> Tuple[List[RR], int]:
        """<hex-ip>.addr.<domain> — synthesized names used inside SRV
        answers for service addresses (dns.go formatNodeRecord)."""
        try:
            raw = bytes.fromhex(hexip)
            addr = socket.inet_ntoa(raw) if len(raw) == 4 else \
                socket.inet_ntop(socket.AF_INET6, raw)
        except (ValueError, OSError):
            return [], NXDOMAIN
        return self._addr_rrs(f"{hexip}.addr.{self.domain}", addr, qtype,
                              self.node_ttl), NOERROR

    def _ptr(self, name: str) -> Tuple[List[RR], int]:
        parts = name.replace(".in-addr.arpa", "").split(".")
        if len(parts) != 4:
            return [], NXDOMAIN
        addr = ".".join(reversed(parts))
        for n in self.store.nodes():
            if n["address"] != addr:
                continue
            if not self._node_readable(n["node"]):
                continue
            return [RR(name, PTR,
                       ptr_rdata(f"{n['node']}.node.{self.domain}"),
                       self.node_ttl)], NOERROR
        return [], NXDOMAIN
