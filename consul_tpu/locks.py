"""Instrumented lock seam: the runtime half of the lock-discipline plane.

The reference's standing concurrency gate is `go test -race` over a tree
where every subsystem shares state across goroutines.  Python has no
TSan, so four PRs' worth of locking contracts (raft's staged
`_metrics_buf`, the store's "nothing emits under the store lock",
ViewStore's "registry lock never held across a snapshot", the
publisher's stage-then-flush eviction accounting) lived only in PR
descriptions.  This module is the TSan-lite seam that makes them
observable:

  * `make_lock(name)` / `make_rlock(name)` / `make_condition(lock)` —
    every production lock in consensus/, catalog/, stream/, api/,
    ratelimit.py, visibility.py, submatview.py, flight.py is created
    through these.  **Zero-cost passthrough** unless audit mode is on:
    with `CONSUL_TPU_LOCK_AUDIT` unset they return the plain
    `threading` primitives — no wrapper, no indirection, nothing on the
    hot path.
  * Audit mode (`CONSUL_TPU_LOCK_AUDIT=1`, or `enable_audit()` before
    the audited objects are constructed) swaps in `_TrackedLock` /
    `_TrackedRLock`: per-thread held stacks feed a process-wide
    acquisition-order graph keyed by lock NAME (instances of the same
    class rank equal — see `same_name_nesting` below), observed
    inversions are recorded as cycles (and journaled as
    `runtime.lock.cycle`), acquisition waits and hold times past
    thresholds journal `runtime.lock.contention` /
    `runtime.lock.held_too_long` flight events — always AFTER release,
    never under the audited lock, and always into the process DEFAULT
    recorder so a chaos scenario's scoped deterministic ring stays
    byte-identical across replays.
  * `register_guards(obj, lock, *fields)` — the runtime twin of the
    static `guarded-by` checker: under audit the owning class's
    `__setattr__` is patched once, and every REBIND of a registered
    field (`self._index += 1`, the `buf, self._buf = self._buf, []`
    staging swap) is owner-checked against the guarding lock.  A rebind
    by a thread that does not hold the lock is recorded as a sampled
    race.  In-place container mutation does not route through
    `__setattr__` — the sampler sees the rebind traffic (counters,
    staging swaps, table installs), which is exactly where the
    write-write races of this codebase's idiom live; the static checker
    covers the rest at the source line.

Same-name nesting: one process hosts many instances of the same class
(three RaftNodes in an in-process cluster; a store per DC).  Their
locks share a graph node, so A.lock -> B.lock between two instances
would read as a self-cycle.  Those edges are counted in
`same_name_nesting` and excluded from cycle detection — a deliberate
precision trade documented in README "Race & lock discipline".

Nothing here imports jax; `flight` is imported lazily at emission time
(flight.py itself creates its ring lock through this module).
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from typing import Dict, List, Optional, Tuple

AUDIT_ENV = "CONSUL_TPU_LOCK_AUDIT"

# journaling thresholds (seconds); tests shrink them on the auditor
CONTENTION_S = 0.05
HELD_S = 0.25

_audit = os.environ.get(AUDIT_ENV, "") == "1"
_auditor: Optional["LockAuditor"] = None
_state_lock = threading.Lock()


def audit_enabled() -> bool:
    return _audit


def enable_audit() -> "LockAuditor":
    """Turn audit mode on for locks created FROM NOW ON (existing plain
    locks stay plain — enable before constructing the objects under
    test, or set CONSUL_TPU_LOCK_AUDIT=1 at process start to cover
    module-level singletons like flight's default recorder)."""
    global _audit
    with _state_lock:
        _audit = True
        return _get_auditor()


def disable_audit() -> None:
    global _audit
    with _state_lock:
        _audit = False


def _get_auditor() -> "LockAuditor":
    global _auditor
    if _auditor is None:
        _auditor = LockAuditor()
    return _auditor


def auditor() -> Optional["LockAuditor"]:
    return _auditor


def reset_audit() -> None:
    """Drop the accumulated graph/stats (tests; the audit CLI between
    phases).  Patched classes stay patched — their checks no-op for
    instances registered with the discarded auditor."""
    global _auditor
    with _state_lock:
        _auditor = None


# ----------------------------------------------------------------- factories


def make_lock(name: str):
    """A mutex for production state.  Plain `threading.Lock` unless
    audit mode is on."""
    if _audit:
        return _TrackedLock(name, _get_auditor())
    return threading.Lock()


def make_rlock(name: str):
    if _audit:
        return _TrackedRLock(name, _get_auditor())
    return threading.RLock()


def make_condition(lock=None, name: str = "cond"):
    """`threading.Condition` over an (optionally tracked) lock.  With
    no lock, the condition gets its own — tracked under `name` in
    audit mode."""
    if lock is None:
        lock = make_rlock(name)
    return threading.Condition(lock)


def lock_of(primitive):
    """The lock behind a Condition made by `make_condition` (or the
    primitive itself) — what `register_guards` wants when a class
    synchronizes on a condition rather than a bare lock."""
    return getattr(primitive, "_lock", primitive)


def held_by_me(lock) -> bool:
    """True when the calling thread holds `lock` — only answerable for
    tracked locks; plain locks conservatively report True (the check
    is an audit-mode assertion, never a control-flow input)."""
    if isinstance(lock, (_TrackedLock, _TrackedRLock)):
        return lock.held_by_me()
    return True


# ------------------------------------------------------------------- auditor


class _Held:
    __slots__ = ("lock", "t_acq", "waited", "count")

    def __init__(self, lock, t_acq: float, waited: float):
        self.lock = lock
        self.t_acq = t_acq
        self.waited = waited
        self.count = 1


class LockAuditor:
    """Process-wide acquisition-order graph + contention/hold stats +
    the guarded-field rebind sampler.  Internally synchronized by a
    PLAIN lock (auditing the auditor would recurse)."""

    def __init__(self, contention_s: float = CONTENTION_S,
                 held_s: float = HELD_S):
        self.contention_s = contention_s
        self.held_s = held_s
        self._mu = threading.Lock()
        self._tls = threading.local()
        # name -> name -> count: "held a while acquiring b"
        self.edges: Dict[str, Dict[str, int]] = {}
        self.cycles: List[dict] = []
        self._cycle_keys: set = set()
        self.same_name_nesting: Dict[str, int] = {}
        # name -> {acquisitions, contended, wait_total_s, wait_max_s,
        #          hold_total_s, hold_max_s}
        self.stats: Dict[str, dict] = {}
        self.races: List[dict] = []
        self._race_keys: set = set()
        self.sampled_writes = 0
        # guarded-field registry: id(obj) -> (weakref, lock, fields)
        self._instances: Dict[int, tuple] = {}
        self._class_fields: Dict[type, set] = {}
        self.guarded_fields = 0

    # ------------------------------------------------------------ held stack

    def _held(self) -> List[_Held]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def _emitting(self) -> bool:
        return getattr(self._tls, "emitting", False)

    def find_held(self, lock) -> Optional[_Held]:
        for h in reversed(self._held()):
            if h.lock is lock:
                return h
        return None

    # ------------------------------------------------------- acquire/release

    def note_acquired(self, lock, waited: float) -> None:
        if self._emitting():
            return
        held = self._held()
        name = lock.name
        with self._mu:
            st = self.stats.setdefault(name, {
                "acquisitions": 0, "contended": 0, "wait_total_s": 0.0,
                "wait_max_s": 0.0, "hold_total_s": 0.0,
                "hold_max_s": 0.0})
            st["acquisitions"] += 1
            if waited > 0.0:
                st["contended"] += 1
                st["wait_total_s"] += waited
                st["wait_max_s"] = max(st["wait_max_s"], waited)
            for h in held:
                if h.lock.name == name:
                    self.same_name_nesting[name] = \
                        self.same_name_nesting.get(name, 0) + 1
                    continue
                out = self.edges.setdefault(h.lock.name, {})
                fresh = name not in out
                out[name] = out.get(name, 0) + 1
                if fresh:
                    path = self._path(name, h.lock.name)
                    if path is not None:
                        key = tuple(sorted(path))
                        if key not in self._cycle_keys:
                            self._cycle_keys.add(key)
                            self.cycles.append(
                                {"edge": f"{h.lock.name}->{name}",
                                 "path": path})
                            self._emit("runtime.lock.cycle",
                                       {"edge": "<".join(path)})
        held.append(_Held(lock, time.perf_counter(), waited))

    def note_released(self, lock) -> Optional[_Held]:
        if self._emitting():
            return None
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i].lock is lock:
                h = held[i]
                if h.count > 1:
                    h.count -= 1
                    return None
                del held[i]
                hold = time.perf_counter() - h.t_acq
                with self._mu:
                    st = self.stats.get(lock.name)
                    if st is not None:
                        st["hold_total_s"] += hold
                        st["hold_max_s"] = max(st["hold_max_s"], hold)
                h.t_acq = hold          # reuse the slot: hold time out
                return h
        return None

    def after_release(self, lock, h: _Held) -> None:
        """Threshold journaling — strictly after the lock is free, so
        the journal write never happens under the audited lock."""
        if h.waited > self.contention_s:
            self._emit("runtime.lock.contention",
                       {"lock": lock.name,
                        "ms": round(h.waited * 1000.0, 2)})
        if h.t_acq > self.held_s:       # t_acq holds the hold time now
            self._emit("runtime.lock.held_too_long",
                       {"lock": lock.name,
                        "ms": round(h.t_acq * 1000.0, 2)})

    def _emit(self, name: str, labels: dict) -> None:
        self._tls.emitting = True
        try:
            from consul_tpu import flight
            # the DEFAULT recorder, not the scoped current(): chaos
            # scenarios assert byte-identical scoped rings across
            # seeded replays, and lock timings are wall-clock noise
            flight.default_recorder().emit(name, labels=labels)
        except Exception:
            pass                        # audit must never take the tree down
        finally:
            self._tls.emitting = False

    def _path(self, src: str, dst: str) -> Optional[List[str]]:
        """DFS: a path src -> ... -> dst in the edge graph (the reverse
        path that would close a cycle with the edge just added)."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self.edges.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    # ------------------------------------------------------- guarded fields

    def register_guards(self, obj, lock, fields: Tuple[str, ...]) -> None:
        cls = type(obj)
        with self._mu:
            known = self._class_fields.setdefault(cls, set())
            fresh = set(fields) - known
            known.update(fields)
            self.guarded_fields += len(fresh)
            oid = id(obj)
            rec = self._instances.get(oid)
            # merge: one object may guard field groups under several
            # locks (the publisher's registry vs stats locks)
            fmap = dict(rec[1]) if rec is not None and \
                rec[0]() is obj else {}
            fmap.update({f: lock for f in fields})

            def _gone(_ref, _oid=oid, _self=self):
                _self._instances.pop(_oid, None)

            self._instances[oid] = (weakref.ref(obj, _gone), fmap)
        self._patch_setattr(cls)

    def _patch_setattr(self, cls: type) -> None:
        orig = cls.__setattr__
        if getattr(orig, "_lock_audit_patch", False):
            return
        aud_ref = weakref.ref(self)

        def checked(selfo, attr, value,
                    _orig=orig, _cls=cls, _aud_ref=aud_ref):
            aud = _aud_ref()
            if aud is not None and aud is _auditor:
                fields = aud._class_fields.get(_cls)
                if fields is not None and attr in fields:
                    aud._check_write(selfo, attr)
            _orig(selfo, attr, value)

        checked._lock_audit_patch = True
        cls.__setattr__ = checked

    def _check_write(self, obj, attr: str) -> None:
        rec = self._instances.get(id(obj))
        if rec is None or rec[0]() is not obj:
            return
        lock = rec[1].get(attr)
        if lock is None:
            return
        self.sampled_writes += 1
        if held_by_me(lock):
            return
        key = (type(obj).__name__, attr)
        with self._mu:
            if key in self._race_keys:
                return
            self._race_keys.add(key)
            self.races.append({
                "class": key[0], "field": attr,
                "lock": getattr(lock, "name", "?"),
                "thread": threading.current_thread().name})

    # ------------------------------------------------------------- reporting

    def report(self) -> dict:
        with self._mu:
            edges = [{"from": a, "to": b, "count": n}
                     for a, out in sorted(self.edges.items())
                     for b, n in sorted(out.items())]
            stats = {}
            for name, st in sorted(self.stats.items()):
                n = max(1, st["acquisitions"])
                stats[name] = {
                    "acquisitions": st["acquisitions"],
                    "contended": st["contended"],
                    "wait_max_ms": round(st["wait_max_s"] * 1e3, 3),
                    "wait_mean_ms": round(
                        st["wait_total_s"] / n * 1e3, 4),
                    "hold_max_ms": round(st["hold_max_s"] * 1e3, 3),
                    "hold_mean_ms": round(
                        st["hold_total_s"] / n * 1e3, 4)}
            return {
                "edges": edges,
                "cycles": list(self.cycles),
                "same_name_nesting": dict(self.same_name_nesting),
                "locks": stats,
                "races": list(self.races),
                "sampled_writes": self.sampled_writes,
                "guarded_fields": self.guarded_fields,
                "guarded_instances": len(self._instances),
            }


def audit_report() -> dict:
    """The full report, or a stub when audit never ran."""
    a = _auditor
    if a is None:
        return {"enabled": False}
    out = a.report()
    out["enabled"] = True
    return out


def audit_summary() -> dict:
    """The one-paragraph artifact stamp (soak/chaos reports)."""
    a = _auditor
    if a is None:
        return {"enabled": False}
    r = a.report()
    return {"enabled": True, "locks": len(r["locks"]),
            "edges": len(r["edges"]), "cycles": len(r["cycles"]),
            "races": len(r["races"]),
            "sampled_writes": r["sampled_writes"],
            "guarded_fields": r["guarded_fields"]}


def check_clean() -> List[str]:
    """Violations the audit observed — the list a gate fails on."""
    a = _auditor
    if a is None:
        return []
    out = []
    for c in a.cycles:
        out.append(f"lock-order cycle observed at runtime: "
                   f"{'<'.join(c['path'])} (closing edge {c['edge']})")
    for r in a.races:
        out.append(f"unlocked write to guarded field "
                   f"{r['class']}.{r['field']} (guarded by "
                   f"{r['lock']}) on thread {r['thread']}")
    return out


def register_guards(obj, lock, *fields: str) -> None:
    """Declare `fields` of `obj` guarded by `lock` for the runtime
    sampler.  No-op (one boolean test) unless audit mode is on — call
    it at the end of __init__, after the fields exist."""
    if not _audit:
        return
    if isinstance(lock, (_TrackedLock, _TrackedRLock)):
        _get_auditor().register_guards(obj, lock, fields)


# ------------------------------------------------------------ tracked locks


class _TrackedLock:
    """A named, audited mutex.  API-compatible with threading.Lock for
    every use in this tree (with-statement, Condition backing,
    non-blocking acquire)."""

    __slots__ = ("name", "_inner", "_aud")

    def __init__(self, name: str, aud: LockAuditor):
        self.name = name
        self._inner = threading.Lock()
        self._aud = aud

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(False)
        waited = 0.0
        if not got:
            if not blocking:
                return False
            t0 = time.perf_counter()
            got = self._inner.acquire(True, timeout)
            waited = time.perf_counter() - t0
            if not got:
                return False
        self._aud.note_acquired(self, waited)
        return True

    def release(self) -> None:
        h = self._aud.note_released(self)
        self._inner.release()
        if h is not None:
            self._aud.after_release(self, h)

    def locked(self) -> bool:
        return self._inner.locked()

    def held_by_me(self) -> bool:
        return self._aud.find_held(self) is not None

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class _TrackedRLock:
    """Audited re-entrant lock; implements the `_release_save` /
    `_acquire_restore` / `_is_owned` protocol so threading.Condition
    fully releases recursion across wait()."""

    __slots__ = ("name", "_inner", "_aud")

    def __init__(self, name: str, aud: LockAuditor):
        self.name = name
        self._inner = threading.RLock()
        self._aud = aud

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        held = self._aud.find_held(self)
        if held is not None:
            if not self._inner.acquire(blocking, timeout):
                return False
            held.count += 1
            return True
        got = self._inner.acquire(False)
        waited = 0.0
        if not got:
            if not blocking:
                return False
            t0 = time.perf_counter()
            got = self._inner.acquire(True, timeout)
            waited = time.perf_counter() - t0
            if not got:
                return False
        self._aud.note_acquired(self, waited)
        return True

    def release(self) -> None:
        h = self._aud.note_released(self)
        self._inner.release()
        if h is not None:
            self._aud.after_release(self, h)

    def held_by_me(self) -> bool:
        return self._aud.find_held(self) is not None

    # Condition protocol: full-depth release around wait()
    def _release_save(self):
        h = self._aud.find_held(self)
        if h is not None:
            h.count = 1                 # collapse recursion, then pop
            self._aud.note_released(self)
        return self._inner._release_save()

    def _acquire_restore(self, state):
        self._inner._acquire_restore(state)
        self._aud.note_acquired(self, 0.0)

    def _is_owned(self):
        return self._inner._is_owned()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False
