"""xDS resource generation: ConfigSnapshot → Envoy-shaped config.

The reference's xDS server (agent/xds/server.go:186, delta.go:33) speaks
gRPC ADS to Envoy, generating Clusters, ClusterLoadAssignments,
Listeners, and Routes (+ RBAC filters from intentions) per proxy
snapshot.  This framework generates the same resource set as plain JSON
dicts in Envoy's v3 field shapes and serves them over HTTP long-poll
(GET /v1/agent/xds/<proxy_id>?version=&wait=) — a deliberate divergence:
the control-plane protocol is JSON/HTTP instead of protobuf/gRPC, but
the resource content and update semantics (version-gated delta polls)
mirror the reference.
"""

from __future__ import annotations

import re
from typing import Dict, List

from consul_tpu.connect import intentions as imod


def _principal_regex(source: str) -> str:
    """SPIFFE principal matcher for an intention source: literal parts
    regex-escaped, only the intention wildcard maps to `.*` — a dotted
    service name must not match arbitrary characters."""
    escaped = ".*".join(re.escape(p) for p in source.split("*"))
    return (r"spiffe://[^/]+/ns/[^/]+/dc/[^/]+/svc/" + escaped)


def clusters(snap) -> List[dict]:
    """CDS: one cluster per upstream + the local app cluster
    (agent/xds/clusters.go)."""
    out = [{
        "@type": "envoy.config.cluster.v3.Cluster",
        "name": "local_app",
        "type": "STATIC",
        "connect_timeout": "5s",
    }]
    for up in snap.upstreams:
        name = up.get("destination_name", "")
        out.append({
            "@type": "envoy.config.cluster.v3.Cluster",
            "name": name,
            "type": "EDS",
            "connect_timeout": "5s",
            "transport_socket": {
                "name": "tls",
                "sni": f"{name}.default.{_trust_domain(snap)}",
                "common_tls_context": {
                    "tls_certificates": [{
                        "certificate_chain": snap.leaf["CertPEM"],
                        "private_key": snap.leaf["PrivateKeyPEM"]}],
                    "validation_context": {
                        "trusted_ca": "".join(
                            r["RootCert"] for r in snap.roots)},
                },
            },
        })
    return out


def endpoints(snap) -> List[dict]:
    """EDS: ClusterLoadAssignment per upstream
    (agent/xds/endpoints.go)."""
    out = []
    for name, eps in snap.upstream_endpoints.items():
        out.append({
            "@type": "envoy.config.endpoint.v3.ClusterLoadAssignment",
            "cluster_name": name,
            "endpoints": [{
                "lb_endpoints": [{
                    "endpoint": {"address": {"socket_address": {
                        "address": e["address"] or "127.0.0.1",
                        "port_value": e["port"]}}}}
                    for e in eps]}],
        })
    return out


def listeners(snap) -> List[dict]:
    """LDS: the public (inbound, mTLS + RBAC from intentions) listener and
    one outbound listener per upstream (agent/xds/listeners.go)."""
    rules = []
    for it in snap.intentions:
        principal = {"authenticated": {"principal_name": {
            "safe_regex": {"regex": _principal_regex(it["source"])}}}}
        rules.append({"action": it["action"].upper(),
                      "precedence": it["precedence"],
                      "principals": [principal]})
    public = {
        "@type": "envoy.config.listener.v3.Listener",
        "name": "public_listener",
        "traffic_direction": "INBOUND",
        "filter_chains": [{
            "transport_socket": {
                "name": "tls",
                "require_client_certificate": True,
                "common_tls_context": {
                    "tls_certificates": [{
                        "certificate_chain": snap.leaf["CertPEM"],
                        "private_key": snap.leaf["PrivateKeyPEM"]}],
                    "validation_context": {
                        "trusted_ca": "".join(
                            r["RootCert"] for r in snap.roots)},
                },
            },
            "filters": [
                {"name": "envoy.filters.network.rbac",
                 "rules": rules,
                 "default_action": "ALLOW" if snap.default_allow
                 else "DENY"},
                {"name": "envoy.filters.network.tcp_proxy",
                 "cluster": "local_app"},
            ],
        }],
    }
    out = [public]
    for up in snap.upstreams:
        name = up.get("destination_name", "")
        out.append({
            "@type": "envoy.config.listener.v3.Listener",
            "name": f"{name}:{up.get('local_bind_port', 0)}",
            "traffic_direction": "OUTBOUND",
            "address": {"socket_address": {
                "address": up.get("local_bind_address", "127.0.0.1"),
                "port_value": up.get("local_bind_port", 0)}},
            "filter_chains": [{"filters": [
                {"name": "envoy.filters.network.tcp_proxy",
                 "cluster": name}]}],
        })
    return out


def routes(snap) -> List[dict]:
    """RDS: trivial catch-all route to the local app (the L4 default;
    discovery-chain L7 routing layers on top in the reference)."""
    return [{
        "@type": "envoy.config.route.v3.RouteConfiguration",
        "name": "public_route",
        "virtual_hosts": [{"name": "default", "domains": ["*"],
                           "routes": [{"match": {"prefix": "/"},
                                       "route": {"cluster":
                                                 "local_app"}}]}],
    }]


def _trust_domain(snap) -> str:
    uri = snap.leaf.get("ServiceURI", "")
    if uri.startswith("spiffe://"):
        return uri[len("spiffe://"):].split("/")[0]
    return "consul"


# ---------------------------------------------------------------------------
# gateway resource generation (agent/xds listeners/clusters per kind:
# makeMeshGatewayListener, makeTerminatingGatewayListener,
# makeIngressGatewayListeners)
# ---------------------------------------------------------------------------

def _eds_cluster(name: str, eps: List[dict]) -> List[dict]:
    return [
        {"@type": "envoy.config.cluster.v3.Cluster", "name": name,
         "type": "EDS", "connect_timeout": "5s"},
        {"@type": "envoy.config.endpoint.v3.ClusterLoadAssignment",
         "cluster_name": name,
         "endpoints": [{"lb_endpoints": [
             {"endpoint": {"address": {"socket_address": {
                 "address": e["address"] or "127.0.0.1",
                 "port_value": e["port"]}}}} for e in eps]}]},
    ]


def mesh_gateway_resources(snap) -> dict:
    """SNI-routed L4 gateway: local services by their mesh SNI, remote
    DCs by a wildcard `*.<dc>` SNI toward that DC's gateways (the
    reference's mesh-gateway listener + cluster-per-dc shape)."""
    td = _trust_domain(snap)
    cl, eds, chains = [], [], []
    for svc, eps in sorted(snap.mesh_endpoints.items()):
        cname = f"local.{svc}"
        c, e = _eds_cluster(cname, eps)
        cl.append(c)
        eds.append(e)
        chains.append({
            "filter_chain_match": {
                "server_names": [f"{svc}.default.{td}"]},
            "filters": [{"name": "envoy.filters.network.sni_cluster"},
                        {"name": "envoy.filters.network.tcp_proxy",
                         "cluster": cname}],
        })
    for fed in snap.federation_states:
        dc = fed["datacenter"]
        cname = f"dc.{dc}"
        gw_eps = [{"address": g.get("address", ""),
                   "port": g.get("port", 0)}
                  for g in fed.get("mesh_gateways", [])]
        c, e = _eds_cluster(cname, gw_eps)
        cl.append(c)
        eds.append(e)
        chains.append({
            "filter_chain_match": {"server_names": [f"*.{dc}"]},
            "filters": [{"name": "envoy.filters.network.sni_cluster"},
                        {"name": "envoy.filters.network.tcp_proxy",
                         "cluster": cname}],
        })
    listener = {
        "@type": "envoy.config.listener.v3.Listener",
        "name": "mesh_gateway",
        "traffic_direction": "UNSPECIFIED",
        "listener_filters": [
            {"name": "envoy.filters.listener.tls_inspector"}],
        "filter_chains": chains,
    }
    return {"clusters": cl, "endpoints": eds, "listeners": [listener],
            "routes": []}


def terminating_gateway_resources(snap) -> dict:
    """TLS-terminating gateway: one SNI filter chain per bound service,
    presenting that service's mesh leaf inward and proxying to the
    real (non-mesh) instances, with per-service RBAC from intentions."""
    cl, eds, chains = [], [], []
    td = _trust_domain(snap)
    for row in snap.gateway_services:
        svc = row["Service"]
        cname = f"term.{svc}"
        c, e = _eds_cluster(cname, snap.upstream_endpoints.get(svc, []))
        cl.append(c)
        eds.append(e)
        leaf = snap.service_leaves.get(svc) or snap.leaf
        rules = [{"action": it["action"].upper(),
                  "precedence": it["precedence"],
                  "principals": [{"authenticated": {"principal_name": {
                      "safe_regex": {"regex":
                                     _principal_regex(it["source"])}}}}]}
                 for it in snap.intentions
                 if it["destination"] in (svc, "*")]
        chains.append({
            "filter_chain_match": {
                "server_names": [f"{svc}.default.{td}"]},
            "transport_socket": {
                "name": "tls", "require_client_certificate": True,
                "common_tls_context": {
                    "tls_certificates": [{
                        "certificate_chain": leaf["CertPEM"],
                        "private_key": leaf["PrivateKeyPEM"]}],
                    "validation_context": {"trusted_ca": "".join(
                        r["RootCert"] for r in snap.roots)}},
            },
            "filters": [
                {"name": "envoy.filters.network.rbac", "rules": rules,
                 "default_action": "ALLOW" if snap.default_allow
                 else "DENY"},
                {"name": "envoy.filters.network.tcp_proxy",
                 "cluster": cname}],
        })
    listener = {
        "@type": "envoy.config.listener.v3.Listener",
        "name": "terminating_gateway",
        "traffic_direction": "INBOUND",
        "listener_filters": [
            {"name": "envoy.filters.listener.tls_inspector"}],
        "filter_chains": chains,
    }
    return {"clusters": cl, "endpoints": eds, "listeners": [listener],
            "routes": []}


def ingress_gateway_resources(snap) -> dict:
    """North-south entry: one listener per configured port; http
    listeners route by host to bound-service clusters, tcp listeners
    proxy straight through (makeIngressGatewayListeners).

    Listeners are built from the RESOLVED gateway_services rows (not
    the raw config) so a wildcard binding expands to real per-service
    routes/clusters instead of a nonexistent `ingress.*` target."""
    cl, eds, lst, rts = [], [], [], []
    seen = set()
    by_port: Dict[int, List[dict]] = {}
    for row in snap.gateway_services:
        svc = row["Service"]
        by_port.setdefault(row.get("Port", 0), []).append(row)
        if svc in seen:
            continue
        seen.add(svc)
        c, e = _eds_cluster(f"ingress.{svc}",
                            snap.upstream_endpoints.get(svc, []))
        cl.append(c)
        eds.append(e)
    for li in snap.listeners:
        port = li.get("port", 0)
        proto = li.get("protocol", "tcp")
        rows = by_port.get(port, [])
        name = f"ingress:{port}"
        if proto == "tcp":
            # tcp carries no routing discriminator: exactly one bound
            # service is servable (the reference validates this at the
            # config entry); zero services → no listener to emit
            if not rows:
                continue
            lst.append({
                "@type": "envoy.config.listener.v3.Listener",
                "name": name, "traffic_direction": "OUTBOUND",
                "address": {"socket_address": {
                    "address": "0.0.0.0", "port_value": port}},
                "filter_chains": [{"filters": [
                    {"name": "envoy.filters.network.tcp_proxy",
                     "cluster": f"ingress.{rows[0]['Service']}"}]}],
            })
        else:
            vhosts = []
            for row in rows:
                svc = row["Service"]
                domains = row.get("Hosts") or [f"{svc}.ingress.*", svc]
                vhosts.append({
                    "name": svc, "domains": domains,
                    "routes": [{"match": {"prefix": "/"},
                                "route": {"cluster":
                                          f"ingress.{svc}"}}]})
            rts.append({
                "@type": "envoy.config.route.v3.RouteConfiguration",
                "name": name, "virtual_hosts": vhosts})
            lst.append({
                "@type": "envoy.config.listener.v3.Listener",
                "name": name, "traffic_direction": "OUTBOUND",
                "address": {"socket_address": {
                    "address": "0.0.0.0", "port_value": port}},
                "filter_chains": [{"filters": [
                    {"name":
                     "envoy.filters.network.http_connection_manager",
                     "rds_route_config_name": name}]}],
            })
    return {"clusters": cl, "endpoints": eds, "listeners": lst,
            "routes": rts}


# resource identity per type (delta.go tracks resources by name so an
# update ships only what changed)
_DELTA_KEYS = {"clusters": "name", "endpoints": "cluster_name",
               "listeners": "name", "routes": "name"}


def delta(prev_resources: dict, new_resources: dict) -> dict:
    """Per-resource diff between two payload versions
    (DeltaAggregatedResources semantics: changed resources in full,
    removed resources by name)."""
    changed, removed = {}, {}
    for rtype, keyf in _DELTA_KEYS.items():
        old = {r[keyf]: r for r in prev_resources.get(rtype, [])}
        new = {r[keyf]: r for r in new_resources.get(rtype, [])}
        ch = [r for k, r in new.items()
              if k not in old or old[k] != r]
        rm = sorted(k for k in old if k not in new)
        if ch:
            changed[rtype] = ch
        if rm:
            removed[rtype] = rm
    return {"Changed": changed, "Removed": removed}


def snapshot_resources(snap) -> dict:
    """Full ADS payload for one proxy version (DeltaAggregatedResources
    response analogue); gateway kinds get their own resource shapes."""
    kind = getattr(snap, "kind", "connect-proxy")
    if kind == "mesh-gateway":
        res = mesh_gateway_resources(snap)
    elif kind == "terminating-gateway":
        res = terminating_gateway_resources(snap)
    elif kind == "ingress-gateway":
        res = ingress_gateway_resources(snap)
    else:
        res = {
            "clusters": clusters(snap),
            "endpoints": endpoints(snap),
            "listeners": listeners(snap),
            "routes": routes(snap),
        }
    return {
        "VersionInfo": str(snap.version),
        "ProxyID": snap.proxy_id,
        "Service": snap.service,
        "Kind": kind,
        "Resources": res,
    }
