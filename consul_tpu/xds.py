"""xDS resource generation: ConfigSnapshot → Envoy v3 config.

The reference's xDS server (agent/xds/server.go:186, delta.go:33) speaks
gRPC ADS to Envoy, generating Clusters, ClusterLoadAssignments,
Listeners, and Routes (+ RBAC filters from intentions) per proxy
snapshot (agent/xds/clusters.go, endpoints.go, listeners.go, routes.go,
rbac.go).

This module generates the same resource set as JSON dicts in STRICT
Envoy v3 shapes — every nested extension rides in a `typed_config`
google.protobuf.Any with its canonical `@type`, certificates ride in
core.v3.DataSource, and intentions compile to config.rbac.v3 policies —
so each resource parses losslessly into the protobuf messages under
consul_tpu/xdsproto (see xds_pb.from_dict).  Two frontends serve them:

  * consul_tpu/xds_grpc.py — real gRPC ADS (StreamAggregatedResources /
    DeltaAggregatedResources), protobuf on the wire: what a stock Envoy
    consumes.
  * GET /v1/agent/xds/<proxy_id> — the same resources as JSON over HTTP
    long-poll, kept for debuggability and the CLI.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional

from consul_tpu import discoverychain as dchain
from consul_tpu.connect import intentions as imod
from consul_tpu.connect import l7
from consul_tpu.servicemgr import expose_paths_by_port

T = "type.googleapis.com/"

# default public listener port when the proxy registration carries none
# (the reference registers sidecars at 21000+; connect proxy config
# sidecar_service defaults)
DEFAULT_PUBLIC_PORT = 20000


def _principal_regex(source: str) -> str:
    """SPIFFE principal matcher for an intention source: literal parts
    regex-escaped, only the intention wildcard maps to `.*` — a dotted
    service name must not match arbitrary characters."""
    escaped = ".*".join(re.escape(p) for p in source.split("*"))
    return (r"spiffe://[^/]+/ns/[^/]+/dc/[^/]+/svc/" + escaped)


def _principal(source: str) -> dict:
    return {"authenticated": {"principal_name": {
        "safe_regex": {"regex": _principal_regex(source)}}}}


def _duration(seconds: float) -> str:
    return f"{seconds:g}s"


def _data_source(pem: str) -> dict:
    return {"inline_string": pem}


def _common_tls_context(leaf: dict, roots: List[dict]) -> dict:
    return {
        "tls_certificates": [{
            "certificate_chain": _data_source(leaf["CertPEM"]),
            "private_key": _data_source(leaf["PrivateKeyPEM"])}],
        "validation_context": {
            "trusted_ca": _data_source(
                "".join(r["RootCert"] for r in roots))},
    }


def _upstream_tls(leaf: dict, roots: List[dict], sni: str) -> dict:
    return {"name": "tls", "typed_config": {
        "@type": T + "envoy.extensions.transport_sockets.tls.v3."
                     "UpstreamTlsContext",
        "sni": sni,
        "common_tls_context": _common_tls_context(leaf, roots)}}


def _downstream_tls(leaf: dict, roots: List[dict]) -> dict:
    return {"name": "tls", "typed_config": {
        "@type": T + "envoy.extensions.transport_sockets.tls.v3."
                     "DownstreamTlsContext",
        "require_client_certificate": True,
        "common_tls_context": _common_tls_context(leaf, roots)}}


def _tcp_proxy(stat_prefix: str, cluster: str) -> dict:
    return {"name": "envoy.filters.network.tcp_proxy", "typed_config": {
        "@type": T + "envoy.extensions.filters.network.tcp_proxy.v3."
                     "TcpProxy",
        "stat_prefix": stat_prefix, "cluster": cluster}}


def _sni_cluster() -> dict:
    return {"name": "envoy.filters.network.sni_cluster", "typed_config": {
        "@type": T + "envoy.extensions.filters.network.sni_cluster.v3."
                     "SniCluster"}}


def _tls_inspector() -> dict:
    return {"name": "envoy.filters.listener.tls_inspector",
            "typed_config": {
                "@type": T + "envoy.extensions.filters.listener."
                             "tls_inspector.v3.TlsInspector"}}


def _address(host: str, port: int) -> dict:
    return {"socket_address": {"address": host, "port_value": port}}


def _ads_config_source() -> dict:
    return {"ads": {}, "resource_api_version": "V3"}


def rbac_rules(intentions: List[dict], default_allow: bool) -> dict:
    """Compile L4 intentions into one config.rbac.v3.RBAC message
    (agent/xds/rbac.go makeRBACRules).

    Envoy RBAC has a single action, so mixed allow/deny intention sets
    flatten the way the reference does: with default-deny the filter
    ALLOWs each allow-intention source minus any higher-precedence deny
    that also matches (not_id exclusion); with default-allow the filter
    DENYs each deny source minus higher-precedence allows.  Policy keys
    are precedence-ordered `consul-intentions-layer4-<n>` so the
    compiled order stays inspectable."""
    want = "deny" if default_allow else "allow"
    ordered = sorted(intentions, key=lambda it: -it["precedence"])
    policies = {}
    n = 0
    for i, it in enumerate(ordered):
        if it["action"] != want:
            continue
        principal = _principal(it["source"])
        # higher-precedence intentions of the OPPOSITE action punch
        # holes in this policy
        excl = [_principal(o["source"]) for o in ordered[:i]
                if o["action"] != want]
        if excl:
            notp = excl[0] if len(excl) == 1 else \
                {"or_ids": {"ids": excl}}
            principal = {"and_ids": {"ids": [
                principal, {"not_id": notp}]}}
        policies[f"consul-intentions-layer4-{n}"] = {
            "permissions": [{"any": True}],
            "principals": [principal]}
        n += 1
    return {"action": "ALLOW" if want == "allow" else "DENY",
            "policies": policies}


def _rbac_filter(intentions: List[dict], default_allow: bool,
                 stat_prefix: str = "connect_authz") -> dict:
    return {"name": "envoy.filters.network.rbac", "typed_config": {
        "@type": T + "envoy.extensions.filters.network.rbac.v3.RBAC",
        "stat_prefix": stat_prefix,
        "rules": rbac_rules(intentions, default_allow)}}


def _http_connection_manager(stat_prefix: str,
                             route_config_name: str) -> dict:
    return {"name": "envoy.filters.network.http_connection_manager",
            "typed_config": {
                "@type": T + "envoy.extensions.filters.network."
                             "http_connection_manager.v3."
                             "HttpConnectionManager",
                "stat_prefix": stat_prefix,
                "rds": {"config_source": _ads_config_source(),
                        "route_config_name": route_config_name},
                "http_filters": [{
                    "name": "envoy.filters.http.router",
                    "typed_config": {
                        "@type": T + "envoy.extensions.filters.http."
                                     "router.v3.Router"}}]}}


def _load_assignment(name: str, eps: List[dict]) -> dict:
    return {
        "cluster_name": name,
        "endpoints": [{"lb_endpoints": [
            {"endpoint": {"address": _address(
                e["address"] or "127.0.0.1", e["port"])}}
            for e in eps]}],
    }


def chain_cluster_name(target_id: str, trust_domain: str) -> str:
    """Per-target cluster name in the reference's SNI form
    `<service>.<subset/ns>.<dc>.internal.<trust-domain>`
    (connect.ServiceSNI via agent/xds/clusters.go:309)."""
    return f"{target_id}.internal.{trust_domain}"


def _upstream_chain(snap, name: str) -> Optional[dict]:
    """The upstream's compiled chain, or None when it is absent or the
    implicit default (default chains keep the plain one-cluster shape,
    routesForConnectProxy's chain.IsDefault() skip)."""
    chain = getattr(snap, "chains", {}).get(name)
    if chain is None or dchain.is_default_chain(chain):
        return None
    return chain


def _upstream_filters(snap, name: str, td: str) -> List[dict]:
    """Network filters for one upstream — shared by the explicit-bind
    outbound listeners and the transparent-proxy filter chains so the
    two can never diverge (listeners.go makeUpstreamListener)."""
    chain = _upstream_chain(snap, name)
    if chain is not None and chain.get("Protocol") in (
            "http", "http2", "grpc"):
        # L7 chain: HTTP connection manager + RDS route named for
        # the upstream (listeners.go makeListener w/ chain)
        return [_http_connection_manager(f"upstream.{name}", name)]
    if chain is not None:
        # tcp chain with a redirect/failover: tcp_proxy straight to
        # the start resolver's target cluster
        start = l7._resolve_to_resolver(chain, chain["StartNode"])
        cname = chain_cluster_name(start["Target"], td) \
            if start and start.get("Target") else name
        return [_tcp_proxy(f"upstream.{name}", cname)]
    return [_tcp_proxy(f"upstream.{name}", name)]


def _chain_resolver_nodes(chain: dict) -> List[dict]:
    return [n for n in chain["Nodes"].values()
            if n.get("Type") == "resolver" and n.get("Target")]


def _escape_from_cfg(cfg: dict, key: str,
                     type_name: str) -> Optional[dict]:
    """Resource override ("escape hatch", agent/xds/config.go): the
    operator supplies a COMPLETE resource as a JSON string in an
    opaque config map; it replaces the generated resource wholesale,
    like the reference's makeListenerFromUserConfig
    (agent/xds/listeners.go:629).

    Malformed JSON raises — the reference fails xDS generation for the
    proxy rather than silently shipping the generated resource the
    operator asked to replace."""
    import json as _json
    raw = (cfg or {}).get(key)
    if not raw:
        return None
    if isinstance(raw, dict):
        res = dict(raw)       # already-parsed map form is accepted
    else:
        try:
            res = _json.loads(raw)
        except (TypeError, ValueError) as e:
            raise ValueError(f"invalid {key}: {e}") from None
        if not isinstance(res, dict):
            raise ValueError(f"invalid {key}: expected an object")
    res.setdefault("@type", T + type_name)
    return res


def _escape_hatch(snap, key: str, type_name: str) -> Optional[dict]:
    """Per-PROXY hatch (envoy_public_listener_json /
    envoy_local_cluster_json in Proxy.Config)."""
    return _escape_from_cfg(getattr(snap, "opaque_config", None) or {},
                            key, type_name)


def _upstream_escape(up: dict, key: str,
                     type_name: str) -> Optional[dict]:
    """Per-UPSTREAM hatch (envoy_listener_json / envoy_cluster_json in
    the upstream's opaque Config — consumed at listeners.go:102 /
    clusters.go makeClusterFromUserConfig)."""
    return _escape_from_cfg(up.get("config") or {}, key, type_name)


def clusters(snap) -> List[dict]:
    """CDS: one cluster per upstream + the local app cluster
    (agent/xds/clusters.go makeUpstreamCluster/makeAppCluster).
    Upstreams with a non-default discovery chain expand to one EDS
    cluster per chain RESOLVER target
    (makeUpstreamClustersForDiscoveryChain)."""
    td = _trust_domain(snap)
    local_app = {
        "@type": T + "envoy.config.cluster.v3.Cluster",
        "name": "local_app",
        "type": "STATIC",
        "connect_timeout": _duration(5),
        "load_assignment": _load_assignment("local_app", [
            {"address": "127.0.0.1",
             "port": getattr(snap, "local_port", 0) or 0}]),
    }
    override = _escape_hatch(snap, "envoy_local_cluster_json",
                             "envoy.config.cluster.v3.Cluster")
    out = [override if override is not None else local_app]
    # expose-path clusters: plaintext STATIC clusters to the app's
    # exposed ports (one per distinct local_path_port)
    expose_lpps = sorted({
        lpp for paths in expose_paths_by_port(
            getattr(snap, "expose", None)).values()
        for lpp in paths.values()})
    for lpp in expose_lpps:
        out.append({
            "@type": T + "envoy.config.cluster.v3.Cluster",
            "name": f"exposed_cluster_{lpp}",
            "type": "STATIC",
            "connect_timeout": _duration(5),
            "load_assignment": _load_assignment(
                f"exposed_cluster_{lpp}",
                [{"address": "127.0.0.1", "port": lpp}]),
        })
    # transparent mode: the original-destination passthrough cluster
    if getattr(snap, "mode", "") == "transparent":
        out.append({
            "@type": T + "envoy.config.cluster.v3.Cluster",
            "name": "original-destination",
            "type": "ORIGINAL_DST",
            "lb_policy": "CLUSTER_PROVIDED",
            "connect_timeout": _duration(5),
        })
    emitted = {}        # cluster name -> index in `out`: two chains
    #                     sharing a target must not emit a duplicate
    #                     name (envoy NACKs the push)
    overridden = set()  # names whose emitted resource came from an
    #                     operator override (an override beats a
    #                     generated cluster; first override wins)
    default_generated = set()   # names emitted by the DEFAULT-chain
    #                     generated branch — the only ones an override
    #                     may replace (a non-default chain's clusters
    #                     always win, clusters.go chain.IsDefault)
    for up in snap.upstreams:
        name = up.get("destination_name", "")
        chain = _upstream_chain(snap, name)
        if chain is None:
            # the cluster hatch only applies on the DEFAULT chain —
            # with a real discovery chain the generated per-target
            # clusters win (clusters.go: EnvoyClusterJSON is honored
            # iff chain.IsDefault).  Dedup on the name the resource
            # actually DECLARES: two clusters sharing a name would
            # NACK the whole push — but an operator override must be
            # checked BEFORE the dedup set: when an earlier upstream
            # already emitted the generated cluster under the same
            # name, the override REPLACES it instead of being
            # silently dropped (ADVICE r5).
            override = _upstream_escape(
                up, "envoy_cluster_json",
                "envoy.config.cluster.v3.Cluster")
            cname_out = override.get("name", name) \
                if override is not None else name
            prev = emitted.get(cname_out)
            if override is not None:
                if prev is not None:
                    # replace ONLY a default-branch generated cluster;
                    # a name owned by a discovery-chain cluster (or an
                    # earlier override) keeps it — operator JSON on a
                    # default chain must never hijack another
                    # upstream's chain output
                    if cname_out in default_generated:
                        out[prev] = override
                        overridden.add(cname_out)
                        default_generated.discard(cname_out)
                    continue
                emitted[cname_out] = len(out)
                overridden.add(cname_out)
                out.append(override)
                continue
            if prev is not None:
                continue
            emitted[cname_out] = len(out)
            default_generated.add(cname_out)
            out.append({
                "@type": T + "envoy.config.cluster.v3.Cluster",
                "name": name,
                "type": "EDS",
                "eds_cluster_config": {
                    "eds_config": _ads_config_source(),
                    "service_name": name},
                "connect_timeout": _duration(5),
                "transport_socket": _upstream_tls(
                    snap.leaf, snap.roots, f"{name}.default.{td}"),
            })
            continue
        for node in _chain_resolver_nodes(chain):
            tid = node["Target"]
            cname = chain_cluster_name(tid, td)
            prev = emitted.get(cname)
            if prev is not None and cname not in overridden \
                    and cname not in default_generated:
                continue   # another chain already owns the name
            svc = chain["Targets"][tid]["Service"]
            cluster = {
                "@type": T + "envoy.config.cluster.v3.Cluster",
                "name": cname,
                "type": "EDS",
                "eds_cluster_config": {
                    "eds_config": _ads_config_source(),
                    "service_name": cname},
                "connect_timeout": _duration(
                    l7._parse_duration(
                        node.get("ConnectTimeout")) or 5),
                "transport_socket": _upstream_tls(
                    snap.leaf, snap.roots, f"{svc}.default.{td}"),
            }
            _inject_lb_to_cluster(node.get("LoadBalancer"), cluster)
            if prev is not None:
                # a chain cluster always wins its name back from an
                # operator override or a default-branch generated
                # cluster that claimed it EARLIER in the upstream list
                # (clusters.go: EnvoyClusterJSON is honored only iff
                # chain.IsDefault — ordering must not change that)
                out[prev] = cluster
                overridden.discard(cname)
                default_generated.discard(cname)
            else:
                emitted[cname] = len(out)
                out.append(cluster)
    return out


_LB_POLICIES = {"": None, "round_robin": "ROUND_ROBIN",
                "least_request": "LEAST_REQUEST",
                "ring_hash": "RING_HASH", "random": "RANDOM",
                "maglev": "MAGLEV"}


def _inject_lb_to_cluster(lb: Optional[dict], cluster: dict) -> None:
    """Resolver LoadBalancer → envoy cluster lb_policy + per-policy
    config (agent/xds/clusters.go injectLBToCluster)."""
    if not lb:
        return
    policy = _LB_POLICIES.get(str(lb.get("policy", "")).lower())
    if policy is None:
        return
    cluster["lb_policy"] = policy
    if policy == "RING_HASH":
        rh = lb.get("ring_hash_config") or {}
        cfg = {}
        if rh.get("minimum_ring_size"):
            cfg["minimum_ring_size"] = int(rh["minimum_ring_size"])
        if rh.get("maximum_ring_size"):
            cfg["maximum_ring_size"] = int(rh["maximum_ring_size"])
        if cfg:
            cluster["ring_hash_lb_config"] = cfg
    elif policy == "LEAST_REQUEST":
        lr = lb.get("least_request_config") or {}
        if lr.get("choice_count"):
            cluster["least_request_lb_config"] = {
                "choice_count": int(lr["choice_count"])}


def _inject_lb_to_route_action(lb: Optional[dict],
                               action: dict) -> None:
    """Hash policies for hash-based LB → RouteAction.hash_policy
    (agent/xds/routes.go injectLBToRouteAction — which only injects
    for ring_hash/maglev; other policies never emit hash_policy)."""
    if not lb or str(lb.get("policy", "")).lower() not in (
            "ring_hash", "maglev"):
        return
    policies = []
    for hp in lb.get("hash_policies") or []:
        if hp.get("source_ip"):
            pol: dict = {"connection_properties": {"source_ip": True}}
        else:
            field = str(hp.get("field", "")).lower()
            value = hp.get("field_value", "")
            if field == "header":
                pol = {"header": {"header_name": value}}
            elif field == "cookie":
                ck = hp.get("cookie_config") or {}
                cookie = {"name": value}
                if ck.get("ttl"):
                    cookie["ttl"] = _duration(
                        l7._parse_duration(ck["ttl"]))
                if ck.get("path"):
                    cookie["path"] = ck["path"]
                pol = {"cookie": cookie}
            elif field == "query_parameter":
                pol = {"query_parameter": {"name": value}}
            else:
                continue
        if hp.get("terminal"):
            pol["terminal"] = True
        policies.append(pol)
    if policies:
        action["hash_policy"] = policies


def endpoints(snap) -> List[dict]:
    """EDS: ClusterLoadAssignment per upstream
    (agent/xds/endpoints.go).  Chain targets get their own assignment;
    a resolver's failover targets ride the PRIMARY cluster's
    assignment as priority>0 locality groups, envoy's native failover
    order (endpoints.go makeLoadAssignment endpointGroups)."""
    td = _trust_domain(snap)
    out = []
    chain_names = set()
    emitted = set()     # dedupe shared targets across upstream chains
    for up in snap.upstreams:
        chain = _upstream_chain(snap, up.get("destination_name", ""))
        if chain is None:
            continue
        chain_names.add(up.get("destination_name", ""))
        ceps = getattr(snap, "chain_endpoints", {})
        for node in _chain_resolver_nodes(chain):
            tid = node["Target"]
            if tid in emitted:
                continue
            emitted.add(tid)
            groups = [{"priority": 0, "lb_endpoints": [
                {"endpoint": {"address": _address(
                    e["address"] or "127.0.0.1", e["port"])}}
                for e in ceps.get(tid, [])]}]
            fo = node.get("Failover") or {}
            for i, ftid in enumerate(fo.get("Targets") or []):
                groups.append({"priority": i + 1, "lb_endpoints": [
                    {"endpoint": {"address": _address(
                        e["address"] or "127.0.0.1", e["port"])}}
                    for e in ceps.get(ftid, [])]})
            out.append({
                "@type": T + "envoy.config.endpoint.v3."
                             "ClusterLoadAssignment",
                "cluster_name": chain_cluster_name(tid, td),
                "endpoints": groups,
            })
    for name, eps in snap.upstream_endpoints.items():
        if name in chain_names:
            continue
        out.append(dict(
            {"@type": T + "envoy.config.endpoint.v3."
                          "ClusterLoadAssignment"},
            **_load_assignment(name, eps)))
    return out


def listeners(snap) -> List[dict]:
    """LDS: the public (inbound, mTLS + RBAC from intentions) listener
    and one outbound listener per upstream (agent/xds/listeners.go
    makePublicListener/makeUpstreamListener)."""
    public = {
        "@type": T + "envoy.config.listener.v3.Listener",
        "name": "public_listener",
        "traffic_direction": "INBOUND",
        "address": _address(
            getattr(snap, "bind_address", "") or "0.0.0.0",
            getattr(snap, "port", 0) or DEFAULT_PUBLIC_PORT),
        "filter_chains": [{
            "transport_socket": _downstream_tls(snap.leaf, snap.roots),
            "filters": [
                _rbac_filter(snap.intentions, snap.default_allow),
                _tcp_proxy("public_listener", "local_app"),
            ],
        }],
    }
    override = _escape_hatch(snap, "envoy_public_listener_json",
                             "envoy.config.listener.v3.Listener")
    out = [override if override is not None else public]
    td = _trust_domain(snap)
    # expose paths: plaintext HTTP listeners that bypass mTLS + RBAC so
    # non-mesh callers (HTTP health checks) can reach specific app
    # paths (agent/structs/connect_proxy_config.go:198,551; consumed in
    # agent/xds/listeners.go expose handling).  Paths sharing a
    # listener_port fold into ONE listener (a second bind on the same
    # port would be NACKed) — the same grouping the builtin proxy's
    # ExposeListener does.
    for lport, paths in sorted(expose_paths_by_port(
            getattr(snap, "expose", None)).items()):
        slug = "_".join(p.strip("/").replace("/", "_")
                        for p in sorted(paths))
        hcm = {
            "name": "envoy.filters.network.http_connection_manager",
            "typed_config": {
                "@type": T + "envoy.extensions.filters.network."
                             "http_connection_manager.v3."
                             "HttpConnectionManager",
                "stat_prefix": f"exposed_path_{slug}",
                "route_config": {
                    "name": f"exposed_path_route_{slug}_{lport}",
                    "virtual_hosts": [{
                        "name": f"exposed_path_route_{slug}_{lport}",
                        "domains": ["*"],
                        "routes": [{
                            "match": {"path": path},
                            "route": {"cluster":
                                      f"exposed_cluster_{lpp}"},
                        } for path, lpp in sorted(paths.items())],
                    }],
                },
                "http_filters": [{
                    "name": "envoy.filters.http.router",
                    "typed_config": {
                        "@type": T + "envoy.extensions.filters.http."
                                     "router.v3.Router"}}],
            },
        }
        out.append({
            "@type": T + "envoy.config.listener.v3.Listener",
            "name": f"exposed_path_{slug}:{lport}",
            "traffic_direction": "INBOUND",
            "address": _address(
                getattr(snap, "bind_address", "") or "0.0.0.0", lport),
            "filter_chains": [{"filters": [hcm]}],
        })
    # transparent-proxy mode: one outbound listener captures all
    # upstream traffic (iptables REDIRECT to outbound_listener_port in
    # the reference; a host-level stand-in on this rig), original-dst
    # restored by the listener filter, per-upstream filter chains
    # matched on the upstream's known endpoint addresses, everything
    # else passed through at the original destination
    # (agent/structs/config_entry.go:89, config_entry_mesh.go:11)
    if getattr(snap, "mode", "") == "transparent":
        oport = (getattr(snap, "transparent_proxy", None) or {}).get(
            "outbound_listener_port") or 15001
        tchains = []
        seen_matches = set()
        for up in snap.upstreams:
            name = up.get("destination_name", "")
            filters = _upstream_filters(snap, name, td)
            addrs = tuple(sorted({
                e.get("address", "")
                for e in getattr(snap, "upstream_endpoints",
                                 {}).get(name, [])
                if e.get("address")}))
            # no known addresses -> no chain: a criteria-less filter
            # chain would act as a catch-all and shadow the default
            # passthrough, capturing ALL outbound traffic into this
            # upstream's cluster at bootstrap; such traffic rides
            # passthrough at the original destination until endpoints
            # resolve.  Identical match sets NACK the listener;
            # colocated upstreams are indistinguishable without
            # per-service virtual IPs — first upstream wins.
            if not addrs or addrs in seen_matches:
                continue
            seen_matches.add(addrs)
            tchains.append({
                "filter_chain_match": {"prefix_ranges": [
                    {"address_prefix": a, "prefix_len": 32}
                    for a in addrs]},
                "filters": filters})
        out.append({
            "@type": T + "envoy.config.listener.v3.Listener",
            "name": f"outbound_listener:127.0.0.1:{oport}",
            "traffic_direction": "OUTBOUND",
            "address": _address("127.0.0.1", oport),
            "listener_filters": [
                {"name": "envoy.filters.listener.original_dst"}],
            "filter_chains": tchains,
            # unmatched destinations pass through at their original
            # address (Envoy picks the default chain when no
            # filter_chain_match hits)
            "default_filter_chain": {"filters": [
                _tcp_proxy("upstream.passthrough",
                           "original-destination")]},
        })
    for up in snap.upstreams:
        name = up.get("destination_name", "")
        # per-upstream listener hatch replaces the generated listener
        # wholesale (listeners.go:102 makeListenerFromUserConfig)
        override = _upstream_escape(
            up, "envoy_listener_json",
            "envoy.config.listener.v3.Listener")
        if override is not None:
            out.append(override)
            continue
        filters = _upstream_filters(snap, name, td)
        out.append({
            "@type": T + "envoy.config.listener.v3.Listener",
            "name": f"{name}:{up.get('local_bind_port', 0)}",
            "traffic_direction": "OUTBOUND",
            "address": _address(
                up.get("local_bind_address", "127.0.0.1"),
                up.get("local_bind_port", 0)),
            "filter_chains": [{"filters": filters}],
        })
    return out


def _envoy_header_matcher(hm: dict) -> Optional[dict]:
    out: Dict = {"name": hm.get("Name", "")}
    if hm.get("Exact"):
        out["exact_match"] = hm["Exact"]
    elif hm.get("Regex"):
        out["safe_regex_match"] = {"google_re2": {}, "regex": hm["Regex"]}
    elif hm.get("Prefix"):
        out["prefix_match"] = hm["Prefix"]
    elif hm.get("Suffix"):
        out["suffix_match"] = hm["Suffix"]
    elif hm.get("Present"):
        out["present_match"] = True
    else:
        return None          # impossible matcher: skip (routes.go does)
    if hm.get("Invert"):
        out["invert_match"] = True
    return out


def _envoy_route_match(match: dict) -> dict:
    em: Dict = {}
    if match.get("PathExact"):
        em["path"] = match["PathExact"]
    elif match.get("PathPrefix"):
        em["prefix"] = match["PathPrefix"]
    elif match.get("PathRegex"):
        em["safe_regex"] = {"google_re2": {}, "regex": match["PathRegex"]}
    else:
        em["prefix"] = "/"
    headers = [h for h in map(_envoy_header_matcher,
                              match.get("Header") or []) if h]
    methods = match.get("Methods") or []
    if methods:
        # methods ride as a :method regex header match (routes.go)
        headers.append({"name": ":method", "safe_regex_match": {
            "google_re2": {}, "regex": "|".join(methods)}})
    if headers:
        em["headers"] = headers
    qps = []
    for qm in match.get("QueryParam") or []:
        q: Dict = {"name": qm.get("Name", "")}
        if qm.get("Exact"):
            q["string_match"] = {"exact": qm["Exact"]}
        elif qm.get("Regex"):
            q["string_match"] = {"safe_regex": {
                "google_re2": {}, "regex": qm["Regex"]}}
        elif qm.get("Present"):
            q["present_match"] = True
        else:
            continue
        qps.append(q)
    if qps:
        em["query_parameters"] = qps
    return em


def _envoy_route_action(route: dict, td: str) -> dict:
    legs = route["clusters"]
    if len(legs) == 1:
        action: Dict = {"cluster": chain_cluster_name(legs[0][1], td)}
    else:
        action = {"weighted_clusters": {
            "clusters": [{"name": chain_cluster_name(t, td), "weight": w}
                         for w, t in legs],
            "total_weight": sum(w for w, _ in legs)}}
    if route.get("prefix_rewrite"):
        action["prefix_rewrite"] = route["prefix_rewrite"]
    if route.get("timeout"):
        action["timeout"] = _duration(route["timeout"])
    retry = route.get("retry") or {}
    if retry:
        rp: Dict = {}
        on = []
        if retry.get("on_connect_failure"):
            on.append("connect-failure")
        if retry.get("on_status_codes"):
            on.append("retriable-status-codes")
            rp["retriable_status_codes"] = retry["on_status_codes"]
        if on:
            rp["retry_on"] = ",".join(on)
        if retry.get("num_retries"):
            rp["num_retries"] = retry["num_retries"]
        action["retry_policy"] = rp
    _inject_lb_to_route_action(route.get("lb"), action)
    return action


def chain_virtual_host(name: str, chain: dict, td: str,
                       domains: Optional[List[str]] = None) -> dict:
    """One virtual host whose routes mirror the chain's router node
    (or a single default route for splitter/resolver starts) —
    makeUpstreamRouteForDiscoveryChain (routes.go:248); shared by the
    connect-proxy RDS and the ingress-gateway vhosts."""
    routes_out = []
    for route in l7.route_table(chain):
        routes_out.append({
            "match": _envoy_route_match(route["match"]),
            "route": _envoy_route_action(route, td)})
    return {"name": name, "domains": domains or ["*"],
            "routes": routes_out}


def chain_route_config(name: str, chain: dict, td: str) -> dict:
    """One upstream's RouteConfiguration from its compiled chain
    (routesForConnectProxy, routes.go:44)."""
    return {
        "@type": T + "envoy.config.route.v3.RouteConfiguration",
        "name": name,
        "virtual_hosts": [chain_virtual_host(name, chain, td)],
        # ValidateClusters defaults false over RDS; the reference
        # re-sets true to prevent null-routing (routes.go:59)
        "validate_clusters": True,
    }


def routes(snap) -> List[dict]:
    """RDS: the public catch-all to the local app, plus one
    RouteConfiguration per upstream with a non-default L7 chain —
    compiled chains REACH THE WIRE here (routesForConnectProxy,
    agent/xds/routes.go:44)."""
    td = _trust_domain(snap)
    out = [{
        "@type": T + "envoy.config.route.v3.RouteConfiguration",
        "name": "public_route",
        "virtual_hosts": [{"name": "default", "domains": ["*"],
                           "routes": [{"match": {"prefix": "/"},
                                       "route": {"cluster":
                                                 "local_app"}}]}],
    }]
    for up in snap.upstreams:
        name = up.get("destination_name", "")
        chain = _upstream_chain(snap, name)
        if chain is not None and chain.get("Protocol") in (
                "http", "http2", "grpc"):
            out.append(chain_route_config(name, chain, td))
    return out


def _trust_domain(snap) -> str:
    uri = snap.leaf.get("ServiceURI", "")
    if uri.startswith("spiffe://"):
        return uri[len("spiffe://"):].split("/")[0]
    return "consul"


# ---------------------------------------------------------------------------
# gateway resource generation (agent/xds listeners/clusters per kind:
# makeMeshGatewayListener, makeTerminatingGatewayListener,
# makeIngressGatewayListeners)
# ---------------------------------------------------------------------------

def _eds_cluster(name: str, eps: List[dict]) -> List[dict]:
    return [
        {"@type": T + "envoy.config.cluster.v3.Cluster", "name": name,
         "type": "EDS",
         "eds_cluster_config": {"eds_config": _ads_config_source(),
                                "service_name": name},
         "connect_timeout": _duration(5)},
        dict({"@type": T + "envoy.config.endpoint.v3."
                           "ClusterLoadAssignment"},
             **_load_assignment(name, eps)),
    ]


def _gateway_port(snap, default: int) -> int:
    return getattr(snap, "port", 0) or default


def mesh_gateway_resources(snap) -> dict:
    """SNI-routed L4 gateway: local services by their mesh SNI, remote
    DCs by a wildcard `*.<dc>` SNI toward that DC's gateways (the
    reference's mesh-gateway listener + cluster-per-dc shape)."""
    td = _trust_domain(snap)
    cl, eds, chains = [], [], []
    for svc, eps in sorted(snap.mesh_endpoints.items()):
        cname = f"local.{svc}"
        c, e = _eds_cluster(cname, eps)
        cl.append(c)
        eds.append(e)
        chains.append({
            "filter_chain_match": {
                "server_names": [f"{svc}.default.{td}"]},
            "filters": [_sni_cluster(),
                        _tcp_proxy(f"mesh_gateway_local.{svc}", cname)],
        })
    for fed in snap.federation_states:
        dc = fed["datacenter"]
        cname = f"dc.{dc}"
        gw_eps = [{"address": g.get("address", ""),
                   "port": g.get("port", 0)}
                  for g in fed.get("mesh_gateways", [])]
        c, e = _eds_cluster(cname, gw_eps)
        cl.append(c)
        eds.append(e)
        chains.append({
            "filter_chain_match": {"server_names": [f"*.{dc}"]},
            "filters": [_sni_cluster(),
                        _tcp_proxy(f"mesh_gateway_remote.{dc}", cname)],
        })
    listener = {
        "@type": T + "envoy.config.listener.v3.Listener",
        "name": "mesh_gateway",
        "traffic_direction": "UNSPECIFIED",
        "address": _address("0.0.0.0", _gateway_port(snap, 8443)),
        "listener_filters": [_tls_inspector()],
        "filter_chains": chains,
    }
    return {"clusters": cl, "endpoints": eds, "listeners": [listener],
            "routes": []}


def terminating_gateway_resources(snap) -> dict:
    """TLS-terminating gateway: one SNI filter chain per bound service,
    presenting that service's mesh leaf inward and proxying to the
    real (non-mesh) instances, with per-service RBAC from intentions.

    HTTP-protocol services get an HTTP connection manager + a named
    default RouteConfiguration with auto_host_rewrite and the
    resolver's LB policy (routesFromSnapshotTerminatingGateway,
    agent/xds/routes.go:71 + makeNamedDefaultRouteWithLB)."""
    cl, eds, fchains, rts = [], [], [], []
    td = _trust_domain(snap)
    svc_chains = getattr(snap, "chains", {})
    for row in snap.gateway_services:
        svc = row["Service"]
        cname = f"term.{svc}"
        chain = svc_chains.get(svc)
        lb = None
        http_like = False
        if chain is not None:
            http_like = chain.get("Protocol") in ("http", "http2",
                                                  "grpc")
            # the service's OWN resolver node, never redirect-chased:
            # term endpoints stay on the original service, so a
            # redirected target's LB must not apply here
            own = chain["Nodes"].get(f"resolver:{svc}") or {}
            lb = own.get("LoadBalancer")
        c, e = _eds_cluster(cname, snap.upstream_endpoints.get(svc, []))
        _inject_lb_to_cluster(lb, c)
        cl.append(c)
        eds.append(e)
        leaf = snap.service_leaves.get(svc) or snap.leaf
        rules = [it for it in snap.intentions
                 if it["destination"] in (svc, "*")]
        if http_like:
            action: Dict = {"cluster": cname,
                            "auto_host_rewrite": True}
            _inject_lb_to_route_action(lb, action)
            rts.append({
                "@type": T + "envoy.config.route.v3."
                             "RouteConfiguration",
                "name": cname,
                "virtual_hosts": [{"name": cname, "domains": ["*"],
                                   "routes": [{
                                       "match": {"prefix": "/"},
                                       "route": action}]}],
                "validate_clusters": True})
            app_filters = [_http_connection_manager(
                f"terminating_gateway.{svc}", cname)]
        else:
            app_filters = [_tcp_proxy(f"terminating_gateway.{svc}",
                                      cname)]
        fchains.append({
            "filter_chain_match": {
                "server_names": [f"{svc}.default.{td}"]},
            "transport_socket": _downstream_tls(leaf, snap.roots),
            "filters": [
                _rbac_filter(rules, snap.default_allow,
                             stat_prefix=f"terminating_gateway.{svc}")]
            + app_filters,
        })
    listener = {
        "@type": T + "envoy.config.listener.v3.Listener",
        "name": "terminating_gateway",
        "traffic_direction": "INBOUND",
        "address": _address("0.0.0.0", _gateway_port(snap, 8443)),
        "listener_filters": [_tls_inspector()],
        "filter_chains": fchains,
    }
    return {"clusters": cl, "endpoints": eds, "listeners": [listener],
            "routes": rts}


def ingress_gateway_resources(snap) -> dict:
    """North-south entry: one listener per configured port; http
    listeners route by host to bound-service clusters, tcp listeners
    proxy straight through (makeIngressGatewayListeners).  Bound
    services with a non-default L7 chain get the CHAIN's virtual host
    and per-target clusters instead of the plain single-cluster route
    (routesForIngressGateway, routes.go:160).

    Listeners are built from the RESOLVED gateway_services rows (not
    the raw config) so a wildcard binding expands to real per-service
    routes/clusters instead of a nonexistent `ingress.*` target."""
    td = _trust_domain(snap)
    cl, eds, lst, rts = [], [], [], []
    seen = set()
    emitted = set()
    by_port: Dict[int, List[dict]] = {}
    chains = getattr(snap, "chains", {})
    ceps = getattr(snap, "chain_endpoints", {})

    def _lb_eps(tid):
        return [{"endpoint": {"address": _address(
            e["address"] or "127.0.0.1", e["port"])}}
            for e in ceps.get(tid, [])]

    # a tcp listener can only ride a chain whose start resolves to a
    # concrete resolver target; a router/splitter-start (http) chain
    # bound to a tcp port falls back to the plain cluster — never a
    # reference to a cluster that was not emitted
    tcp_bound = {r["Service"] for r in snap.gateway_services
                 if str(r.get("Protocol", "tcp")).lower() == "tcp"}

    def _tcp_chain_cluster(chain) -> Optional[str]:
        start = l7._resolve_to_resolver(chain, chain["StartNode"])
        if start is not None and start.get("Target"):
            return chain_cluster_name(start["Target"], td)
        return None

    for row in snap.gateway_services:
        svc = row["Service"]
        by_port.setdefault(row.get("Port", 0), []).append(row)
        if svc in seen:
            continue
        seen.add(svc)
        chain = chains.get(svc)
        if chain is not None and not dchain.is_default_chain(chain):
            if svc in tcp_bound and _tcp_chain_cluster(chain) is None:
                # keep the plain cluster alive for the tcp binding
                c, e = _eds_cluster(
                    f"ingress.{svc}",
                    snap.upstream_endpoints.get(svc, []))
                cl.append(c)
                eds.append(e)
            for node in _chain_resolver_nodes(chain):
                tid = node["Target"]
                cname = chain_cluster_name(tid, td)
                if cname in emitted:
                    continue
                emitted.add(cname)
                c = {"@type": T + "envoy.config.cluster.v3.Cluster",
                     "name": cname, "type": "EDS",
                     "eds_cluster_config": {
                         "eds_config": _ads_config_source(),
                         "service_name": cname},
                     "connect_timeout": _duration(
                         l7._parse_duration(
                             node.get("ConnectTimeout")) or 5)}
                _inject_lb_to_cluster(node.get("LoadBalancer"), c)
                cl.append(c)
                # failover targets ride as priority>0 groups, same as
                # the connect-proxy endpoints() contract
                groups = [{"priority": 0, "lb_endpoints": _lb_eps(tid)}]
                fo = node.get("Failover") or {}
                for i, ftid in enumerate(fo.get("Targets") or []):
                    groups.append({"priority": i + 1,
                                   "lb_endpoints": _lb_eps(ftid)})
                eds.append({"@type": T + "envoy.config.endpoint.v3."
                                         "ClusterLoadAssignment",
                            "cluster_name": cname,
                            "endpoints": groups})
            continue
        c, e = _eds_cluster(f"ingress.{svc}",
                            snap.upstream_endpoints.get(svc, []))
        cl.append(c)
        eds.append(e)
    for li in snap.listeners:
        port = li.get("port", 0)
        proto = li.get("protocol", "tcp")
        rows = by_port.get(port, [])
        name = f"ingress:{port}"
        if proto == "tcp":
            # tcp carries no routing discriminator: exactly one bound
            # service is servable (the reference validates this at the
            # config entry); zero services → no listener to emit
            if not rows:
                continue
            tcp_svc = rows[0]["Service"]
            tcp_chain = chains.get(tcp_svc)
            tcp_cluster = None
            if tcp_chain is not None and \
                    not dchain.is_default_chain(tcp_chain):
                # a non-default tcp chain replaced ingress.<svc> with
                # per-target clusters: proxy to the start resolver's
                # target (same shape as the connect-proxy listeners);
                # http-start chains fall back to the plain cluster the
                # cluster loop kept alive for this exact case
                tcp_cluster = _tcp_chain_cluster(tcp_chain)
            if tcp_cluster is None:
                tcp_cluster = f"ingress.{tcp_svc}"
            lst.append({
                "@type": T + "envoy.config.listener.v3.Listener",
                "name": name, "traffic_direction": "OUTBOUND",
                "address": _address("0.0.0.0", port),
                "filter_chains": [{"filters": [
                    _tcp_proxy(name, tcp_cluster)]}],
            })
        else:
            vhosts = []
            for row in rows:
                svc = row["Service"]
                domains = row.get("Hosts") or [f"{svc}.ingress.*", svc]
                chain = chains.get(svc)
                if chain is not None and \
                        not dchain.is_default_chain(chain):
                    vhosts.append(chain_virtual_host(
                        svc, chain, td, domains=domains))
                else:
                    vhosts.append({
                        "name": svc, "domains": domains,
                        "routes": [{"match": {"prefix": "/"},
                                    "route": {"cluster":
                                              f"ingress.{svc}"}}]})
            rts.append({
                "@type": T + "envoy.config.route.v3.RouteConfiguration",
                "name": name, "virtual_hosts": vhosts,
                "validate_clusters": True})
            lst.append({
                "@type": T + "envoy.config.listener.v3.Listener",
                "name": name, "traffic_direction": "OUTBOUND",
                "address": _address("0.0.0.0", port),
                "filter_chains": [{"filters": [
                    _http_connection_manager(name, name)]}],
            })
    return {"clusters": cl, "endpoints": eds, "listeners": lst,
            "routes": rts}


# resource identity per type (delta.go tracks resources by name so an
# update ships only what changed)
_DELTA_KEYS = {"clusters": "name", "endpoints": "cluster_name",
               "listeners": "name", "routes": "name"}

# canonical Envoy v3 type URLs per resource group (the ADS contract)
TYPE_URLS = {
    "clusters": T + "envoy.config.cluster.v3.Cluster",
    "endpoints": T + "envoy.config.endpoint.v3.ClusterLoadAssignment",
    "listeners": T + "envoy.config.listener.v3.Listener",
    "routes": T + "envoy.config.route.v3.RouteConfiguration",
}


def delta(prev_resources: dict, new_resources: dict) -> dict:
    """Per-resource diff between two payload versions
    (DeltaAggregatedResources semantics: changed resources in full,
    removed resources by name)."""
    changed, removed = {}, {}
    for rtype, keyf in _DELTA_KEYS.items():
        old = {r[keyf]: r for r in prev_resources.get(rtype, [])}
        new = {r[keyf]: r for r in new_resources.get(rtype, [])}
        ch = [r for k, r in new.items()
              if k not in old or old[k] != r]
        rm = sorted(k for k in old if k not in new)
        if ch:
            changed[rtype] = ch
        if rm:
            removed[rtype] = rm
    return {"Changed": changed, "Removed": removed}


def note_http_push_counters(payload: dict, mode: str = "full") -> None:
    """Transport parity for the JSON/HTTP ADS frontend: the same
    `consul.xds.{pushes,resources}{type,mode}` counters the gRPC
    stream emits per type URL (xds_grpc._note_pushed), keyed here by
    the payload's resource-group names.  For a ?delta response only
    the CHANGED groups count — that is what actually crossed the wire
    — and `mode` records whether the client got a per-subset delta or
    a whole snapshot (ISSUE 19: the delta/full split is how the
    fan-out sweep proves wire cost scales with affected subsets).
    Called AFTER the HTTP response flush; no store/proxycfg lock is
    held."""
    from consul_tpu import telemetry
    res = payload.get("Resources")
    if res is None:
        res = (payload.get("Delta") or {}).get("Changed") or {}
    if not isinstance(res, dict):
        return
    for group, rows in res.items():
        telemetry.incr_counter(("xds", "pushes"), 1.0,
                               labels={"type": group, "mode": mode})
        if rows:
            telemetry.incr_counter(("xds", "resources"),
                                   float(len(rows)),
                                   labels={"type": group,
                                           "mode": mode})


def snapshot_resources(snap) -> dict:
    """Full ADS payload for one proxy version (DeltaAggregatedResources
    response analogue); gateway kinds get their own resource shapes."""
    kind = getattr(snap, "kind", "connect-proxy")
    if kind == "mesh-gateway":
        res = mesh_gateway_resources(snap)
    elif kind == "terminating-gateway":
        res = terminating_gateway_resources(snap)
    elif kind == "ingress-gateway":
        res = ingress_gateway_resources(snap)
    else:
        res = {
            "clusters": clusters(snap),
            "endpoints": endpoints(snap),
            "listeners": listeners(snap),
            "routes": routes(snap),
        }
    return {
        "VersionInfo": str(snap.version),
        "ProxyID": snap.proxy_id,
        "Service": snap.service,
        "Kind": kind,
        "Resources": res,
    }
