"""xDS resource generation: ConfigSnapshot → Envoy-shaped config.

The reference's xDS server (agent/xds/server.go:186, delta.go:33) speaks
gRPC ADS to Envoy, generating Clusters, ClusterLoadAssignments,
Listeners, and Routes (+ RBAC filters from intentions) per proxy
snapshot.  This framework generates the same resource set as plain JSON
dicts in Envoy's v3 field shapes and serves them over HTTP long-poll
(GET /v1/agent/xds/<proxy_id>?version=&wait=) — a deliberate divergence:
the control-plane protocol is JSON/HTTP instead of protobuf/gRPC, but
the resource content and update semantics (version-gated delta polls)
mirror the reference.
"""

from __future__ import annotations

import re
from typing import Dict, List

from consul_tpu.connect import intentions as imod


def _principal_regex(source: str) -> str:
    """SPIFFE principal matcher for an intention source: literal parts
    regex-escaped, only the intention wildcard maps to `.*` — a dotted
    service name must not match arbitrary characters."""
    escaped = ".*".join(re.escape(p) for p in source.split("*"))
    return (r"spiffe://[^/]+/ns/[^/]+/dc/[^/]+/svc/" + escaped)


def clusters(snap) -> List[dict]:
    """CDS: one cluster per upstream + the local app cluster
    (agent/xds/clusters.go)."""
    out = [{
        "@type": "envoy.config.cluster.v3.Cluster",
        "name": "local_app",
        "type": "STATIC",
        "connect_timeout": "5s",
    }]
    for up in snap.upstreams:
        name = up.get("destination_name", "")
        out.append({
            "@type": "envoy.config.cluster.v3.Cluster",
            "name": name,
            "type": "EDS",
            "connect_timeout": "5s",
            "transport_socket": {
                "name": "tls",
                "sni": f"{name}.default.{_trust_domain(snap)}",
                "common_tls_context": {
                    "tls_certificates": [{
                        "certificate_chain": snap.leaf["CertPEM"],
                        "private_key": snap.leaf["PrivateKeyPEM"]}],
                    "validation_context": {
                        "trusted_ca": "".join(
                            r["RootCert"] for r in snap.roots)},
                },
            },
        })
    return out


def endpoints(snap) -> List[dict]:
    """EDS: ClusterLoadAssignment per upstream
    (agent/xds/endpoints.go)."""
    out = []
    for name, eps in snap.upstream_endpoints.items():
        out.append({
            "@type": "envoy.config.endpoint.v3.ClusterLoadAssignment",
            "cluster_name": name,
            "endpoints": [{
                "lb_endpoints": [{
                    "endpoint": {"address": {"socket_address": {
                        "address": e["address"] or "127.0.0.1",
                        "port_value": e["port"]}}}}
                    for e in eps]}],
        })
    return out


def listeners(snap) -> List[dict]:
    """LDS: the public (inbound, mTLS + RBAC from intentions) listener and
    one outbound listener per upstream (agent/xds/listeners.go)."""
    rules = []
    for it in snap.intentions:
        principal = {"authenticated": {"principal_name": {
            "safe_regex": {"regex": _principal_regex(it["source"])}}}}
        rules.append({"action": it["action"].upper(),
                      "precedence": it["precedence"],
                      "principals": [principal]})
    public = {
        "@type": "envoy.config.listener.v3.Listener",
        "name": "public_listener",
        "traffic_direction": "INBOUND",
        "filter_chains": [{
            "transport_socket": {
                "name": "tls",
                "require_client_certificate": True,
                "common_tls_context": {
                    "tls_certificates": [{
                        "certificate_chain": snap.leaf["CertPEM"],
                        "private_key": snap.leaf["PrivateKeyPEM"]}],
                    "validation_context": {
                        "trusted_ca": "".join(
                            r["RootCert"] for r in snap.roots)},
                },
            },
            "filters": [
                {"name": "envoy.filters.network.rbac",
                 "rules": rules,
                 "default_action": "ALLOW" if snap.default_allow
                 else "DENY"},
                {"name": "envoy.filters.network.tcp_proxy",
                 "cluster": "local_app"},
            ],
        }],
    }
    out = [public]
    for up in snap.upstreams:
        name = up.get("destination_name", "")
        out.append({
            "@type": "envoy.config.listener.v3.Listener",
            "name": f"{name}:{up.get('local_bind_port', 0)}",
            "traffic_direction": "OUTBOUND",
            "address": {"socket_address": {
                "address": up.get("local_bind_address", "127.0.0.1"),
                "port_value": up.get("local_bind_port", 0)}},
            "filter_chains": [{"filters": [
                {"name": "envoy.filters.network.tcp_proxy",
                 "cluster": name}]}],
        })
    return out


def routes(snap) -> List[dict]:
    """RDS: trivial catch-all route to the local app (the L4 default;
    discovery-chain L7 routing layers on top in the reference)."""
    return [{
        "@type": "envoy.config.route.v3.RouteConfiguration",
        "name": "public_route",
        "virtual_hosts": [{"name": "default", "domains": ["*"],
                           "routes": [{"match": {"prefix": "/"},
                                       "route": {"cluster":
                                                 "local_app"}}]}],
    }]


def _trust_domain(snap) -> str:
    uri = snap.leaf.get("ServiceURI", "")
    if uri.startswith("spiffe://"):
        return uri[len("spiffe://"):].split("/")[0]
    return "consul"


def snapshot_resources(snap) -> dict:
    """Full ADS payload for one proxy version (DeltaAggregatedResources
    response analogue)."""
    return {
        "VersionInfo": str(snap.version),
        "ProxyID": snap.proxy_id,
        "Service": snap.service,
        "Resources": {
            "clusters": clusters(snap),
            "endpoints": endpoints(snap),
            "listeners": listeners(snap),
            "routes": routes(snap),
        },
    }
