"""Device synchronization that actually synchronizes.

`jax.block_until_ready` can return before remote-tunnel execution
finishes (observed under the axon backend), silently folding unfinished
device work into whatever the caller times next.  `hard_sync` forces a
host transfer of (a leaf of) the value, which cannot complete before the
producing computation has.
"""

from __future__ import annotations

import jax
import numpy as np


def hard_sync(tree) -> None:
    """Block until every leaf of `tree` has materialized, via a host
    transfer of each leaf's first element (tiny, but a true fence)."""
    for leaf in jax.tree_util.tree_leaves(tree):
        arr = np.asarray(leaf if getattr(leaf, "ndim", 0) == 0
                         else leaf.ravel()[:1])
        del arr
