"""Device synchronization that actually synchronizes.

`jax.block_until_ready` can return before remote-tunnel execution
finishes (observed under the axon backend), silently folding unfinished
device work into whatever the caller times next.  `hard_sync` forces a
host transfer, which cannot complete before the producing computation
has.

Cost model matters under a remote tunnel: every transfer pays an RTT.
Outputs of ONE jit call complete atomically before any of them can
transfer, so fencing a single leaf fences the whole call — the default.
Pass all_leaves=True only when the tree mixes results from multiple
dispatches.
"""

from __future__ import annotations

import jax
import numpy as np


def hard_sync(tree, all_leaves: bool = False) -> None:
    """Block until `tree` has materialized via host transfer of one leaf
    (or every leaf when they may come from different dispatches)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return
    targets = leaves if all_leaves else leaves[-1:]
    for leaf in targets:
        np.asarray(leaf if getattr(leaf, "ndim", 0) == 0
                   else leaf.ravel()[:1])
