"""Device synchronization that actually synchronizes.

`jax.block_until_ready` can return before remote-tunnel execution
finishes (observed under the axon backend), silently folding unfinished
device work into whatever the caller times next.  `hard_sync` forces a
host transfer, which cannot complete before the producing computation
has.

Cost model matters under a remote tunnel: every transfer pays an RTT.
Outputs of ONE jit call complete atomically before any of them can
transfer, so fencing a single leaf fences the whole call — the default.
Pass all_leaves=True only when the tree mixes results from multiple
dispatches.
"""

from __future__ import annotations

import jax
import numpy as np


# donation capability per backend, probed once (a process never swaps
# the implementation under a backend name)
_DONATION_PROBED: dict = {}


def backend_honors_donation() -> bool:
    """Does the current backend actually alias donated buffers?  Probed
    by compiling one trivial donated program and reading the
    `input_output_alias` header from the executable — the same
    evidence the hlo_lint donation rule judges, so the gate and the
    gate's gate can never disagree.  (The old hard-coded `backend !=
    "cpu"` test was stale: current jax CPU honors aliasing, and the
    gate was silently disabling donation — and with it the
    donation-honored contract — on the whole CPU test rig.  ISSUE 20's
    first tree-wide finding.)"""
    backend = jax.default_backend()
    ok = _DONATION_PROBED.get(backend)
    if ok is None:
        import jax.numpy as jnp
        probe = jax.jit(lambda x: x + 1, donate_argnums=0)
        try:
            hlo = probe.lower(
                jnp.zeros((16,), jnp.float32)).compile().as_text()
            ok = "input_output_alias" in hlo
        except Exception:   # pragma: no cover - exotic backends
            ok = False
        _DONATION_PROBED[backend] = ok
    return ok


def donation(*argnums: int) -> tuple:
    """`donate_argnums` for a state-carry jit, gated on the backend's
    PROBED aliasing support (backend_honors_donation) rather than a
    hard-coded platform list.

    Donating the SwimState/ClusterState carry lets XLA update the
    [N]-shaped state arrays in place instead of double-buffering
    1M-row tensors in HBM.  Only donate when the caller owns its state
    exclusively and always rebinds to the output (bench/tool loops do;
    the oracle does NOT — see oracle.py)."""
    return tuple(argnums) if backend_honors_donation() else ()


def hard_sync(tree, all_leaves: bool = False) -> None:
    """Block until `tree` has materialized via host transfer of one leaf
    (or every leaf when they may come from different dispatches)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return
    targets = leaves if all_leaves else leaves[-1:]
    for leaf in targets:
        np.asarray(leaf if getattr(leaf, "ndim", 0) == 0
                   else leaf.ravel()[:1])
