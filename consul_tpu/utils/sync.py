"""Device synchronization that actually synchronizes.

`jax.block_until_ready` can return before remote-tunnel execution
finishes (observed under the axon backend), silently folding unfinished
device work into whatever the caller times next.  `hard_sync` forces a
host transfer, which cannot complete before the producing computation
has.

Cost model matters under a remote tunnel: every transfer pays an RTT.
Outputs of ONE jit call complete atomically before any of them can
transfer, so fencing a single leaf fences the whole call — the default.
Pass all_leaves=True only when the tree mixes results from multiple
dispatches.
"""

from __future__ import annotations

import jax
import numpy as np


def donation(*argnums: int) -> tuple:
    """`donate_argnums` for a state-carry jit, gated off the CPU backend.

    Donating the SwimState/ClusterState carry lets XLA update the
    [N]-shaped state arrays in place instead of double-buffering
    1M-row tensors in HBM; the CPU backend ignores donation and warns
    on every call, so the gate keeps test logs clean.  Only donate when
    the caller owns its state exclusively and always rebinds to the
    output (bench/tool loops do; the oracle does NOT — see oracle.py)."""
    return tuple(argnums) if jax.default_backend() != "cpu" else ()


def hard_sync(tree, all_leaves: bool = False) -> None:
    """Block until `tree` has materialized via host transfer of one leaf
    (or every leaf when they may come from different dispatches)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return
    targets = leaves if all_leaves else leaves[-1:]
    for leaf in targets:
        np.asarray(leaf if getattr(leaf, "ndim", 0) == 0
                   else leaf.ravel()[:1])
