"""Counter-based stateless PRNG helpers.

At 1M simulated nodes there is no per-node host entropy; every random draw
is derived from (seed, tick, stream) via threefry fold-ins so the whole
simulation is a pure function of its seed (SURVEY.md §7 hard part (b)).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def tick_key(seed, tick, stream: int):
    """Derive a key for (tick, stream) from an integer seed.

    `tick` may be a traced int32; `stream` must be a static python int.
    """
    base = jax.random.PRNGKey(seed)
    return jax.random.fold_in(jax.random.fold_in(base, stream), tick)


def other_nodes(key, n: int, shape) -> jnp.ndarray:
    """Uniform node ids excluding the row's own id.

    Returns int32 array of `shape`; shape[0] must be n (row i never draws i).
    """
    draw = jax.random.randint(key, shape, 0, n - 1, dtype=jnp.int32)
    rows = jnp.arange(n, dtype=jnp.int32).reshape((n,) + (1,) * (len(shape) - 1))
    return (rows + 1 + draw) % n
