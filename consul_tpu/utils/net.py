"""Small socket helpers shared across the runtime's listeners."""

from __future__ import annotations

import socket


def shutdown_and_close(sock: socket.socket) -> None:
    """Wake any thread parked in accept()/recv() on `sock`, then close.

    close() alone does NOT wake a thread already blocked in accept():
    the orphan keeps the fd slot and, once the number is reused by an
    unrelated socket (ssl/grpc), accepts on IT — native-level
    corruption that surfaces as interpreter segfaults long after the
    leak.  shutdown(SHUT_RDWR) wakes the parked thread first (Linux
    semantics; the ENOTCONN some platforms raise is swallowed)."""
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass
