"""One duration parser for the whole tree ("10s"/"1.5m"/"500ms").

The HTTP layer's ?wait= parsing and the client-side session TTLs both
speak Go duration strings; a single implementation keeps them from
drifting (lib parseWait / time.ParseDuration role)."""

from __future__ import annotations

import re
from typing import Any

_DURATION = re.compile(r"(\d+(?:\.\d+)?)(ms|s|m|h)?")

_SCALE = {"ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0}


def parse_duration(val: Any, default: float) -> float:
    """Seconds from a duration string; bare numbers mean seconds;
    anything unparsable yields `default`."""
    m = _DURATION.fullmatch(str(val))
    if not m:
        return default
    return float(m.group(1)) * _SCALE[m.group(2) or "s"]
