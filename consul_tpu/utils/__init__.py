from consul_tpu.utils import prng

__all__ = ["prng"]
