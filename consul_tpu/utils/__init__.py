from consul_tpu.utils import prng
from consul_tpu.utils.sync import donation, hard_sync

__all__ = ["prng", "hard_sync", "donation"]
