from consul_tpu.utils import prng
from consul_tpu.utils.sync import (backend_honors_donation, donation,
                                   hard_sync)

__all__ = ["prng", "hard_sync", "donation", "backend_honors_donation"]
