"""proxycfg: per-proxy configuration snapshots for the mesh data plane.

The reference's proxycfg manager (agent/proxycfg/manager.go:38, Watch
:303, state machine state.go) assembles, per registered sidecar proxy, a
ConfigSnapshot from many watches — CA roots, the service leaf, upstream
health, intentions — and pushes a fresh snapshot to the xDS server on
every relevant change.  Here each snapshot rebuilds from materialized
sources when a relevant store event lands (health of an upstream,
intention change) or the CA rotates, and `watch()` serves blocking
fetches keyed by version, exactly the shape the xDS layer long-polls.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from consul_tpu.connect import intentions as imod

# re-sign margin: leaves refresh well before their notAfter
_LEAF_REFRESH_FRACTION = 0.75


class ConfigSnapshot:
    """One proxy's full mesh view (proxycfg.ConfigSnapshot)."""

    def __init__(self, proxy_id: str, service: str, upstreams: List[dict],
                 roots: List[dict], leaf: dict,
                 upstream_endpoints: Dict[str, List[dict]],
                 intentions: List[dict], default_allow: bool,
                 version: int):
        self.proxy_id = proxy_id
        self.service = service
        self.upstreams = upstreams
        self.roots = roots
        self.leaf = leaf
        self.upstream_endpoints = upstream_endpoints
        self.intentions = intentions
        self.default_allow = default_allow
        self.version = version


class ProxyState:
    """Watch set + rebuild loop for one proxy (proxycfg/state.go)."""

    def __init__(self, manager: "Manager", proxy_id: str, svc: dict,
                 start_version: int = 0):
        self.manager = manager
        self.proxy_id = proxy_id
        self.svc = svc
        self._cond = threading.Condition()
        self._snapshot: Optional[ConfigSnapshot] = None
        # versions survive state replacement: a long-poller parked on
        # version N must see N+1 from the REPLACED state, not a restart
        # at 1 it would read as no-change
        self._version = start_version
        self._subs = []
        self._running = False
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._running = True
        self._rebuild()
        pub = self.manager.store.publisher
        proxy = self.svc.get("proxy") or {}
        # CA topic included: a root rotation must rebuild every proxy
        # snapshot without waiting for unrelated churn
        topics = [("intentions", None), ("ca", None)]
        for up in proxy.get("upstreams") or []:
            topics.append(("health", up.get("destination_name", "")))
        self._subs = [pub.subscribe(t, k, since_index=None)
                      for t, k in topics]
        self._thread = threading.Thread(target=self._follow, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        with self._cond:
            # wake parked fetchers so they re-poll (and land on the
            # replacement state) instead of sleeping out their wait
            self._cond.notify_all()
        for s in self._subs:
            s.close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _follow(self) -> None:
        from consul_tpu.stream.publisher import SnapshotRequired
        while self._running:
            fired = False
            for s in self._subs:
                try:
                    if s.events(timeout=0.2):
                        fired = True
                except SnapshotRequired:
                    if not self._running:
                        return
                    fired = True
            if fired:
                self._rebuild()

    def _rebuild(self) -> None:
        m = self.manager
        proxy = self.svc.get("proxy") or {}
        service = proxy.get("destination_service",
                            self.svc.get("name", ""))
        upstreams = proxy.get("upstreams") or []
        endpoints: Dict[str, List[dict]] = {}
        for up in upstreams:
            name = up.get("destination_name", "")
            rows = m.store.health_service_nodes(name)
            eps = []
            for r in rows:
                if any(c["status"] == "critical" for c in r["checks"]):
                    continue
                s = r["service"]
                eps.append({"address": s.get("service_address")
                            or s.get("address", ""),
                            "port": s.get("port", 0),
                            "node": s.get("node", "")})
            endpoints[name] = eps
        relevant = imod.match_order(m.store.intention_list(), service,
                                    "destination")
        leaf = m.get_leaf(service)
        with self._cond:
            self._version += 1
            self._snapshot = ConfigSnapshot(
                proxy_id=self.proxy_id, service=service,
                upstreams=upstreams, roots=m.ca.roots(), leaf=leaf,
                upstream_endpoints=endpoints, intentions=relevant,
                default_allow=m.default_allow, version=self._version)
            self._cond.notify_all()

    def fetch(self, min_version: int = 0,
              timeout: float = 300.0) -> ConfigSnapshot:
        deadline = time.time() + timeout
        with self._cond:
            while (self._snapshot is None
                   or self._snapshot.version <= min_version):
                remaining = deadline - time.time()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            return self._snapshot


class Manager:
    """Proxy registry (proxycfg.Manager): one ProxyState per registered
    sidecar, created lazily from the catalog's connect-proxy services."""

    def __init__(self, store, ca, default_allow: bool = True):
        self.store = store
        self.ca = ca
        self.default_allow = default_allow
        # svc -> (root_id, leaf, refresh_deadline)
        self._leaves: Dict[str, Tuple[str, dict, float]] = {}
        self._leaf_lock = threading.Lock()
        self._states: Dict[str, ProxyState] = {}
        self._lock = threading.Lock()

    def get_leaf(self, service: str) -> dict:
        """Cached leaf, re-signed when missing, when the active root
        moved, or when the leaf nears expiry (an agent outliving the
        72h leaf TTL must not serve expired certs)."""
        active = self.ca.active.id
        now = time.time()
        with self._leaf_lock:
            hit = self._leaves.get(service)
            if hit is not None and hit[0] == active and now < hit[2]:
                return hit[1]
            leaf = self.ca.sign_leaf(service)
            ttl_s = self.ca.leaf_ttl_hours * 3600.0
            refresh_at = now + ttl_s * _LEAF_REFRESH_FRACTION
            self._leaves[service] = (active, leaf, refresh_at)
            return leaf

    def watch(self, proxy_id: str) -> Optional[ProxyState]:
        """ProxyState for a registered connect-proxy service id
        (Manager.Watch :303); None when no such proxy exists.  The
        catalog is revalidated on every call: a re-registration with a
        changed proxy config replaces the state (new watch set), a
        deregistered proxy drops it."""
        svc = self._find_proxy(proxy_id)
        with self._lock:
            st = self._states.get(proxy_id)
            if svc is None:
                if st is not None:
                    st.stop()
                    del self._states[proxy_id]
                return None
            if st is not None and st.svc.get("modify_index") == \
                    svc.get("modify_index"):
                return st
            start_version = st._version if st is not None else 0
            if st is not None:
                st.stop()
            st = ProxyState(self, proxy_id, svc,
                            start_version=start_version)
            st.start()
            self._states[proxy_id] = st
            return st

    def _find_proxy(self, proxy_id: str) -> Optional[dict]:
        s = self.store.service_by_id(proxy_id)
        if s is not None and s.get("kind") == "connect-proxy":
            return s
        return None

    def close(self) -> None:
        with self._lock:
            for st in self._states.values():
                st.stop()
            self._states.clear()
