"""proxycfg: per-proxy configuration snapshots for the mesh data plane.

The reference's proxycfg manager (agent/proxycfg/manager.go:38, Watch
:303, state machine state.go) assembles, per registered sidecar proxy, a
ConfigSnapshot from many watches — CA roots, the service leaf, upstream
health, intentions — and pushes a fresh snapshot to the xDS server on
every relevant change.

Shared-shape materialization (ISSUE 19 tentpole): N same-shaped sidecars
of one service used to pay N materializations (and N publisher
subscription sets) per catalog change.  The rebuild now routes through a
single-flight shape store keyed on ``(kind, service, config-hash)`` —
one `SharedShape` owns the follow loop, the watch set, and the expensive
materialization; each `ProxyState` is a cheap projection that overlays
the per-proxy fields (proxy id, leaf, bind address/ports) on the shared
build.  Creation is single-flight (submatview.ViewStore discipline: the
first requester materializes, concurrent requesters park on the entry
gate, a failed creation releases waiters and vacates the slot), and the
shape evicts on last disconnect.  `watch()` still serves blocking
fetches keyed by per-proxy version, exactly the shape the xDS layer
long-polls, and the per-proxy `stats()` rows keep rendering.
"""

from __future__ import annotations

import hashlib
import json
import logging
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from consul_tpu import locks
from consul_tpu.connect import intentions as imod

# re-sign margin: leaves refresh well before their notAfter
_LEAF_REFRESH_FRACTION = 0.75

# per-proxy registration keys that do NOT shape the shared
# materialization: everything else in the proxy block must hash equal
# for two sidecars to share a build
_PER_PROXY_KEYS = ("local_service_port",)


def shape_key(svc: dict) -> Tuple[str, str, str]:
    """The shape identity ``(kind, service, config-hash)`` of a proxy
    registration: proxies agreeing on all three share one
    materialization.  The hash covers the registration's proxy block
    minus the per-proxy fields (bind port/address live at the top
    level and never enter it)."""
    kind = svc.get("kind", "connect-proxy")
    proxy = svc.get("proxy") or {}
    if kind == "connect-proxy":
        service = proxy.get("destination_service", svc.get("name", ""))
    else:
        service = svc.get("name", "")
    shaped = {k: v for k, v in proxy.items() if k not in _PER_PROXY_KEYS}
    blob = json.dumps(shaped, sort_keys=True, default=str)
    h = hashlib.sha1(blob.encode()).hexdigest()[:12]
    return (kind, service, h)


class ConfigSnapshot:
    """One proxy's full mesh view (proxycfg.ConfigSnapshot).

    `kind` selects the per-kind extras (proxycfg's
    configSnapshotConnectProxy / MeshGateway / TerminatingGateway /
    IngressGateway unions):
      mesh-gateway:        mesh_endpoints (local svc -> endpoints),
                           federation_states (remote dc -> gateways)
      terminating-gateway: gateway_services rows + per-service leaves
      ingress-gateway:     listeners from the config entry
    """

    def __init__(self, proxy_id: str, service: str, upstreams: List[dict],
                 roots: List[dict], leaf: dict,
                 upstream_endpoints: Dict[str, List[dict]],
                 intentions: List[dict], default_allow: bool,
                 version: int, kind: str = "connect-proxy",
                 gateway_services: Optional[List[dict]] = None,
                 service_leaves: Optional[Dict[str, dict]] = None,
                 mesh_endpoints: Optional[Dict[str, List[dict]]] = None,
                 federation_states: Optional[List[dict]] = None,
                 listeners: Optional[List[dict]] = None,
                 port: int = 0, bind_address: str = "",
                 local_port: int = 0,
                 chains: Optional[Dict[str, dict]] = None,
                 chain_endpoints: Optional[Dict[str, List[dict]]] = None,
                 expose: Optional[dict] = None, mode: str = "",
                 transparent_proxy: Optional[dict] = None,
                 opaque_config: Optional[dict] = None):
        self.proxy_id = proxy_id
        self.service = service
        self.upstreams = upstreams
        self.roots = roots
        self.leaf = leaf
        self.upstream_endpoints = upstream_endpoints
        self.intentions = intentions
        self.default_allow = default_allow
        self.version = version
        self.kind = kind
        self.gateway_services = gateway_services or []
        self.service_leaves = service_leaves or {}
        self.mesh_endpoints = mesh_endpoints or {}
        self.federation_states = federation_states or []
        self.listeners = listeners or []
        # bind surface of the proxy itself (registration port) and the
        # local app port behind it — Envoy listener addresses and the
        # local_app load assignment need real sockets to be valid
        self.port = port
        self.bind_address = bind_address
        self.local_port = local_port
        # discovery chains per upstream + endpoints per chain TARGET id
        # (proxycfg's ConfigSnapshotUpstreams DiscoveryChain /
        # WatchedUpstreamEndpoints)
        self.chains = chains or {}
        self.chain_endpoints = chain_endpoints or {}
        # operational proxy surface, already merged with central
        # defaults (structs.ConnectProxyConfig Expose / Mode /
        # TransparentProxy — agent/structs/connect_proxy_config.go:198,
        # config_entry.go:89)
        self.expose = expose or {}
        self.mode = mode
        self.transparent_proxy = transparent_proxy or {}
        # the registration's opaque Proxy.Config merged with central
        # proxy-defaults (xDS escape hatches live here —
        # agent/xds/config.go:28,34 envoy_public_listener_json /
        # envoy_local_cluster_json)
        self.opaque_config = opaque_config or {}
        # commit-to-push correlation (ISSUE 16): the store index and
        # writer trace id of the stream event that TRIGGERED this
        # rebuild (0/"" for the initial build — nothing to correlate),
        # plus a once-only marker the first push site to deliver this
        # snapshot flips (under the owning state's lock) so the
        # apply->push stage is sampled exactly once per snapshot
        self.store_index = 0
        self.trace_id = ""
        self.push_emitted = False


class SharedShape:
    """ONE follow/rebuild loop per distinct (kind, service,
    config-hash): the shared materialization every same-shaped proxy
    projects from (ISSUE 19).  Owns the watch set (ONE publisher
    subscription set per shape), the shape-level build (everything in
    a ConfigSnapshot that does not name a specific proxy), and the
    rebuild SLI ring the per-proxy stats rows render."""

    def __init__(self, manager: "Manager", key: Tuple[str, str, str],
                 svc: dict):
        self.manager = manager
        self.key = key
        self.kind = key[0]
        self.name = f"shape:{key[1]}@{key[2][:8]}"
        # shape EXEMPLAR registration: the shared rebuild reads only
        # shape-relevant fields from it (per-proxy fields are overlaid
        # at projection time by each ProxyState)
        self.svc = svc
        self._lock = locks.make_lock("proxycfg.shape")
        self._cond = locks.make_condition(self._lock)
        self._build: Optional[dict] = None               # guarded-by: _lock
        self._version = 0                                # guarded-by: _lock
        self._subs: List[object] = []                    # guarded-by: _lock
        # gateways + chain targets: per-bound-service health subs,
        # resynced after each rebuild as bindings change
        self._health_subs: Dict[str, object] = {}        # guarded-by: _lock
        self._running = False                            # guarded-by: _lock
        self._inflight = 0                               # guarded-by: _lock
        self._thread: Optional[threading.Thread] = None
        # shared wakeup for the follow loop: attached to EVERY
        # subscription so one park covers the whole watch set (Event
        # is self-synchronized; not guarded)
        self._wake = threading.Event()
        self._rebuild_ms = deque(maxlen=128)             # guarded-by: _lock
        self._rebuilds = 0                               # guarded-by: _lock
        self._last_rebuild_ts = 0.0                      # guarded-by: _lock
        locks.register_guards(self, self._lock, "_build", "_version",
                              "_subs", "_health_subs", "_running",
                              "_inflight", "_rebuild_ms", "_rebuilds",
                              "_last_rebuild_ts")

    def start(self) -> None:
        with self._lock:
            self._running = True
        self._rebuild()
        pub = self.manager.store.publisher
        proxy = self.svc.get("proxy") or {}
        kind = self.kind
        # CA topic included: a root rotation must rebuild every proxy
        # snapshot without waiting for unrelated churn
        topics = [("intentions", None), ("ca", None)]
        if kind == "connect-proxy":
            for up in proxy.get("upstreams") or []:
                topics.append(("health", up.get("destination_name", "")))
            # router/splitter/resolver entries reshape the chain; the
            # chain's split/failover TARGET services get per-service
            # health subs via _sync_health_subs after each rebuild.
            # federation: cross-dc failover targets resolve through the
            # remote DC's mesh gateways, so gateway address changes
            # must rebuild chain_endpoints too
            topics.append(("config", None))
            topics.append(("federation", None))
        elif kind == "mesh-gateway":
            # a mesh gateway genuinely fronts every local service and
            # every remote DC: topic-wide health + federation watches
            # are its real dependency set (proxycfg/state.go mesh-gw)
            topics += [("config", None), ("health", None),
                       ("federation", None)]
        elif kind == "ingress-gateway":
            # ingress consumes bound services' DISCOVERY CHAINS, so any
            # router/splitter/resolver write must rebuild — topic-wide
            # config sub (plus services for wildcard binding changes,
            # and federation because cross-dc failover targets resolve
            # through remote mesh gateways)
            topics += [("config", None), ("services", None),
                       ("federation", None)]
        else:
            # terminating: bound services' protocols (service-defaults)
            # and resolvers (LB) shape the filter chains, so config
            # writes anywhere must rebuild, like ingress; endpoint
            # health stays per bound service via _sync_health_subs
            topics += [("config", None), ("services", None)]
        subs = [pub.subscribe(t, k, since_index=None)
                for t, k in topics]
        for s in subs:
            s.attach_wake(self._wake)
        with self._lock:
            stopped = not self._running
            if not stopped:
                self._subs = subs
        if stopped:
            # stop() raced start(): release the fresh subscriptions
            # instead of leaking them on a dead shape
            for s in subs:
                s.close()
            return
        self._sync_health_subs()
        self._thread = threading.Thread(
            target=self._follow, daemon=True,
            name=f"proxycfg-{self.name}")
        self._thread.start()

    def stop(self) -> None:
        """Idempotent, callable from any thread (a degenerate call
        from the follow thread itself skips the self-join), and safe
        mid-`_rebuild`: the in-flight rebuild finishes against closed
        subscriptions and the loop exits on its next `_running`
        check.  Parked projections are notified so their fetches
        return promptly instead of waiting out the poll."""
        with self._lock:
            self._running = False
            self._cond.notify_all()
            subs = list(self._subs) + list(self._health_subs.values())
            self._subs = []
            self._health_subs = {}
        self._wake.set()         # unpark the follow loop immediately
        for s in subs:
            s.close()
        t = self._thread
        self._thread = None
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5.0)

    def _sync_health_subs(self) -> None:
        """Re-key per-service health subscriptions to the shape's
        CURRENT bound services (bindings change with its config entry;
        a stale watch set would miss new services or churn on dropped
        ones).  Runs in whichever thread just rebuilt; sub churn
        happens under the shape lock so a concurrent stop() can't
        leak a freshly created subscription."""
        kind = self.kind
        if kind not in ("ingress-gateway", "terminating-gateway",
                        "connect-proxy"):
            return
        with self._lock:
            build = self._build
        if kind == "connect-proxy":
            # chain split/failover targets beyond the upstreams already
            # watched at start(): their health moves chain_endpoints
            from consul_tpu import discoverychain as dchain
            direct = {up.get("destination_name", "")
                      for up in (build["upstreams"] if build else [])}
            want = set()
            for chain in (build["chains"] if build else {}).values():
                want |= set(dchain.chain_target_services(chain))
            want -= direct
        else:
            want = {row["Service"] for row in
                    (build["gateway_services"] if build is not None
                     else [])}
            if kind == "ingress-gateway":
                # chain split/failover targets of bound services
                from consul_tpu import discoverychain as dchain
                for chain in (build["chains"] if build else {}).values():
                    want |= set(dchain.chain_target_services(chain))
        pub = self.manager.store.publisher
        drop = []
        with self._lock:
            if not self._running:
                return
            for svc in list(self._health_subs):
                if svc not in want:
                    drop.append(self._health_subs.pop(svc))
            for svc in want - set(self._health_subs):
                s = pub.subscribe("health", svc, since_index=None)
                s.attach_wake(self._wake)
                self._health_subs[svc] = s
        for s in drop:
            s.close()

    def _follow(self) -> None:
        from consul_tpu.stream.publisher import SnapshotRequired
        while True:
            with self._lock:
                if not self._running:
                    return
                watched = list(self._subs) + \
                    list(self._health_subs.values())
            fired = False
            # clear-then-drain: a publish landing on ANY sub after its
            # drain below re-sets the shared wake, so the park at the
            # bottom returns immediately — no lost-wakeup window
            self._wake.clear()
            # the rebuild TRIGGER: the max-index drained event carries
            # the writer's trace id (stream Event.trace_id) — the
            # rebuild it causes inherits that correlation (ISSUE 16)
            trigger: Optional[Tuple[int, str]] = None
            for s in watched:
                try:
                    # non-blocking drain of the whole watch set; the
                    # shared wake (attached to every sub) replaces
                    # per-sub blocking.  Serial per-sub timeouts would
                    # stack (0.2s × topic count) onto commit-to-push
                    # visibility for events landing on later subs —
                    # measured at ~1.3s before the xds_bench existed
                    evs = s.events(timeout=0.0)
                except SnapshotRequired:
                    with self._lock:
                        if not self._running:
                            return
                    fired = True
                    continue
                if evs:
                    fired = True
                    for ev in evs:
                        idx = getattr(ev, "index", 0) or 0
                        if trigger is None or idx >= trigger[0]:
                            trigger = (idx,
                                       getattr(ev, "trace_id", "")
                                       or "")
            if not fired:
                # nothing buffered anywhere: park on the shared wake.
                # Bounded so a missed set (none known) can't wedge the
                # shape; stop() sets it for an immediate exit.
                self._wake.wait(timeout=0.5)
                continue
            with self._lock:
                if not self._running:
                    return
            try:
                self._rebuild(trigger)
            except Exception:
                # a transient failure (CSR rate pressure, store
                # contention) must not kill the follow thread and
                # freeze this shape's build forever; the next
                # event retries
                logging.getLogger("consul_tpu.proxycfg").warning(
                    "shape %s rebuild failed; will retry",
                    self.name, exc_info=True)

    def _connect_endpoints(self, name: str,
                           target: Optional[dict] = None) -> List[dict]:
        """Mesh-reachable endpoints for upstream `name`: the healthy
        sidecar PROXIES fronting it (health connect semantics — the
        reference's UpstreamEndpoints point at proxies, not apps);
        Connect-native services with no proxy fall back to their own
        instances.

        A chain `target` carrying a Subset applies the subset's bexpr
        filter + only_passing (ServiceResolverSubset).  The filter
        evaluates against the APP instance a sidecar fronts (the row's
        attached `app` record; the instance itself for proxy-less
        services) and the match maps to the sidecar's address — the
        reference's CheckConnectServiceNodes semantics
        (agent/consul/state/catalog.go)."""
        rows = self.manager.store.health_connect_nodes(name)
        native = not rows
        if native:
            rows = self.manager.store.health_service_nodes(name)
        rows = self._subset_filter(rows, target)
        eps = []
        for r in rows:
            if any(c["status"] == "critical" for c in r["checks"]):
                continue
            s = r["service"]
            eps.append({"address": s.get("service_address")
                        or s.get("address", ""),
                        "port": s.get("port", 0),
                        "node": s.get("node", "")})
        # proxies exist for this service: all-unhealthy means NO
        # endpoint, never a silent downgrade to the plaintext app
        # ports (a TLS hello at the app would just confuse it)
        return eps

    @staticmethod
    def _subset_filter(rows: List[dict],
                       target: Optional[dict]) -> List[dict]:
        if not target or not target.get("Subset"):
            return rows
        if target.get("OnlyPassing"):
            rows = [r for r in rows
                    if all(c["status"] == "passing"
                           for c in r["checks"])]
        expr = target.get("Filter") or ""
        if not expr:
            return rows
        from consul_tpu.bexpr import BexprError, compile_filter
        try:
            flt = compile_filter(expr)
        except BexprError:
            return []     # a broken subset filter selects nothing
        out = []
        for r in rows:
            s = r["service"]
            # sidecar rows filter against the APP instance they front
            # (connect_service_nodes attaches it): the reference's
            # CheckConnectServiceNodes evaluates actual service
            # instances and maps to their sidecars — a deployment that
            # tags apps but not sidecars must still subset correctly
            app = s.get("app")
            src = app if app is not None else s
            shaped = {"Service": {"Meta": src.get("meta", {}),
                                  "Tags": src.get("tags", []),
                                  "ID": (src.get("id", "")
                                         if app is not None else
                                         s.get("service_id", "")),
                                  "Service": src.get("service_name",
                                                     ""),
                                  "Port": src.get("port", 0)},
                      "Node": s.get("node", "")}
            try:
                if flt(shaped):
                    out.append(r)
            except BexprError:
                continue
        return out

    def _healthy_endpoints(self, name: str,
                           target: Optional[dict] = None) -> List[dict]:
        rows = self.manager.store.health_service_nodes(name)
        rows = self._subset_filter(rows, target)
        eps = []
        for r in rows:
            if any(c["status"] == "critical" for c in r["checks"]):
                continue
            s = r["service"]
            eps.append({"address": s.get("service_address")
                        or s.get("address", ""),
                        "port": s.get("port", 0),
                        "node": s.get("node", "")})
        return eps

    def _rebuild(self, trigger: Optional[Tuple[int, str]] = None) -> None:
        t0 = time.time()
        kind = self.kind
        if kind in ("mesh-gateway", "ingress-gateway",
                    "terminating-gateway"):
            build = self._build_gateway(kind)
        else:
            build = self._build_connect_proxy()
        index, tid = trigger if trigger is not None else (0, "")
        build["store_index"], build["trace_id"] = index, tid
        with self._cond:
            self._version += 1
            build["version"] = self._version
            self._build = build
            self._cond.notify_all()
        self._sync_health_subs()
        dur_ms = (time.time() - t0) * 1000.0
        with self._lock:
            self._rebuild_ms.append(dur_ms)
            self._rebuilds += 1
            self._last_rebuild_ts = time.time()
            version = self._version
        # SLI emission strictly AFTER every proxycfg lock release —
        # staged like raft's _metrics_buf; stage_xds takes only the
        # visibility table's own lock.  ONE rebuild row per shape
        # materialization, however many proxies project it — that is
        # the honest accounting the fan-out sweep judges.
        from consul_tpu import flight, telemetry
        telemetry.incr_counter(("xds", "rebuilds"), 1,
                               labels={"kind": kind})
        flight.emit("xds.rebuild",
                    labels={"proxy": self.name, "kind": kind,
                            "version": version, "index": index},
                    trace_id=tid or None)
        if index:
            vis = getattr(self.manager.store, "visibility", None)
            if vis is not None:
                vis.stage_xds("rebuild", index, kind, self.name)

    def _build_connect_proxy(self) -> dict:
        from consul_tpu import discoverychain as dchain
        from consul_tpu import servicemgr
        m = self.manager
        raw_proxy = self.svc.get("proxy") or {}
        service = self.key[1]
        # ServiceManager merge: central proxy-defaults/service-defaults
        # land in every snapshot (mode, expose, transparent_proxy,
        # config) with the registration winning — the ("config", None)
        # watch already rebuilds on central-entry changes
        proxy = servicemgr.merged_proxy(m.store, raw_proxy, service)
        upstreams = proxy.get("upstreams") or []
        endpoints = {up.get("destination_name", ""):
                     self._connect_endpoints(
                         up.get("destination_name", ""))
                     for up in upstreams}
        # compile each upstream's discovery chain and resolve endpoints
        # per chain TARGET (proxycfg/state.go watches discovery-chain +
        # per-target health; here both read the same store snapshot)
        chains: Dict[str, dict] = {}
        chain_eps: Dict[str, List[dict]] = {}
        for up in upstreams:
            name = up.get("destination_name", "")
            chain = dchain.compile_chain(m.store, name, dc=m.dc)
            chains[name] = chain
            for tid, tgt in chain["Targets"].items():
                if tid in chain_eps:
                    continue
                if tgt["Datacenter"] != m.dc:
                    # cross-dc target: route via the remote DC's mesh
                    # gateways from federation state (the reference's
                    # mesh-gateway failover path); absent federation,
                    # the target resolves empty rather than wrong
                    chain_eps[tid] = self._remote_dc_endpoints(
                        tgt["Datacenter"])
                else:
                    chain_eps[tid] = self._connect_endpoints(
                        tgt["Service"], target=tgt)
        relevant = imod.match_order(m.store.intention_list(), service,
                                    "destination")
        return {
            "kind": "connect-proxy", "service": service,
            "upstreams": upstreams, "roots": m.ca.roots(),
            "upstream_endpoints": endpoints, "intentions": relevant,
            "default_allow": m.default_allow,
            "gateway_services": [], "service_leaves": {},
            "mesh_endpoints": {}, "federation_states": [],
            "listeners": [],
            "chains": chains, "chain_endpoints": chain_eps,
            "expose": proxy.get("expose") or {},
            "mode": proxy.get("mode", ""),
            "transparent_proxy": proxy.get("transparent_proxy") or {},
            "opaque_config": proxy.get("config") or {},
            "local_port_default": proxy.get("local_service_port", 0),
        }

    def _remote_dc_endpoints(self, dc: str) -> List[dict]:
        for f in self.manager.store.federation_state_list():
            if f["datacenter"] == dc:
                return [{"address": g.get("address", ""),
                         "port": g.get("port", 0), "node": ""}
                        for g in f.get("mesh_gateways", [])]
        return []

    def _build_gateway(self, kind: str) -> dict:
        """Per-kind gateway build (proxycfg/state.go
        initialize/handleUpdate for MeshGateway / TerminatingGateway /
        IngressGateway)."""
        from consul_tpu import gateways as gmod
        m = self.manager
        gw_name = self.key[1]
        endpoints: Dict[str, List[dict]] = {}
        bound: List[dict] = []
        service_leaves: Dict[str, dict] = {}
        mesh_endpoints: Dict[str, List[dict]] = {}
        federation: List[dict] = []
        listeners: List[dict] = []
        intentions: List[dict] = []
        gw_chains: Dict[str, dict] = {}
        gw_chain_eps: Dict[str, List[dict]] = {}
        if kind == "mesh-gateway":
            # every local connect-capable service is routable through
            # the mesh gateway by SNI; remote DCs resolve through their
            # federation-state gateway lists (state.go mesh-gw watches).
            # One locked table pass — this rebuild runs on every health
            # event, so per-name scans would be quadratic under churn
            mesh_endpoints = m.store.healthy_plain_endpoints()
            federation = [f for f in m.store.federation_state_list()
                          if f["datacenter"] != m.dc]
        elif kind == "terminating-gateway":
            from consul_tpu import discoverychain as dchain
            bound = gmod.resolve_wildcard(
                m.store, gmod.gateway_services(m.store, gw_name))
            # ONE intention-table pass for all bound services — this
            # rebuild fires on every config write (same hoist rationale
            # as the mesh-gateway branch)
            all_intentions = m.store.intention_list()
            for row in bound:
                svc = row["Service"]
                endpoints[svc] = self._healthy_endpoints(svc)
                # the terminating gateway presents a mesh identity for
                # each service it fronts (leader_connect_ca leaf per
                # GatewayService)
                service_leaves[svc] = m.get_leaf(svc)
                intentions += imod.match_order(
                    all_intentions, svc, "destination")
                # the chain carries the service's protocol + resolver
                # LB, which decide http-vs-tcp filter chains and route
                # emission (TerminatingGateway.ServiceResolvers role)
                gw_chains[svc] = dchain.compile_chain(m.store, svc,
                                                      dc=m.dc)
        elif kind == "ingress-gateway":
            from consul_tpu import discoverychain as dchain
            ent = m.store.config_entry_get("ingress-gateway", gw_name)
            listeners = (ent.get("listeners") or []) if ent else []
            bound = gmod.resolve_wildcard(
                m.store, gmod.gateway_services(m.store, gw_name))
            for row in bound:
                svc = row["Service"]
                # one row per (service, port): a service bound to N
                # listeners must not recompile/rescan N times
                if svc in gw_chains:
                    continue
                endpoints[svc] = self._healthy_endpoints(svc)
                # bound services with L7 chains route through the
                # chain's targets (IngressGateway.DiscoveryChain role)
                chain = dchain.compile_chain(m.store, svc, dc=m.dc)
                gw_chains[svc] = chain
                for tid, tgt in chain["Targets"].items():
                    if tid in gw_chain_eps:
                        continue
                    if tgt["Datacenter"] != m.dc:
                        gw_chain_eps[tid] = \
                            self._remote_dc_endpoints(
                                tgt["Datacenter"])
                    else:
                        gw_chain_eps[tid] = self._healthy_endpoints(
                            tgt["Service"], target=tgt)
        return {
            "kind": kind, "service": gw_name, "upstreams": [],
            "roots": m.ca.roots(), "upstream_endpoints": endpoints,
            "intentions": intentions, "default_allow": m.default_allow,
            "gateway_services": bound, "service_leaves": service_leaves,
            "mesh_endpoints": mesh_endpoints,
            "federation_states": federation, "listeners": listeners,
            "chains": gw_chains, "chain_endpoints": gw_chain_eps,
            "expose": {}, "mode": "", "transparent_proxy": {},
            "opaque_config": {}, "local_port_default": 0,
        }

    def stats(self) -> dict:
        """Shape-level slice of the per-proxy stats row."""
        with self._lock:
            ms = sorted(self._rebuild_ms)
            rebuilds = self._rebuilds
            last_rebuild = self._last_rebuild_ts
            refs = 0

        def _pctl(q: float) -> float:
            if not ms:
                return 0.0
            return round(ms[min(len(ms) - 1,
                                max(0, int(q * len(ms))))], 3)

        return {"rebuilds": rebuilds,
                "rebuild_ms": {"p50": _pctl(0.5), "p99": _pctl(0.99)},
                "last_rebuild_ts": last_rebuild, "refs": refs}


class _ShapeEntry:
    """One shared shape slot: the SharedShape once ready, the
    single-flight gate concurrent requesters park on, the attach
    refcount last-disconnect eviction judges, and the tombstone flag
    closing the attach/evict race."""

    __slots__ = ("key", "shape", "ready", "error", "refs", "dead")

    def __init__(self, key: Tuple[str, str, str]):
        self.key = key
        self.shape: Optional[SharedShape] = None
        self.ready = threading.Event()
        self.error: Optional[BaseException] = None
        self.refs = 0
        self.dead = False


class ProxyState:
    """Cheap per-proxy projection over a SharedShape
    (proxycfg/state.go's per-proxy surface): overlays proxy id, leaf,
    and bind ports on the shared build, serves version-keyed blocking
    fetches, and keeps the per-proxy push clocks the UI table and the
    visibility plane read."""

    def __init__(self, manager: "Manager", proxy_id: str, svc: dict,
                 start_version: int = 0):
        self.manager = manager
        self.proxy_id = proxy_id
        self.svc = svc
        self.kind = svc.get("kind", "connect-proxy")
        self._lock = locks.make_lock("proxycfg.state")
        self._snapshot: Optional[ConfigSnapshot] = None  # guarded-by: _lock
        self._snap_shape_v = 0                           # guarded-by: _lock
        self._projections = 0                            # guarded-by: _lock
        self._pushes = 0                                 # guarded-by: _lock
        self._last_push_ts = 0.0                         # guarded-by: _lock
        # versions survive state replacement: a long-poller parked on
        # version N must see N+1 from the REPLACED state, not a restart
        # at 1 it would read as no-change.  Per-proxy version =
        # shape_version + _offset, fixed at attach time.
        self._base = start_version
        self._offset = 0
        self._shape: Optional[SharedShape] = None
        self._ent: Optional[_ShapeEntry] = None
        # terminal marker (dereg / replacement): self-synchronized
        # Event so fetchers parked on the SHAPE's condition can read it
        # without taking this state's lock
        self._stop_event = threading.Event()
        self._stop_lock = threading.Lock()
        self._stopped = False                # guarded-by: _stop_lock
        locks.register_guards(self, self._lock, "_snapshot",
                              "_snap_shape_v", "_projections",
                              "_pushes", "_last_push_ts")

    def start(self) -> None:
        ent = self.manager._attach_shape(self.svc)
        sh = ent.shape
        with sh._lock:
            shape_v0 = sh._version
        self._ent = ent
        self._shape = sh
        # first projected version must exceed everything the previous
        # incarnation served: current(v) = v + offset maps the shape's
        # CURRENT build to base+1
        self._offset = self._base + 1 - shape_v0

    def alive(self) -> bool:
        """False once deregistered or replaced — the terminal signal
        the xDS frontends turn into a prompt terminal answer instead
        of letting a parked long-poll wait out its timeout."""
        return not self._stop_event.is_set()

    def stop(self) -> None:
        """Idempotent: marks the state terminal, wakes every fetcher
        parked on the shared shape, and drops the shape refcount (last
        disconnect evicts the shape and its subscription set)."""
        with self._stop_lock:
            if self._stopped:
                return
            self._stopped = True
        self._stop_event.set()
        sh, ent = self._shape, self._ent
        if sh is not None:
            with sh._cond:
                sh._cond.notify_all()
        if ent is not None:
            self.manager._detach_shape(ent)

    def current_version(self) -> int:
        sh = self._shape
        if sh is None:
            return self._base
        with sh._lock:
            v = sh._version
        return v + self._offset

    def fetch(self, min_version: int = 0,
              timeout: float = 300.0) -> Optional[ConfigSnapshot]:
        """Blocking per-proxy read: parks on the SHARED shape's
        condition until the shape's build projects to a per-proxy
        version > min_version, the deadline passes, or the state turns
        terminal (dereg mid-long-poll returns promptly).  The
        projection itself happens outside the shape lock — N proxies
        of one shape share the park, not the overlay."""
        sh = self._shape
        if sh is None:
            with self._lock:
                return self._snapshot
        deadline = time.time() + timeout
        with sh._cond:
            sh._inflight += 1
            try:
                while (sh._version + self._offset <= min_version
                       and sh._running
                       and not self._stop_event.is_set()):
                    remaining = deadline - time.time()
                    if remaining <= 0:
                        break
                    sh._cond.wait(remaining)
                build = self._build_ref(sh)
                shape_v = sh._version
            finally:
                sh._inflight -= 1
        if build is None:
            with self._lock:
                return self._snapshot
        return self._project(build, shape_v)

    @staticmethod
    def _build_ref(sh: SharedShape) -> Optional[dict]:
        # requires-lock: sh._lock
        return sh._build

    def _project(self, build: dict, shape_v: int) -> ConfigSnapshot:
        """The cheap per-proxy overlay (ISSUE 19): shared references
        for everything shape-level, fresh per-proxy leaf + identity +
        bind surface.  Cached per shape version so concurrent fetchers
        of one proxy share ONE snapshot object (the push_emitted
        once-per-snapshot contract and the gRPC payload cache key on
        object identity)."""
        with self._lock:
            snap = self._snapshot
            if snap is not None and self._snap_shape_v >= shape_v:
                return snap
        m = self.manager
        raw_proxy = self.svc.get("proxy") or {}
        leaf = m.get_leaf(build["service"])
        snap = ConfigSnapshot(
            proxy_id=self.proxy_id, service=build["service"],
            upstreams=build["upstreams"], roots=build["roots"],
            leaf=leaf,
            upstream_endpoints=build["upstream_endpoints"],
            intentions=build["intentions"],
            default_allow=build["default_allow"],
            version=shape_v + self._offset, kind=build["kind"],
            gateway_services=build["gateway_services"],
            service_leaves=build["service_leaves"],
            mesh_endpoints=build["mesh_endpoints"],
            federation_states=build["federation_states"],
            listeners=build["listeners"],
            port=self.svc.get("port", 0),
            bind_address=self.svc.get("address", ""),
            local_port=raw_proxy.get("local_service_port")
            or build["local_port_default"],
            chains=build["chains"],
            chain_endpoints=build["chain_endpoints"],
            expose=build["expose"], mode=build["mode"],
            transparent_proxy=build["transparent_proxy"],
            opaque_config=build["opaque_config"])
        snap.store_index = build["store_index"]
        snap.trace_id = build["trace_id"]
        with self._lock:
            if self._snapshot is None or self._snap_shape_v < shape_v:
                self._snapshot = snap
                self._snap_shape_v = shape_v
                self._projections += 1
            return self._snapshot

    def note_push(self, snap: Optional[ConfigSnapshot]) -> None:
        """Push-site bookkeeping, called by the ADS stream / HTTP
        long-poll AFTER the response left this process: stamps the
        per-proxy push clock and emits the apply->push visibility
        stage once per snapshot (the first transport to deliver it
        wins; stage_xds runs off every proxycfg lock)."""
        emit_stage = False
        with self._lock:
            self._pushes += 1
            self._last_push_ts = time.time()
            if snap is not None and not snap.push_emitted \
                    and snap.store_index:
                snap.push_emitted = True
                emit_stage = True
        if not emit_stage:
            return
        vis = getattr(self.manager.store, "visibility", None)
        if vis is not None:
            vis.stage_xds("push", snap.store_index, snap.kind,
                          self.proxy_id)

    def stats(self, now: Optional[float] = None) -> dict:
        """One per-proxy row of the /v1/internal/ui/xds table.
        Rebuild cost/counters come from the SHARED shape (the honest
        materialization accounting); pushes/projections stay
        per-proxy."""
        now = time.time() if now is None else now
        with self._lock:
            snap = self._snapshot
            pushes = self._pushes
            projections = self._projections
            last_push = self._last_push_ts
        sh = self._shape
        shape_row = sh.stats() if sh is not None else {
            "rebuilds": 0, "rebuild_ms": {"p50": 0.0, "p99": 0.0},
            "last_rebuild_ts": 0.0}
        last_rebuild = shape_row["last_rebuild_ts"]
        return {
            "proxy_id": self.proxy_id,
            "kind": self.kind,
            "service": (snap.service if snap is not None
                        else self.svc.get("name", "")),
            "version": self.current_version(),
            "store_index": (snap.store_index if snap is not None
                            else 0),
            "shape": "/".join(self._ent.key) if self._ent is not None
                     else "",
            "rebuilds": shape_row["rebuilds"],
            "projections": projections,
            "pushes": pushes,
            "rebuild_ms": shape_row["rebuild_ms"],
            "last_rebuild_age_s": (round(now - last_rebuild, 3)
                                   if last_rebuild else None),
            "last_push_age_s": (round(now - last_push, 3)
                                if last_push else None),
        }


class Manager:
    """Proxy registry (proxycfg.Manager): one ProxyState per registered
    sidecar, created lazily from the catalog's connect-proxy services,
    projecting from single-flight SharedShapes keyed on
    (kind, service, config-hash)."""

    # single-flight wait bound: a wedged shape creator must surface as
    # an error to its waiters, not park them forever
    SHAPE_TIMEOUT = 30.0

    def __init__(self, store, ca, default_allow: bool = True,
                 dc: Optional[str] = None):
        self.store = store
        self.ca = ca
        self.dc = dc or getattr(ca, "dc", "dc1")
        self.default_allow = default_allow
        self._leaf_lock = locks.make_lock("proxycfg.leaves")
        # svc -> (root_id, leaf, refresh_deadline)  # guarded-by: _leaf_lock
        self._leaves: Dict[str, Tuple[str, dict, float]] = {}
        self._lock = locks.make_lock("proxycfg.manager")
        self._states: Dict[str, ProxyState] = {}    # guarded-by: _lock
        # the shared-shape registry; held for dict ops ONLY, never
        # across a materialization (ViewStore discipline — requesters
        # for OTHER shapes never wait behind a slow rebuild)
        self._shape_lock = locks.make_lock("proxycfg.shapes")
        self._shapes: Dict[Tuple[str, str, str], _ShapeEntry] = {}  # guarded-by: _shape_lock
        # dereg reaper: one ("services") subscription that revalidates
        # live states so a deregistered proxy's parked long-polls get
        # their terminal answer promptly (ISSUE 19 satellite)
        self._reap_stop = threading.Event()
        self._reap_wake = threading.Event()
        self._reap_thread: Optional[threading.Thread] = None
        locks.register_guards(self, self._leaf_lock, "_leaves")
        locks.register_guards(self, self._lock, "_states")
        locks.register_guards(self, self._shape_lock, "_shapes")

    # ------------------------------------------------------------- leaves

    def get_leaf(self, service: str) -> dict:
        """Cached leaf, re-signed when missing, when the active root
        moved, or when the leaf nears expiry (an agent outliving the
        72h leaf TTL must not serve expired certs)."""
        active = self.ca.active.id
        now = time.time()
        with self._leaf_lock:
            hit = self._leaves.get(service)
            if hit is not None and hit[0] == active and now < hit[2]:
                return hit[1]
            from consul_tpu.connect.ca import CARateLimitError
            try:
                leaf = self.ca.sign_leaf(service)
            except CARateLimitError:
                if hit is not None and self._leaf_still_valid(hit[1]):
                    # serve the stale-but-VALID leaf under CSR
                    # pressure rather than failing the snapshot
                    # (the reference's leaf cache behaves the same);
                    # an expired cert would just move the failure to
                    # every handshake
                    return hit[1]
                raise
            ttl_s = self.ca.leaf_ttl_hours * 3600.0
            refresh_at = now + ttl_s * _LEAF_REFRESH_FRACTION
            self._leaves[service] = (active, leaf, refresh_at)
            return leaf

    @staticmethod
    def _leaf_still_valid(leaf: dict) -> bool:
        import datetime
        from consul_tpu.connect import ca as camod
        now = datetime.datetime.now(datetime.timezone.utc)
        if not camod.HAVE_CRYPTOGRAPHY:
            try:
                payload = camod._stub_payload(leaf["CertPEM"])
            except Exception:
                return False
            return payload.get("not_after", 0.0) > now.timestamp()
        from cryptography import x509
        try:
            cert = x509.load_pem_x509_certificate(
                leaf["CertPEM"].encode())
        except Exception:
            return False
        return cert.not_valid_after_utc > now

    # ------------------------------------------------------------- shapes

    def _attach_shape(self, svc: dict) -> _ShapeEntry:
        """Acquire + pin the shape for a registration (single-flight):
        the first requester materializes, concurrent requesters for
        the SAME key park on the entry gate, requesters for other keys
        never wait behind it.  The returned entry holds one reference
        for the caller; `_detach_shape` releases it."""
        from consul_tpu import telemetry
        key = shape_key(svc)
        for _ in range(8):
            creator = False
            with self._shape_lock:
                ent = self._shapes.get(key)
                if ent is None:
                    ent = _ShapeEntry(key)
                    self._shapes[key] = ent
                    creator = True
            telemetry.incr_counter(
                ("cache", "miss" if creator else "hit"),
                labels={"type": f"shape:{key[0]}"})
            if creator:
                sh = SharedShape(self, key, svc)
                try:
                    sh.start()
                except BaseException as e:
                    # a failed materialization must release its
                    # waiters AND vacate the slot so the next
                    # requester retries fresh
                    with self._shape_lock:
                        ent.error = e
                        ent.dead = True
                        if self._shapes.get(key) is ent:
                            del self._shapes[key]
                    ent.ready.set()
                    raise
                with self._shape_lock:
                    ent.shape = sh
                    ent.refs += 1       # the creator's pin
                ent.ready.set()
                return ent
            if not ent.ready.wait(self.SHAPE_TIMEOUT):
                raise RuntimeError(
                    f"shape {key} materialization timed out")
            with self._shape_lock:
                if ent.shape is not None and not ent.dead \
                        and self._shapes.get(key) is ent:
                    ent.refs += 1
                    return ent
            if ent.error is not None:
                raise RuntimeError(
                    f"shape {key} creation failed: {ent.error}")
            # evicted between ready and pin (last-disconnect race):
            # retry against a fresh slot
        raise RuntimeError(f"shape {key} attach retry budget exhausted")

    def _detach_shape(self, ent: _ShapeEntry) -> None:
        """Release one pin; the LAST disconnect evicts the shape and
        its whole subscription set (the reference refcounts proxycfg
        watches the same way).  The stop runs outside the registry
        lock so eviction never stalls unrelated attaches."""
        dead = None
        with self._shape_lock:
            ent.refs -= 1
            if ent.refs <= 0 and ent.shape is not None \
                    and not ent.dead:
                ent.dead = True
                if self._shapes.get(ent.key) is ent:
                    del self._shapes[ent.key]
                dead = ent.shape
        if dead is not None:
            dead.stop()

    def shape_stats(self) -> dict:
        """Live shape-registry shape (tests + /v1/internal/ui/xds
        summary): distinct shapes, total pins, per-shape rows."""
        with self._shape_lock:
            ents = [(e.key, e.refs, e.shape)
                    for e in self._shapes.values()]
        rows = []
        inflight = 0
        for key, refs, sh in ents:
            if sh is None:
                continue
            with sh._lock:
                rebuilds = sh._rebuilds
                inflight += sh._inflight
            rows.append({"shape": "/".join(key), "refs": refs,
                         "rebuilds": rebuilds})
        rows.sort(key=lambda r: r["shape"])
        return {"shapes": len(rows),
                "pinned": sum(r["refs"] for r in rows),
                "inflight": inflight, "rows": rows}

    # -------------------------------------------------------------- reaper

    def _ensure_reaper(self) -> None:
        if self._reap_thread is not None:
            return
        try:
            sub = self.store.publisher.subscribe("services", None,
                                                 since_index=None)
        except Exception:
            return
        sub.attach_wake(self._reap_wake)
        self._reap_thread = threading.Thread(
            target=self._reap_loop, args=(sub,), daemon=True,
            name="proxycfg-reaper")
        self._reap_thread.start()

    def _reap_loop(self, sub) -> None:
        """Catalog-churn reaper: any services-topic event revalidates
        every live state so a DEREGISTERED proxy's state stops (its
        parked long-polls return terminally and its shape pin drops)
        without waiting for the next watch() call."""
        from consul_tpu.stream.publisher import SnapshotRequired
        try:
            while not self._reap_stop.is_set():
                self._reap_wake.clear()
                try:
                    evs = sub.events(timeout=0.0)
                except SnapshotRequired:
                    evs = [True]
                if not evs:
                    self._reap_wake.wait(timeout=0.5)
                    continue
                with self._lock:
                    pids = list(self._states)
                for pid in pids:
                    if self._reap_stop.is_set():
                        return
                    if self._find_proxy(pid) is not None:
                        continue
                    with self._lock:
                        st = self._states.pop(pid, None)
                    if st is not None:
                        st.stop()
        finally:
            sub.close()

    # --------------------------------------------------------------- watch

    def watch(self, proxy_id: str) -> Optional[ProxyState]:
        """ProxyState for a registered connect-proxy service id
        (Manager.Watch :303); None when no such proxy exists.  The
        catalog is revalidated on every call: a re-registration with a
        changed proxy config replaces the state (new shape pin), a
        deregistered proxy drops it.  The registry lock is held for
        dict ops only — building a replacement (which may materialize
        a new shape) never serializes unrelated watch() calls."""
        svc = self._find_proxy(proxy_id)
        old = None
        with self._lock:
            st = self._states.get(proxy_id)
            if svc is None:
                if st is not None:
                    del self._states[proxy_id]
                    old = st
            elif st is not None and st.svc.get("modify_index") == \
                    svc.get("modify_index"):
                return st
            else:
                old = st
        if svc is None:
            if old is not None:
                old.stop()
            return None
        self._ensure_reaper()
        start_version = old.current_version() if old is not None else 0
        if old is not None:
            old.stop()
        new = ProxyState(self, proxy_id, svc,
                         start_version=start_version)
        new.start()
        with self._lock:
            cur = self._states.get(proxy_id)
            if cur is not None and cur is not old and \
                    cur.svc.get("modify_index") == \
                    svc.get("modify_index"):
                loser, winner = new, cur    # a concurrent watch() won
            else:
                self._states[proxy_id] = new
                loser, winner = None, new
        if loser is not None:
            loser.stop()
        return winner

    def _find_proxy(self, proxy_id: str) -> Optional[dict]:
        s = self.store.service_by_id(proxy_id)
        if s is not None and s.get("kind") in (
                "connect-proxy", "mesh-gateway", "ingress-gateway",
                "terminating-gateway"):
            return s
        return None

    def close(self) -> None:
        """Stop every state (detaching its shape pin) and the reaper;
        any shape still pinned (a leaked ref) is stopped too.  States
        detach under the lock, the stops happen outside it so a slow
        in-flight rebuild can't wedge concurrent watch() calls behind
        the registry."""
        self._reap_stop.set()
        self._reap_wake.set()
        t = self._reap_thread
        if t is not None:
            t.join(timeout=5.0)
            self._reap_thread = None
        with self._lock:
            states = list(self._states.values())
            self._states.clear()
        for st in states:
            st.stop()
        with self._shape_lock:
            ents = list(self._shapes.values())
            self._shapes.clear()
        for e in ents:
            e.dead = True
            if e.shape is not None:
                e.shape.stop()

    def table(self) -> List[dict]:
        """The per-proxy mesh-control-plane table served at
        /v1/internal/ui/xds: one row per live ProxyState (kind,
        snapshot version, rebuild/push counters, rebuild p50/p99,
        last-activity ages), plus the consul.xds.proxies{kind} and
        consul.xds.shapes gauges — rows computed from a detached state
        list and gauges emitted off every proxycfg lock."""
        with self._lock:
            states = list(self._states.values())
        with self._shape_lock:
            n_shapes = len(self._shapes)
        now = time.time()
        rows = [st.stats(now) for st in states]
        rows.sort(key=lambda r: r["proxy_id"])
        from consul_tpu import telemetry
        kinds: Dict[str, int] = {}
        for r in rows:
            kinds[r["kind"]] = kinds.get(r["kind"], 0) + 1
        for kind, n in sorted(kinds.items()):
            telemetry.set_gauge(("xds", "proxies"), float(n),
                                labels={"kind": kind})
        telemetry.set_gauge(("xds", "shapes"), float(n_shapes))
        return rows
