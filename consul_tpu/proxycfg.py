"""proxycfg: per-proxy configuration snapshots for the mesh data plane.

The reference's proxycfg manager (agent/proxycfg/manager.go:38, Watch
:303, state machine state.go) assembles, per registered sidecar proxy, a
ConfigSnapshot from many watches — CA roots, the service leaf, upstream
health, intentions — and pushes a fresh snapshot to the xDS server on
every relevant change.  Here each snapshot rebuilds from materialized
sources when a relevant store event lands (health of an upstream,
intention change) or the CA rotates, and `watch()` serves blocking
fetches keyed by version, exactly the shape the xDS layer long-polls.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from consul_tpu import locks
from consul_tpu.connect import intentions as imod

# re-sign margin: leaves refresh well before their notAfter
_LEAF_REFRESH_FRACTION = 0.75


class ConfigSnapshot:
    """One proxy's full mesh view (proxycfg.ConfigSnapshot).

    `kind` selects the per-kind extras (proxycfg's
    configSnapshotConnectProxy / MeshGateway / TerminatingGateway /
    IngressGateway unions):
      mesh-gateway:        mesh_endpoints (local svc -> endpoints),
                           federation_states (remote dc -> gateways)
      terminating-gateway: gateway_services rows + per-service leaves
      ingress-gateway:     listeners from the config entry
    """

    def __init__(self, proxy_id: str, service: str, upstreams: List[dict],
                 roots: List[dict], leaf: dict,
                 upstream_endpoints: Dict[str, List[dict]],
                 intentions: List[dict], default_allow: bool,
                 version: int, kind: str = "connect-proxy",
                 gateway_services: Optional[List[dict]] = None,
                 service_leaves: Optional[Dict[str, dict]] = None,
                 mesh_endpoints: Optional[Dict[str, List[dict]]] = None,
                 federation_states: Optional[List[dict]] = None,
                 listeners: Optional[List[dict]] = None,
                 port: int = 0, bind_address: str = "",
                 local_port: int = 0,
                 chains: Optional[Dict[str, dict]] = None,
                 chain_endpoints: Optional[Dict[str, List[dict]]] = None,
                 expose: Optional[dict] = None, mode: str = "",
                 transparent_proxy: Optional[dict] = None,
                 opaque_config: Optional[dict] = None):
        self.proxy_id = proxy_id
        self.service = service
        self.upstreams = upstreams
        self.roots = roots
        self.leaf = leaf
        self.upstream_endpoints = upstream_endpoints
        self.intentions = intentions
        self.default_allow = default_allow
        self.version = version
        self.kind = kind
        self.gateway_services = gateway_services or []
        self.service_leaves = service_leaves or {}
        self.mesh_endpoints = mesh_endpoints or {}
        self.federation_states = federation_states or []
        self.listeners = listeners or []
        # bind surface of the proxy itself (registration port) and the
        # local app port behind it — Envoy listener addresses and the
        # local_app load assignment need real sockets to be valid
        self.port = port
        self.bind_address = bind_address
        self.local_port = local_port
        # discovery chains per upstream + endpoints per chain TARGET id
        # (proxycfg's ConfigSnapshotUpstreams DiscoveryChain /
        # WatchedUpstreamEndpoints)
        self.chains = chains or {}
        self.chain_endpoints = chain_endpoints or {}
        # operational proxy surface, already merged with central
        # defaults (structs.ConnectProxyConfig Expose / Mode /
        # TransparentProxy — agent/structs/connect_proxy_config.go:198,
        # config_entry.go:89)
        self.expose = expose or {}
        self.mode = mode
        self.transparent_proxy = transparent_proxy or {}
        # the registration's opaque Proxy.Config merged with central
        # proxy-defaults (xDS escape hatches live here —
        # agent/xds/config.go:28,34 envoy_public_listener_json /
        # envoy_local_cluster_json)
        self.opaque_config = opaque_config or {}
        # commit-to-push correlation (ISSUE 16): the store index and
        # writer trace id of the stream event that TRIGGERED this
        # rebuild (0/"" for the initial build — nothing to correlate),
        # plus a once-only marker the first push site to deliver this
        # snapshot flips (under the owning state's lock) so the
        # apply->push stage is sampled exactly once per snapshot
        self.store_index = 0
        self.trace_id = ""
        self.push_emitted = False


class ProxyState:
    """Watch set + rebuild loop for one proxy (proxycfg/state.go)."""

    def __init__(self, manager: "Manager", proxy_id: str, svc: dict,
                 start_version: int = 0):
        self.manager = manager
        self.proxy_id = proxy_id
        self.svc = svc
        self.kind = svc.get("kind", "connect-proxy")
        # one lock guards the whole per-proxy state; the condition is
        # built OVER it so `with self._cond:` and `with self._lock:`
        # are the same critical section (fetch parks on the condition,
        # everything else takes the lock directly)
        self._lock = locks.make_lock("proxycfg.state")
        self._cond = locks.make_condition(self._lock)
        self._snapshot: Optional[ConfigSnapshot] = None  # guarded-by: _lock
        # versions survive state replacement: a long-poller parked on
        # version N must see N+1 from the REPLACED state, not a restart
        # at 1 it would read as no-change  # guarded-by: _lock
        self._version = start_version
        self._subs: List[object] = []                    # guarded-by: _lock
        # ingress/terminating gateways: per-bound-service health subs,
        # resynced after each rebuild as bindings change
        self._health_subs: Dict[str, object] = {}        # guarded-by: _lock
        self._running = False                            # guarded-by: _lock
        self._thread: Optional[threading.Thread] = None
        # per-proxy SLI bookkeeping (ISSUE 16): rebuild-duration ring
        # (p50/p99 for the /v1/internal/ui/xds table), counters, and
        # last-activity clocks  # guarded-by: _lock
        self._rebuild_ms = deque(maxlen=128)
        self._rebuilds = 0                               # guarded-by: _lock
        # shared wakeup for the follow loop: attached to EVERY
        # subscription so one park covers the whole watch set (Event
        # is self-synchronized; not guarded)
        self._wake = threading.Event()
        self._pushes = 0                                 # guarded-by: _lock
        self._last_rebuild_ts = 0.0                      # guarded-by: _lock
        self._last_push_ts = 0.0                         # guarded-by: _lock
        locks.register_guards(self, self._lock, "_snapshot", "_version",
                              "_subs", "_health_subs", "_running",
                              "_rebuild_ms", "_rebuilds", "_pushes",
                              "_last_rebuild_ts", "_last_push_ts")

    def start(self) -> None:
        with self._lock:
            self._running = True
        self._rebuild()
        pub = self.manager.store.publisher
        proxy = self.svc.get("proxy") or {}
        kind = self.kind
        # CA topic included: a root rotation must rebuild every proxy
        # snapshot without waiting for unrelated churn
        topics = [("intentions", None), ("ca", None)]
        if kind == "connect-proxy":
            for up in proxy.get("upstreams") or []:
                topics.append(("health", up.get("destination_name", "")))
            # router/splitter/resolver entries reshape the chain; the
            # chain's split/failover TARGET services get per-service
            # health subs via _sync_health_subs after each rebuild.
            # federation: cross-dc failover targets resolve through the
            # remote DC's mesh gateways, so gateway address changes
            # must rebuild chain_endpoints too
            topics.append(("config", None))
            topics.append(("federation", None))
        elif kind == "mesh-gateway":
            # a mesh gateway genuinely fronts every local service and
            # every remote DC: topic-wide health + federation watches
            # are its real dependency set (proxycfg/state.go mesh-gw)
            topics += [("config", None), ("health", None),
                       ("federation", None)]
        elif kind == "ingress-gateway":
            # ingress consumes bound services' DISCOVERY CHAINS, so any
            # router/splitter/resolver write must rebuild — topic-wide
            # config sub (plus services for wildcard binding changes,
            # and federation because cross-dc failover targets resolve
            # through remote mesh gateways)
            topics += [("config", None), ("services", None),
                       ("federation", None)]
        else:
            # terminating: bound services' protocols (service-defaults)
            # and resolvers (LB) shape the filter chains, so config
            # writes anywhere must rebuild, like ingress; endpoint
            # health stays per bound service via _sync_health_subs
            topics += [("config", None), ("services", None)]
        subs = [pub.subscribe(t, k, since_index=None)
                for t, k in topics]
        for s in subs:
            s.attach_wake(self._wake)
        with self._lock:
            stopped = not self._running
            if not stopped:
                self._subs = subs
        if stopped:
            # stop() raced start(): release the fresh subscriptions
            # instead of leaking them on a dead state
            for s in subs:
                s.close()
            return
        self._sync_health_subs()
        self._thread = threading.Thread(
            target=self._follow, daemon=True,
            name=f"proxycfg-{self.proxy_id}")
        self._thread.start()

    def stop(self) -> None:
        """Idempotent, callable from any thread (a degenerate call
        from the follow thread itself skips the self-join), and safe
        mid-`_rebuild`: the in-flight rebuild finishes against closed
        subscriptions and the loop exits on its next `_running`
        check."""
        with self._lock:
            self._running = False
            # wake parked fetchers so they re-poll (and land on the
            # replacement state) instead of sleeping out their wait
            self._cond.notify_all()
            subs = list(self._subs) + list(self._health_subs.values())
            self._subs = []
            self._health_subs = {}
        self._wake.set()         # unpark the follow loop immediately
        for s in subs:
            s.close()
        t = self._thread
        self._thread = None
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5.0)

    def _sync_health_subs(self) -> None:
        """Re-key per-service health subscriptions to the gateway's
        CURRENT bound services (bindings change with its config entry;
        a stale watch set would miss new services or churn on dropped
        ones).  Runs in whichever thread just rebuilt; sub churn
        happens under the state lock so a concurrent stop() can't
        leak a freshly created subscription."""
        kind = self.kind
        if kind not in ("ingress-gateway", "terminating-gateway",
                        "connect-proxy"):
            return
        with self._lock:
            snap = self._snapshot
        if kind == "connect-proxy":
            # chain split/failover targets beyond the upstreams already
            # watched at start(): their health moves chain_endpoints
            from consul_tpu import discoverychain as dchain
            direct = {up.get("destination_name", "")
                      for up in (snap.upstreams if snap else [])}
            want = set()
            for chain in (snap.chains if snap else {}).values():
                want |= set(dchain.chain_target_services(chain))
            want -= direct
        else:
            want = {row["Service"] for row in
                    (snap.gateway_services if snap is not None else [])}
            if kind == "ingress-gateway":
                # chain split/failover targets of bound services
                from consul_tpu import discoverychain as dchain
                for chain in (snap.chains if snap else {}).values():
                    want |= set(dchain.chain_target_services(chain))
        pub = self.manager.store.publisher
        drop = []
        with self._lock:
            if not self._running:
                return
            for svc in list(self._health_subs):
                if svc not in want:
                    drop.append(self._health_subs.pop(svc))
            for svc in want - set(self._health_subs):
                s = pub.subscribe("health", svc, since_index=None)
                s.attach_wake(self._wake)
                self._health_subs[svc] = s
        for s in drop:
            s.close()

    def _follow(self) -> None:
        from consul_tpu.stream.publisher import SnapshotRequired
        while True:
            with self._lock:
                if not self._running:
                    return
                watched = list(self._subs) + \
                    list(self._health_subs.values())
            fired = False
            # clear-then-drain: a publish landing on ANY sub after its
            # drain below re-sets the shared wake, so the park at the
            # bottom returns immediately — no lost-wakeup window
            self._wake.clear()
            # the rebuild TRIGGER: the max-index drained event carries
            # the writer's trace id (stream Event.trace_id) — the
            # rebuild it causes inherits that correlation (ISSUE 16)
            trigger: Optional[Tuple[int, str]] = None
            for s in watched:
                try:
                    # non-blocking drain of the whole watch set; the
                    # shared wake (attached to every sub) replaces
                    # per-sub blocking.  Serial per-sub timeouts would
                    # stack (0.2s × topic count) onto commit-to-push
                    # visibility for events landing on later subs —
                    # measured at ~1.3s before the xds_bench existed
                    evs = s.events(timeout=0.0)
                except SnapshotRequired:
                    with self._lock:
                        if not self._running:
                            return
                    fired = True
                    continue
                if evs:
                    fired = True
                    for ev in evs:
                        idx = getattr(ev, "index", 0) or 0
                        if trigger is None or idx >= trigger[0]:
                            trigger = (idx,
                                       getattr(ev, "trace_id", "")
                                       or "")
            if not fired:
                # nothing buffered anywhere: park on the shared wake.
                # Bounded so a missed set (none known) can't wedge the
                # proxy; stop() sets it for an immediate exit.
                self._wake.wait(timeout=0.5)
                continue
            with self._lock:
                if not self._running:
                    return
            try:
                self._rebuild(trigger)
            except Exception:
                # a transient failure (CSR rate pressure, store
                # contention) must not kill the follow thread and
                # freeze this proxy's snapshot forever; the next
                # event retries
                logging.getLogger("consul_tpu.proxycfg").warning(
                    "proxy %s rebuild failed; will retry",
                    self.proxy_id, exc_info=True)

    def _connect_endpoints(self, name: str,
                           target: Optional[dict] = None) -> List[dict]:
        """Mesh-reachable endpoints for upstream `name`: the healthy
        sidecar PROXIES fronting it (health connect semantics — the
        reference's UpstreamEndpoints point at proxies, not apps);
        Connect-native services with no proxy fall back to their own
        instances.

        A chain `target` carrying a Subset applies the subset's bexpr
        filter + only_passing (ServiceResolverSubset).  The filter
        evaluates against the APP instance a sidecar fronts (the row's
        attached `app` record; the instance itself for proxy-less
        services) and the match maps to the sidecar's address — the
        reference's CheckConnectServiceNodes semantics
        (agent/consul/state/catalog.go)."""
        rows = self.manager.store.health_connect_nodes(name)
        native = not rows
        if native:
            rows = self.manager.store.health_service_nodes(name)
        rows = self._subset_filter(rows, target)
        eps = []
        for r in rows:
            if any(c["status"] == "critical" for c in r["checks"]):
                continue
            s = r["service"]
            eps.append({"address": s.get("service_address")
                        or s.get("address", ""),
                        "port": s.get("port", 0),
                        "node": s.get("node", "")})
        # proxies exist for this service: all-unhealthy means NO
        # endpoint, never a silent downgrade to the plaintext app
        # ports (a TLS hello at the app would just confuse it)
        return eps

    @staticmethod
    def _subset_filter(rows: List[dict],
                       target: Optional[dict]) -> List[dict]:
        if not target or not target.get("Subset"):
            return rows
        if target.get("OnlyPassing"):
            rows = [r for r in rows
                    if all(c["status"] == "passing"
                           for c in r["checks"])]
        expr = target.get("Filter") or ""
        if not expr:
            return rows
        from consul_tpu.bexpr import BexprError, compile_filter
        try:
            flt = compile_filter(expr)
        except BexprError:
            return []     # a broken subset filter selects nothing
        out = []
        for r in rows:
            s = r["service"]
            # sidecar rows filter against the APP instance they front
            # (connect_service_nodes attaches it): the reference's
            # CheckConnectServiceNodes evaluates actual service
            # instances and maps to their sidecars — a deployment that
            # tags apps but not sidecars must still subset correctly
            app = s.get("app")
            src = app if app is not None else s
            shaped = {"Service": {"Meta": src.get("meta", {}),
                                  "Tags": src.get("tags", []),
                                  "ID": (src.get("id", "")
                                         if app is not None else
                                         s.get("service_id", "")),
                                  "Service": src.get("service_name",
                                                     ""),
                                  "Port": src.get("port", 0)},
                      "Node": s.get("node", "")}
            try:
                if flt(shaped):
                    out.append(r)
            except BexprError:
                continue
        return out

    def _healthy_endpoints(self, name: str,
                           target: Optional[dict] = None) -> List[dict]:
        rows = self.manager.store.health_service_nodes(name)
        rows = self._subset_filter(rows, target)
        eps = []
        for r in rows:
            if any(c["status"] == "critical" for c in r["checks"]):
                continue
            s = r["service"]
            eps.append({"address": s.get("service_address")
                        or s.get("address", ""),
                        "port": s.get("port", 0),
                        "node": s.get("node", "")})
        return eps

    def _rebuild(self, trigger: Optional[Tuple[int, str]] = None) -> None:
        t0 = time.time()
        kind = self.kind
        if kind in ("mesh-gateway", "ingress-gateway",
                    "terminating-gateway"):
            self._rebuild_gateway(kind, trigger)
        else:
            self._rebuild_connect_proxy(trigger)
        dur_ms = (time.time() - t0) * 1000.0
        with self._lock:
            self._rebuild_ms.append(dur_ms)
            self._rebuilds += 1
            self._last_rebuild_ts = time.time()
            version = self._version
        # SLI emission strictly AFTER every proxycfg lock release —
        # staged like raft's _metrics_buf; stage_xds takes only the
        # visibility table's own lock
        from consul_tpu import flight, telemetry
        telemetry.incr_counter(("xds", "rebuilds"), 1,
                               labels={"kind": kind})
        index, tid = trigger if trigger is not None else (0, "")
        flight.emit("xds.rebuild",
                    labels={"proxy": self.proxy_id, "kind": kind,
                            "version": version, "index": index},
                    trace_id=tid or None)
        if index:
            vis = getattr(self.manager.store, "visibility", None)
            if vis is not None:
                vis.stage_xds("rebuild", index, kind, self.proxy_id)

    def note_push(self, snap: Optional[ConfigSnapshot]) -> None:
        """Push-site bookkeeping, called by the ADS stream / HTTP
        long-poll AFTER the response left this process: stamps the
        per-proxy push clock and emits the apply->push visibility
        stage once per snapshot (the first transport to deliver it
        wins; stage_xds runs off every proxycfg lock)."""
        emit_stage = False
        with self._lock:
            self._pushes += 1
            self._last_push_ts = time.time()
            if snap is not None and not snap.push_emitted \
                    and snap.store_index:
                snap.push_emitted = True
                emit_stage = True
        if not emit_stage:
            return
        vis = getattr(self.manager.store, "visibility", None)
        if vis is not None:
            vis.stage_xds("push", snap.store_index, snap.kind,
                          self.proxy_id)

    def stats(self, now: Optional[float] = None) -> dict:
        """One per-proxy row of the /v1/internal/ui/xds table."""
        now = time.time() if now is None else now
        with self._lock:
            snap = self._snapshot
            version = self._version
            ms = sorted(self._rebuild_ms)
            rebuilds, pushes = self._rebuilds, self._pushes
            last_rebuild = self._last_rebuild_ts
            last_push = self._last_push_ts

        def _pctl(q: float) -> float:
            if not ms:
                return 0.0
            return round(ms[min(len(ms) - 1,
                                max(0, int(q * len(ms))))], 3)

        return {
            "proxy_id": self.proxy_id,
            "kind": self.kind,
            "service": (snap.service if snap is not None
                        else self.svc.get("name", "")),
            "version": version,
            "store_index": (snap.store_index if snap is not None
                            else 0),
            "rebuilds": rebuilds,
            "pushes": pushes,
            "rebuild_ms": {"p50": _pctl(0.5), "p99": _pctl(0.99)},
            "last_rebuild_age_s": (round(now - last_rebuild, 3)
                                   if last_rebuild else None),
            "last_push_age_s": (round(now - last_push, 3)
                                if last_push else None),
        }

    def _rebuild_connect_proxy(
            self, trigger: Optional[Tuple[int, str]] = None) -> None:
        from consul_tpu import discoverychain as dchain
        from consul_tpu import servicemgr
        m = self.manager
        raw_proxy = self.svc.get("proxy") or {}
        service = raw_proxy.get("destination_service",
                                self.svc.get("name", ""))
        # ServiceManager merge: central proxy-defaults/service-defaults
        # land in every snapshot (mode, expose, transparent_proxy,
        # config) with the registration winning — the ("config", None)
        # watch already rebuilds on central-entry changes
        proxy = servicemgr.merged_proxy(m.store, raw_proxy, service)
        upstreams = proxy.get("upstreams") or []
        endpoints = {up.get("destination_name", ""):
                     self._connect_endpoints(
                         up.get("destination_name", ""))
                     for up in upstreams}
        # compile each upstream's discovery chain and resolve endpoints
        # per chain TARGET (proxycfg/state.go watches discovery-chain +
        # per-target health; here both read the same store snapshot)
        chains: Dict[str, dict] = {}
        chain_eps: Dict[str, List[dict]] = {}
        for up in upstreams:
            name = up.get("destination_name", "")
            chain = dchain.compile_chain(m.store, name, dc=m.dc)
            chains[name] = chain
            for tid, tgt in chain["Targets"].items():
                if tid in chain_eps:
                    continue
                if tgt["Datacenter"] != m.dc:
                    # cross-dc target: route via the remote DC's mesh
                    # gateways from federation state (the reference's
                    # mesh-gateway failover path); absent federation,
                    # the target resolves empty rather than wrong
                    chain_eps[tid] = self._remote_dc_endpoints(
                        tgt["Datacenter"])
                else:
                    chain_eps[tid] = self._connect_endpoints(
                        tgt["Service"], target=tgt)
        relevant = imod.match_order(m.store.intention_list(), service,
                                    "destination")
        leaf = m.get_leaf(service)
        with self._cond:
            self._version += 1
            snap = ConfigSnapshot(
                proxy_id=self.proxy_id, service=service,
                upstreams=upstreams, roots=m.ca.roots(), leaf=leaf,
                upstream_endpoints=endpoints, intentions=relevant,
                default_allow=m.default_allow, version=self._version,
                port=self.svc.get("port", 0),
                bind_address=self.svc.get("address", ""),
                local_port=proxy.get("local_service_port", 0),
                chains=chains, chain_endpoints=chain_eps,
                expose=proxy.get("expose") or {},
                mode=proxy.get("mode", ""),
                transparent_proxy=proxy.get("transparent_proxy")
                or {},
                opaque_config=proxy.get("config") or {})
            if trigger is not None:
                snap.store_index, snap.trace_id = trigger
            self._snapshot = snap
            self._cond.notify_all()
        self._sync_health_subs()

    def _remote_dc_endpoints(self, dc: str) -> List[dict]:
        for f in self.manager.store.federation_state_list():
            if f["datacenter"] == dc:
                return [{"address": g.get("address", ""),
                         "port": g.get("port", 0), "node": ""}
                        for g in f.get("mesh_gateways", [])]
        return []

    def _rebuild_gateway(self, kind: str,
                         trigger: Optional[Tuple[int, str]] = None
                         ) -> None:
        """Per-kind gateway snapshot (proxycfg/state.go
        initialize/handleUpdate for MeshGateway / TerminatingGateway /
        IngressGateway)."""
        from consul_tpu import gateways as gmod
        m = self.manager
        gw_name = self.svc.get("name", "")
        endpoints: Dict[str, List[dict]] = {}
        bound: List[dict] = []
        service_leaves: Dict[str, dict] = {}
        mesh_endpoints: Dict[str, List[dict]] = {}
        federation: List[dict] = []
        listeners: List[dict] = []
        intentions: List[dict] = []
        gw_chains: Dict[str, dict] = {}
        gw_chain_eps: Dict[str, List[dict]] = {}
        if kind == "mesh-gateway":
            # every local connect-capable service is routable through
            # the mesh gateway by SNI; remote DCs resolve through their
            # federation-state gateway lists (state.go mesh-gw watches).
            # One locked table pass — this rebuild runs on every health
            # event, so per-name scans would be quadratic under churn
            mesh_endpoints = m.store.healthy_plain_endpoints()
            federation = [f for f in m.store.federation_state_list()
                          if f["datacenter"] != m.dc]
        elif kind == "terminating-gateway":
            from consul_tpu import discoverychain as dchain
            bound = gmod.resolve_wildcard(
                m.store, gmod.gateway_services(m.store, gw_name))
            # ONE intention-table pass for all bound services — this
            # rebuild fires on every config write (same hoist rationale
            # as the mesh-gateway branch)
            all_intentions = m.store.intention_list()
            for row in bound:
                svc = row["Service"]
                endpoints[svc] = self._healthy_endpoints(svc)
                # the terminating gateway presents a mesh identity for
                # each service it fronts (leader_connect_ca leaf per
                # GatewayService)
                service_leaves[svc] = m.get_leaf(svc)
                intentions += imod.match_order(
                    all_intentions, svc, "destination")
                # the chain carries the service's protocol + resolver
                # LB, which decide http-vs-tcp filter chains and route
                # emission (TerminatingGateway.ServiceResolvers role)
                gw_chains[svc] = dchain.compile_chain(m.store, svc,
                                                      dc=m.dc)
        elif kind == "ingress-gateway":
            from consul_tpu import discoverychain as dchain
            ent = m.store.config_entry_get("ingress-gateway", gw_name)
            listeners = (ent.get("listeners") or []) if ent else []
            bound = gmod.resolve_wildcard(
                m.store, gmod.gateway_services(m.store, gw_name))
            for row in bound:
                svc = row["Service"]
                # one row per (service, port): a service bound to N
                # listeners must not recompile/rescan N times
                if svc in gw_chains:
                    continue
                endpoints[svc] = self._healthy_endpoints(svc)
                # bound services with L7 chains route through the
                # chain's targets (IngressGateway.DiscoveryChain role)
                chain = dchain.compile_chain(m.store, svc, dc=m.dc)
                gw_chains[svc] = chain
                for tid, tgt in chain["Targets"].items():
                    if tid in gw_chain_eps:
                        continue
                    if tgt["Datacenter"] != m.dc:
                        gw_chain_eps[tid] = \
                            self._remote_dc_endpoints(
                                tgt["Datacenter"])
                    else:
                        gw_chain_eps[tid] = self._healthy_endpoints(
                            tgt["Service"], target=tgt)
        leaf = m.get_leaf(gw_name)
        with self._cond:
            self._version += 1
            snap = ConfigSnapshot(
                proxy_id=self.proxy_id, service=gw_name,
                upstreams=[], roots=m.ca.roots(), leaf=leaf,
                upstream_endpoints=endpoints, intentions=intentions,
                default_allow=m.default_allow, version=self._version,
                kind=kind, gateway_services=bound,
                service_leaves=service_leaves,
                mesh_endpoints=mesh_endpoints,
                federation_states=federation, listeners=listeners,
                port=self.svc.get("port", 0),
                bind_address=self.svc.get("address", ""),
                chains=gw_chains, chain_endpoints=gw_chain_eps)
            if trigger is not None:
                snap.store_index, snap.trace_id = trigger
            self._snapshot = snap
            self._cond.notify_all()
        self._sync_health_subs()

    def fetch(self, min_version: int = 0,
              timeout: float = 300.0) -> ConfigSnapshot:
        deadline = time.time() + timeout
        with self._cond:
            while (self._snapshot is None
                   or self._snapshot.version <= min_version):
                remaining = deadline - time.time()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            return self._snapshot


class Manager:
    """Proxy registry (proxycfg.Manager): one ProxyState per registered
    sidecar, created lazily from the catalog's connect-proxy services."""

    def __init__(self, store, ca, default_allow: bool = True,
                 dc: Optional[str] = None):
        self.store = store
        self.ca = ca
        self.dc = dc or getattr(ca, "dc", "dc1")
        self.default_allow = default_allow
        self._leaf_lock = locks.make_lock("proxycfg.leaves")
        # svc -> (root_id, leaf, refresh_deadline)  # guarded-by: _leaf_lock
        self._leaves: Dict[str, Tuple[str, dict, float]] = {}
        self._lock = locks.make_lock("proxycfg.manager")
        self._states: Dict[str, ProxyState] = {}    # guarded-by: _lock
        locks.register_guards(self, self._leaf_lock, "_leaves")
        locks.register_guards(self, self._lock, "_states")

    def get_leaf(self, service: str) -> dict:
        """Cached leaf, re-signed when missing, when the active root
        moved, or when the leaf nears expiry (an agent outliving the
        72h leaf TTL must not serve expired certs)."""
        active = self.ca.active.id
        now = time.time()
        with self._leaf_lock:
            hit = self._leaves.get(service)
            if hit is not None and hit[0] == active and now < hit[2]:
                return hit[1]
            from consul_tpu.connect.ca import CARateLimitError
            try:
                leaf = self.ca.sign_leaf(service)
            except CARateLimitError:
                if hit is not None and self._leaf_still_valid(hit[1]):
                    # serve the stale-but-VALID leaf under CSR
                    # pressure rather than failing the snapshot
                    # (the reference's leaf cache behaves the same);
                    # an expired cert would just move the failure to
                    # every handshake
                    return hit[1]
                raise
            ttl_s = self.ca.leaf_ttl_hours * 3600.0
            refresh_at = now + ttl_s * _LEAF_REFRESH_FRACTION
            self._leaves[service] = (active, leaf, refresh_at)
            return leaf

    @staticmethod
    def _leaf_still_valid(leaf: dict) -> bool:
        import datetime
        from consul_tpu.connect import ca as camod
        now = datetime.datetime.now(datetime.timezone.utc)
        if not camod.HAVE_CRYPTOGRAPHY:
            try:
                payload = camod._stub_payload(leaf["CertPEM"])
            except Exception:
                return False
            return payload.get("not_after", 0.0) > now.timestamp()
        from cryptography import x509
        try:
            cert = x509.load_pem_x509_certificate(
                leaf["CertPEM"].encode())
        except Exception:
            return False
        return cert.not_valid_after_utc > now

    def watch(self, proxy_id: str) -> Optional[ProxyState]:
        """ProxyState for a registered connect-proxy service id
        (Manager.Watch :303); None when no such proxy exists.  The
        catalog is revalidated on every call: a re-registration with a
        changed proxy config replaces the state (new watch set), a
        deregistered proxy drops it."""
        svc = self._find_proxy(proxy_id)
        with self._lock:
            st = self._states.get(proxy_id)
            if svc is None:
                if st is not None:
                    st.stop()
                    del self._states[proxy_id]
                return None
            if st is not None and st.svc.get("modify_index") == \
                    svc.get("modify_index"):
                return st
            start_version = st._version if st is not None else 0
            if st is not None:
                st.stop()
            st = ProxyState(self, proxy_id, svc,
                            start_version=start_version)
            st.start()
            self._states[proxy_id] = st
            return st

    def _find_proxy(self, proxy_id: str) -> Optional[dict]:
        s = self.store.service_by_id(proxy_id)
        if s is not None and s.get("kind") in (
                "connect-proxy", "mesh-gateway", "ingress-gateway",
                "terminating-gateway"):
            return s
        return None

    def close(self) -> None:
        """Stop every state and JOIN its follower thread (the PR 14
        thread-hygiene contract): states detach under the lock, the
        joins happen outside it so a slow in-flight rebuild can't
        wedge concurrent watch() calls behind the registry."""
        with self._lock:
            states = list(self._states.values())
            self._states.clear()
        for st in states:
            st.stop()

    def table(self) -> List[dict]:
        """The per-proxy mesh-control-plane table served at
        /v1/internal/ui/xds: one row per live ProxyState (kind,
        snapshot version, rebuild/push counters, rebuild p50/p99,
        last-activity ages), plus the consul.xds.proxies{kind}
        gauges — rows computed from a detached state list and gauges
        emitted off every proxycfg lock."""
        with self._lock:
            states = list(self._states.values())
        now = time.time()
        rows = [st.stats(now) for st in states]
        rows.sort(key=lambda r: r["proxy_id"])
        from consul_tpu import telemetry
        kinds: Dict[str, int] = {}
        for r in rows:
            kinds[r["kind"]] = kinds.get(r["kind"], 0) + 1
        for kind, n in sorted(kinds.items()):
            telemetry.set_gauge(("xds", "proxies"), float(n),
                                labels={"kind": kind})
        return rows
