"""Runtime-updatable agent tokens (the reference's agent/token/store.go).

Four token slots drive which identity the agent itself uses:

  default        — requests with no explicit token (also the DNS token)
  agent          — the agent's own ops: catalog AE sync, check updates
  agent_recovery — emergency local access (agent_master in older configs)
  replication    — secondary-DC replicators

`PUT /v1/agent/token/<slot>` updates a slot at runtime; when a
`data_dir` is wired the slots persist across restarts (store.go
persistence + Load).  Consumers (the HTTP token fallback, the DNS
authorizer) read through the store on every use, so an update takes
effect immediately — no subscription machinery needed.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, Optional

# accepted slot aliases → canonical name (token/store.go's
# agent_master/agent_recovery duality)
_ALIASES = {
    "default": "default",
    "agent": "agent",
    "agent_master": "agent_recovery",
    "agent_recovery": "agent_recovery",
    "replication": "replication",
}


class TokenStore:
    def __init__(self, data_dir: Optional[str] = None):
        self._lock = threading.Lock()
        self._tokens: Dict[str, str] = {
            "default": "", "agent": "", "agent_recovery": "",
            "replication": ""}
        # slots set from config files are not persisted; only API
        # updates are (store.go WithPersistenceLock semantics)
        self._from_api: set = set()
        self.data_dir = data_dir
        if data_dir:
            self._load()

    # ------------------------------------------------------------ access

    @staticmethod
    def canonical(slot: str) -> Optional[str]:
        return _ALIASES.get(slot)

    def get(self, slot: str) -> str:
        name = _ALIASES.get(slot, slot)
        with self._lock:
            return self._tokens.get(name, "")

    def user_token(self) -> str:
        return self.get("default")

    def agent_token(self) -> str:
        """Agent ops fall back to the default token when no agent token
        is set (store.go AgentToken fallback)."""
        with self._lock:
            return self._tokens["agent"] or self._tokens["default"]

    def replication_token(self) -> str:
        return self.get("replication")

    def set(self, slot: str, token: str, from_api: bool = False) -> bool:
        name = _ALIASES.get(slot)
        if name is None:
            return False
        with self._lock:
            self._tokens[name] = token
            if from_api:
                self._from_api.add(name)
                self._persist()
        return True

    # ------------------------------------------------------- persistence

    def _path(self) -> str:
        return os.path.join(self.data_dir, "acl-tokens.json")

    def _persist(self) -> None:
        if not self.data_dir:
            return
        os.makedirs(self.data_dir, exist_ok=True)
        data = {name: self._tokens[name] for name in self._from_api}
        from consul_tpu import storage
        storage.atomic_replace(self._path(), json.dumps(data).encode())

    def _load(self) -> None:
        try:
            with open(self._path()) as f:
                data = json.load(f)
        except (OSError, ValueError):
            return
        for name, token in data.items():
            if name in self._tokens:
                self._tokens[name] = token
                self._from_api.add(name)
