"""Remote exec: cluster-wide command execution over KV + events.

The reference's `consul exec` (agent/remote_exec.go:121 handleRemoteExec;
disabled by default, agent/config/default.go:46) coordinates through the
KV store and a user event: the initiator writes a job spec under
`_rexec/<session>/job`, fires a `consul:exec` event, and each agent that
sees the event reads the spec, runs the command, and writes its output
and exit code back under `_rexec/<session>/<node>/`.  Same protocol
here, over this framework's KV + user-event layers.
"""

from __future__ import annotations

import base64
import json
import subprocess
import threading
import uuid
from typing import Dict, List, Optional

EXEC_EVENT = "_rexec"
PREFIX = "_rexec"


class RemoteExecutor:
    """Agent-side handler: watches for exec events and runs jobs
    (handleRemoteExec).  Disabled by default like the reference."""

    def __init__(self, store, oracle, node_name: str,
                 enabled: bool = False, timeout: float = 30.0):
        self.store = store
        self.oracle = oracle
        self.node_name = node_name
        self.enabled = enabled
        self.timeout = timeout
        self._seen: set = set()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def start(self) -> None:
        if not self.enabled:
            return
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _loop(self) -> None:
        while not self._stop.wait(0.2):
            for ev in self.oracle.event_list():
                if ev["name"] != EXEC_EVENT or ev["id"] in self._seen:
                    continue
                self._seen.add(ev["id"])
                try:
                    spec = json.loads(ev["payload"].decode())
                    self._run_job(spec.get("Session", ""))
                except Exception:
                    # one malformed job (bad JSON spec, non-numeric
                    # Wait, ...) must not kill the executor thread for
                    # every future exec
                    continue

    def _run_job(self, session: str) -> None:
        job = self.store.kv_get(f"{PREFIX}/{session}/job")
        if job is None:
            return
        spec = json.loads(job["value"].decode())
        cmd = spec.get("Command", "")
        # ack before running (remote_exec.go writeAck)
        self.store.kv_set(f"{PREFIX}/{session}/{self.node_name}/ack", b"")
        try:
            proc = subprocess.run(["/bin/sh", "-c", cmd],
                                  capture_output=True,
                                  timeout=spec.get("Wait", self.timeout))
            out = proc.stdout + proc.stderr
            code = proc.returncode
        except subprocess.TimeoutExpired:
            out, code = b"command timed out", -1
        self.store.kv_set(f"{PREFIX}/{session}/{self.node_name}/out",
                          out[:64 * 1024])
        self.store.kv_set(f"{PREFIX}/{session}/{self.node_name}/exit",
                          str(code).encode())


def fire_exec(store, oracle, command: str, origin: str,
              wait: float = 30.0) -> str:
    """Initiator side (`consul exec`): write the job, fire the event;
    returns the session id to poll results under."""
    session = str(uuid.uuid4())
    spec = json.dumps({"Command": command, "Wait": wait}).encode()
    store.kv_set(f"{PREFIX}/{session}/job", spec)
    oracle.fire_event(EXEC_EVENT,
                      json.dumps({"Session": session}).encode(),
                      origin=origin)
    return session


def collect_results(store, session: str) -> Dict[str, dict]:
    """node -> {"acked", "output", "exit_code"} for a session."""
    rows = store.kv_list(f"{PREFIX}/{session}/")
    out: Dict[str, dict] = {}
    for row in rows:
        parts = row["key"].split("/")
        if len(parts) != 4:
            continue
        _, _, node, kind = parts
        rec = out.setdefault(node, {"acked": False, "output": b"",
                                    "exit_code": None})
        if kind == "ack":
            rec["acked"] = True
        elif kind == "out":
            rec["output"] = row["value"]
        elif kind == "exit":
            rec["exit_code"] = int(row["value"] or b"-1")
    return out
