"""Config system: multi-source merge → frozen RuntimeConfig + reload.

The reference's config pipeline (agent/config/builder.go Builder.Build;
immutable result agent/config/runtime.go:43 RuntimeConfig; defaults
default.go:17-120; SIGHUP reload server.go:1395 ReloadableConfig):

    defaults  ←  config files / dirs (HCL or JSON, auto-detected)
              ←  CLI flags
              →  validate  →  frozen RuntimeConfig

Supported keys mirror the reference's surface where this framework has
the feature: node_name, datacenter, server, ports{http,dns}, acl{...},
gossip_lan{...}, gossip_wan{...}, sim{...} (the TPU pool sizing — this
framework's analogue of bind/advertise), dns_config{...}, checks[...],
services[...], log_level.

Reload (`Agent.reload` / PUT /v1/agent/reload) re-applies the RELOADABLE
subset — log_level, dns_config, check/service definitions — and reports
which changed fields require a restart, like the reference's reload
warning path.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Any, Dict, List, Optional, Tuple

# RuntimeConfig FIELD names that reload applies without a restart
RELOADABLE = {"ui_metrics_proxy_json",
              "log_level", "services", "checks", "dns_only_passing",
              "dns_node_ttl", "dns_service_ttl", "dns_domain",
              "recursors", "dns_recursor_timeout"}


class ConfigError(Exception):
    pass


# --------------------------------------------------------------- HCL subset

_TOKEN = re.compile(r'''
    (?P<ws>\s+|\#[^\n]*|//[^\n]*)
  | (?P<lbrace>\{) | (?P<rbrace>\})
  | (?P<lbrack>\[) | (?P<rbrack>\])
  | (?P<eq>=) | (?P<comma>,)
  | (?P<string>"(?:[^"\\]|\\.)*")
  | (?P<number>-?\d+(?:\.\d+)?)
  | (?P<bool>\btrue\b|\bfalse\b)
  | (?P<ident>[A-Za-z_][A-Za-z0-9_.-]*)
''', re.X)


def _tokenize(text: str):
    pos = 0
    out = []
    while pos < len(text):
        m = _TOKEN.match(text, pos)
        if m is None:
            raise ConfigError(f"bad config syntax at {text[pos:pos+20]!r}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        out.append((kind, m.group()))
    return out


def parse_hcl(text: str) -> dict:
    """Parse the HCL subset: `key = value`, `block "label" { ... }`,
    lists, nested objects.  Labeled blocks become {key: {label: {...}}}
    like hcl1's json representation."""
    toks = _tokenize(text)
    i = 0

    def value():
        nonlocal i
        kind, tok = toks[i]
        if kind == "string":
            i += 1
            return json.loads(tok)
        if kind == "number":
            i += 1
            return float(tok) if "." in tok else int(tok)
        if kind == "bool":
            i += 1
            return tok == "true"
        if kind == "lbrack":
            i += 1
            items = []
            while toks[i][0] != "rbrack":
                items.append(value())
                if toks[i][0] == "comma":
                    i += 1
            i += 1
            return items
        if kind == "lbrace":
            return obj()
        raise ConfigError(f"unexpected {tok!r}")

    def obj():
        nonlocal i
        assert toks[i][0] == "lbrace"
        i += 1
        out: Dict[str, Any] = {}
        while toks[i][0] != "rbrace":
            for k, v in entry().items():
                _merge_into(out, k, v)
            if toks[i][0] == "comma":
                i += 1
        i += 1
        return out

    def entry():
        nonlocal i
        kind, tok = toks[i]
        if kind not in ("ident", "string"):
            raise ConfigError(f"expected key, got {tok!r}")
        key = json.loads(tok) if kind == "string" else tok
        i += 1
        # labeled block: key "label" { ... }
        labels = []
        while i < len(toks) and toks[i][0] == "string":
            labels.append(json.loads(toks[i][1]))
            i += 1
        if i < len(toks) and toks[i][0] == "eq":
            i += 1
            return {key: value()}
        if i < len(toks) and toks[i][0] == "lbrace":
            body = obj()
            for lab in reversed(labels):
                body = {lab: body}
            return {key: body}
        raise ConfigError(f"expected '=' or block after {key!r}")

    out: Dict[str, Any] = {}
    try:
        while i < len(toks):
            for k, v in entry().items():
                _merge_into(out, k, v)
    except IndexError:
        raise ConfigError("unexpected end of config (unclosed block?)")
    return out


def _merge_into(dst: dict, key: str, val: Any) -> None:
    if key in dst and isinstance(dst[key], dict) and isinstance(val, dict):
        for k, v in val.items():
            _merge_into(dst[key], k, v)
    elif key in dst and isinstance(dst[key], list) and isinstance(val, list):
        dst[key] = dst[key] + val
    else:
        dst[key] = val


def _deep_merge(base: dict, over: dict) -> dict:
    out = dict(base)
    for k, v in over.items():
        if k in out and isinstance(out[k], dict) and isinstance(v, dict):
            out[k] = _deep_merge(out[k], v)
        elif k in out and isinstance(out[k], list) and isinstance(v, list):
            # definitions accumulate across sources (two config files each
            # adding a service both count — reference slice-merge)
            out[k] = out[k] + v
        else:
            out[k] = v
    return out


# ------------------------------------------------------------ RuntimeConfig

@dataclasses.dataclass(frozen=True)
class RuntimeConfig:
    """Immutable merged config (agent/config/runtime.go:43)."""

    node_name: str = "node0"
    datacenter: str = "dc1"
    server: bool = True
    data_dir: str = ""
    log_level: str = "INFO"
    enable_remote_exec: bool = False
    enable_debug: bool = False
    http_port: int = 0
    dns_port: int = 0
    # ports.grpc: the gRPC ADS/xDS listener; -1 disabled (the
    # reference's convention), 0 ephemeral (config GRPCPort)
    grpc_port: int = -1
    # encrypt: base64 gossip key preloaded into the keyring at boot
    # (agent/keyring.go loadKeyringFile / config `encrypt`)
    encrypt: str = ""
    # acl block (agent/config: acl{enabled, default_policy, down_policy,
    # tokens{agent, default}})
    acl_enabled: bool = False
    acl_default_policy: str = "allow"
    acl_down_policy: str = "extend-cache"
    acl_agent_token: str = ""
    # gossip tuning: (field, value) overrides onto GossipConfig defaults
    gossip_lan: Tuple[Tuple[str, Any], ...] = ()
    gossip_wan: Tuple[Tuple[str, Any], ...] = ()
    # sim sizing (the TPU pool)
    sim: Tuple[Tuple[str, Any], ...] = ()
    # segments[{name, sim{...}}]: additional LAN gossip segments beyond
    # the default; each is its own pool (segment_oss.go; SURVEY §2.2)
    segments: Tuple[Tuple[str, Any], ...] = ()
    # connect{enable_mesh_gateway_wan_federation}: route cross-DC
    # requests through mesh gateways from replicated federation states
    # (agent/consul/wanfed; config runtime.go ConnectMeshGatewayWANFederationEnabled)
    connect_mesh_gateway_wan_federation: bool = False
    # dns_config{only_passing, node_ttl, service_ttl, domain}
    dns_only_passing: bool = False
    dns_node_ttl: int = 0
    dns_service_ttl: int = 0
    dns_domain: str = "consul."
    # recursors[]: upstreams for out-of-zone names (agent/dns.go:251)
    recursors: Tuple[str, ...] = ()
    dns_recursor_timeout: float = 2.0
    # limits{kv_max_value_size, txn_max_ops} (config runtime.go
    # KVMaxValueSize; txn_endpoint.go maxTxnOps)
    kv_max_value_size: int = 512 * 1024
    txn_max_ops: int = 64
    # ui_config.metrics_proxy (config/config.go:837 RawUIMetricsProxy):
    # {base_url, path_allowlist, add_headers:[{name,value}]}, frozen as
    # JSON so the config stays hashable.  Empty = proxy disabled.
    ui_metrics_proxy_json: str = ""
    # static service/check definitions (lists of dicts, agent JSON shapes)
    services: Tuple[dict, ...] = ()
    checks: Tuple[dict, ...] = ()
    # raw merged view for debugging / agent/self
    raw: Tuple[Tuple[str, Any], ...] = ()

    def gossip_config(self, wan: bool = False):
        from consul_tpu.config import GossipConfig
        base = GossipConfig.wan() if wan else GossipConfig.lan()
        over = dict(self.gossip_wan if wan else self.gossip_lan)
        return dataclasses.replace(base, **over) if over else base

    def sim_config(self):
        from consul_tpu.config import SimConfig
        over = dict(self.sim)
        return SimConfig(**over) if over else SimConfig()

    def segment_pools(self):
        """{segment -> (GossipConfig, SimConfig)} for SegmentedOracle;
        None when no extra segments are configured.  The default
        segment "" always carries the main gossip/sim config."""
        if not self.segments:
            return None
        from consul_tpu.config import SimConfig
        pools = {"": (self.gossip_config(), self.sim_config())}
        for name, sim_over in self.segments:
            over = dict(sim_over)
            pools[name] = (self.gossip_config(),
                           SimConfig(**over) if over else SimConfig())
        return pools


_DURATION = re.compile(r"^(\d+(?:\.\d+)?)(ms|s|m|h)$")


def _seconds(v: Any) -> Any:
    if isinstance(v, str):
        m = _DURATION.match(v)
        if m:
            scale = {"ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0}
            return float(m.group(1)) * scale[m.group(2)]
    return v


class Builder:
    """Accumulates sources in precedence order and builds (builder.go)."""

    _GOSSIP_KEYS = {"probe_interval", "probe_timeout", "gossip_interval",
                    "gossip_nodes", "indirect_checks", "suspicion_mult",
                    "suspicion_max_timeout_mult", "retransmit_mult"}
    _SIM_KEYS = {"n_nodes", "rumor_slots", "alloc_cap", "p_loss",
                 "rtt_base_ms", "rtt_spread_ms", "coord_dims", "seed"}

    def __init__(self):
        self._sources: List[dict] = []

    # ----------------------------------------------------------- sources

    def add_dict(self, cfg: dict) -> "Builder":
        self._sources.append(cfg)
        return self

    def add_file(self, path: str) -> "Builder":
        with open(path) as f:
            text = f.read()
        if path.endswith(".json"):
            cfg = json.loads(text or "{}")
        elif path.endswith(".hcl"):
            cfg = parse_hcl(text)
        else:  # sniff (builder.go auto-detect)
            try:
                cfg = json.loads(text)
            except json.JSONDecodeError:
                cfg = parse_hcl(text)
        if not isinstance(cfg, dict):
            raise ConfigError(f"{path}: top level must be an object")
        return self.add_dict(cfg)

    def add_dir(self, path: str) -> "Builder":
        """Load *.json/*.hcl in lexical order (config-dir semantics)."""
        for name in sorted(os.listdir(path)):
            if name.endswith((".json", ".hcl")):
                self.add_file(os.path.join(path, name))
        return self

    def add_flags(self, **flags: Any) -> "Builder":
        """CLI flags: highest precedence; None values are unset.  Flat
        port flags nest into the ports block so deep-merge precedence
        holds (an explicit -http-port must beat a file's ports.http)."""
        src = {k: v for k, v in flags.items() if v is not None}
        ports = {}
        if "http_port" in src:
            ports["http"] = src.pop("http_port")
        if "dns_port" in src:
            ports["dns"] = src.pop("dns_port")
        if "grpc_port" in src:
            ports["grpc"] = src.pop("grpc_port")
        if ports:
            src["ports"] = {**src.get("ports", {}), **ports}
        self._sources.append(src)
        return self

    # ------------------------------------------------------------- build

    def build(self) -> RuntimeConfig:
        merged: dict = {}
        for src in self._sources:
            merged = _deep_merge(merged, src)
        return self._to_runtime(merged)

    def _to_runtime(self, m: dict) -> RuntimeConfig:
        acl = m.get("acl") or {}
        tokens = acl.get("tokens") or {}
        ports = m.get("ports") or {}
        dnscfg = m.get("dns_config") or {}

        def gossip_block(name):
            blk = m.get(name) or {}
            bad = set(blk) - self._GOSSIP_KEYS
            if bad:
                raise ConfigError(f"{name}: unknown keys {sorted(bad)}")
            return tuple(sorted((k, _seconds(v)) for k, v in blk.items()))

        sim = m.get("sim") or {}
        bad = set(sim) - self._SIM_KEYS
        if bad:
            raise ConfigError(f"sim: unknown keys {sorted(bad)}")

        seg_out = []
        seg_names = set()
        for seg in m.get("segments") or []:
            name = seg.get("name", "")
            if not name:
                raise ConfigError("segment missing name (the default "
                                  "segment needs no entry)")
            if name in seg_names:
                raise ConfigError(f"duplicate segment {name!r}")
            seg_names.add(name)
            seg_sim = seg.get("sim") or {}
            bad = set(seg_sim) - self._SIM_KEYS
            if bad:
                raise ConfigError(
                    f"segment {name!r} sim: unknown keys {sorted(bad)}")
            seg_out.append((name, tuple(sorted(seg_sim.items()))))

        dp = acl.get("default_policy", "allow")
        if dp not in ("allow", "deny"):
            raise ConfigError(f"acl.default_policy must be allow|deny, "
                              f"got {dp!r}")
        down = acl.get("down_policy", "extend-cache")
        if down not in ("allow", "deny", "extend-cache", "async-cache"):
            raise ConfigError(f"acl.down_policy invalid: {down!r}")
        for svc in m.get("services") or []:
            if not (svc.get("Name") or svc.get("name")):
                raise ConfigError("service definition missing name")
        for chk in m.get("checks") or []:
            if not (chk.get("Name") or chk.get("name")
                    or chk.get("CheckID") or chk.get("id")):
                raise ConfigError("check definition missing name/id")
        if m.get("encrypt"):
            # a malformed gossip key must fail the boot, not silently
            # wedge the delegate socket later (agent startup validates
            # the encrypt key the same way)
            from consul_tpu.gossip_crypto import _decode_key
            try:
                _decode_key(str(m["encrypt"]))
            except (ValueError, TypeError) as e:
                raise ConfigError(f"invalid encrypt key: {e}")
        for r in m.get("recursors") or []:
            # validate HERE (agent/dns.go:251 stance): a malformed
            # recursor must fail the load/reload atomically, not blow
            # up mid-apply after other fields were already mutated
            from consul_tpu.dns import parse_recursor
            try:
                parse_recursor(str(r))
            except (ValueError, TypeError):
                raise ConfigError(f"invalid recursor address {r!r}")

        def freeze(d):
            return tuple(sorted(d.items()))

        return RuntimeConfig(
            node_name=m.get("node_name", "node0"),
            datacenter=m.get("datacenter", "dc1"),
            server=bool(m.get("server", True)),
            data_dir=str(m.get("data_dir", "") or ""),
            enable_remote_exec=bool(m.get("enable_remote_exec", False)),
            enable_debug=bool(m.get("enable_debug", False)),
            log_level=str(m.get("log_level", "INFO")).upper(),
            http_port=int(ports.get("http", 0) or 0),
            dns_port=int(ports.get("dns", 0) or 0),
            grpc_port=int(ports.get("grpc", -1)),
            encrypt=str(m.get("encrypt", "") or ""),
            acl_enabled=bool(acl.get("enabled", False)),
            acl_default_policy=dp,
            acl_down_policy=down,
            acl_agent_token=tokens.get("agent", ""),
            connect_mesh_gateway_wan_federation=bool(
                (m.get("connect") or {}).get(
                    "enable_mesh_gateway_wan_federation", False)),
            gossip_lan=gossip_block("gossip_lan"),
            gossip_wan=gossip_block("gossip_wan"),
            sim=tuple(sorted(sim.items())),
            segments=tuple(seg_out),
            dns_only_passing=bool(dnscfg.get("only_passing", False)),
            dns_node_ttl=int(_seconds(dnscfg.get("node_ttl", 0)) or 0),
            dns_service_ttl=int(_seconds(dnscfg.get("service_ttl", 0)) or 0),
            dns_domain=str(dnscfg.get("domain", "consul.")),
            recursors=tuple(str(r) for r in m.get("recursors") or []),
            kv_max_value_size=int((m.get("limits") or {}).get(
                "kv_max_value_size", 512 * 1024)),
            txn_max_ops=int((m.get("limits") or {}).get(
                "txn_max_ops", 64)),
            ui_metrics_proxy_json=_metrics_proxy_json(
                (m.get("ui_config") or {}).get("metrics_proxy") or {}),
            dns_recursor_timeout=float(
                _seconds(dnscfg.get("recursor_timeout", 2.0)) or 2.0),
            services=tuple(m.get("services") or []),
            checks=tuple(m.get("checks") or []),
            raw=freeze({k: json.dumps(v, sort_keys=True)
                        for k, v in m.items()}),
        )


def _metrics_proxy_json(mp: dict) -> str:
    """Normalize ui_config.metrics_proxy; the prometheus default
    allowlist applies when a base_url is set with no explicit list
    (config/builder.go:1117-1122)."""
    base = str(mp.get("base_url", "") or "")
    if not base:
        return ""
    raw_allow = mp.get("path_allowlist")
    if raw_allow is None:
        # prometheus default ONLY when unset — an explicit [] is an
        # operator locking the proxy down, not asking for defaults
        raw_allow = ["/api/v1/query", "/api/v1/query_range"]
    allow = [str(p) for p in raw_allow]
    headers = [{"name": str(h.get("name", "")),
                "value": str(h.get("value", ""))}
               for h in mp.get("add_headers") or [] if h.get("name")]
    return json.dumps({"base_url": base.rstrip("/"),
                       "path_allowlist": allow,
                       "add_headers": headers}, sort_keys=True)


def load(files: List[str] = (), dirs: List[str] = (),
         **flags: Any) -> RuntimeConfig:
    """One-call load: defaults ← files ← dirs ← flags."""
    b = Builder()
    for f in files:
        b.add_file(f)
    for d in dirs:
        b.add_dir(d)
    b.add_flags(**flags)
    return b.build()


def diff_reloadable(old: RuntimeConfig,
                    new: RuntimeConfig) -> Tuple[List[str], List[str]]:
    """(reloadable_changes, restart_required_changes) field names."""
    reload_keys: List[str] = []
    restart_keys: List[str] = []
    for f in dataclasses.fields(RuntimeConfig):
        if f.name == "raw":
            continue
        if getattr(old, f.name) != getattr(new, f.name):
            if f.name in RELOADABLE:
                reload_keys.append(f.name)
            else:
                restart_keys.append(f.name)
    return reload_keys, restart_keys
