"""Envoy version gating for the ADS server.

The reference rejects ADS streams from Envoy builds it does not
support before serving them any config
(agent/xds/envoy_versioning.go determineSupportedProxyFeatures,
called on stream start at agent/xds/server.go:360 / delta.go:177):
the announced `node.user_agent_build_version` is compared against a
minimum mainline version plus a denylist of broken point releases.
Serving an unsupported proxy risks silent misconfiguration — failing
the stream with a clear reason is strictly better.

Custom builds that announce no version (or a non-envoy user agent)
pass through ungated, matching the reference's nil-version behavior.
"""

from __future__ import annotations

from typing import Optional, Tuple

# Oldest supported mainline (proxysupport.EnvoyVersions floor — the
# reference pins 1.15.0 for the Envoy generation this API targets).
MIN_SUPPORTED = (1, 15, 0)

# Specific point releases rejected with an upgrade hint even though
# their mainline is supported (envoy_versioning.go
# specificUnsupportedVersions shape; empty in the reference at this
# vintage, populated here the same way when needed).
SPECIFIC_UNSUPPORTED: dict = {}


def version_from_node(node) -> Optional[Tuple[int, int, int]]:
    """(major, minor, patch) announced by an envoy node, or None for
    custom/ancient builds with no parseable version
    (determineEnvoyVersionFromNode)."""
    if node is None:
        return None
    if getattr(node, "user_agent_name", "") != "envoy":
        return None
    which = None
    try:
        which = node.WhichOneof("user_agent_version_type")
    except Exception:
        pass
    if which == "user_agent_build_version":
        v = node.user_agent_build_version.version
        return (v.major_number, v.minor_number, v.patch)
    if which == "user_agent_version":
        # tolerate build suffixes ("1.14.9-dev"): leading digits of
        # each dotted part; a part with no digits at all is unparseable
        import re as _re
        nums = []
        for part in node.user_agent_version.split(".")[:3]:
            m = _re.match(r"\d+", part)
            if m is None:
                return None
            nums.append(int(m.group()))
        if not nums:
            return None
        return tuple(nums + [0] * (3 - len(nums)))  # type: ignore
    return None


def check_supported(node) -> Optional[str]:
    """None when the announced version is servable; otherwise the
    rejection reason the stream should fail with."""
    v = version_from_node(node)
    if v is None:
        return None
    if v < MIN_SUPPORTED:
        return (f"Envoy {v[0]}.{v[1]}.{v[2]} is too old and is not "
                f"supported by this control plane (minimum "
                f"{'.'.join(map(str, MIN_SUPPORTED))})")
    hint = SPECIFIC_UNSUPPORTED.get(v)
    if hint:
        return (f"Envoy {v[0]}.{v[1]}.{v[2]} is an unsupported point "
                f"release ({hint})")
    return None
