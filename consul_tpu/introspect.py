"""Cluster introspection: scrape + merge every node's observability
surfaces into ONE view.

PR 1 gave each process `/v1/agent/metrics`, PR 8 `/v1/agent/events` +
`/v1/agent/profile` — but an operator (and the chaos harness, and the
visibility prober) needs the CLUSTER's story: who leads, how far each
follower lags, what the commit-to-visibility SLIs look like, and one
merged timeline across nodes that survives restarts.  The reference
builds the same cross-node view for its UI behind
`/v1/internal/ui/metrics-proxy` and the streaming-reads telemetry
(PAPER.md: contributing/rpc/streaming/); here the pieces are:

  * `EventCollector` — promoted from PR 9's `chaos_live.py` (the chaos
    harness re-exports it; no behavior change): polls every node's
    `/v1/agent/events` feed on a cursor, tags rows (node, generation),
    survives deaths and seq resets across restarts, merges everything
    into one timestamp-ordered timeline.
  * `scrape_node` / `cluster_view` — one-shot scrapes of
    `/v1/agent/{self,metrics,events,profile}` +
    `/v1/operator/raft/configuration` per node, merged into a
    leader/lag table with per-stage visibility quantiles.  Served by
    `/v1/internal/ui/cluster-metrics` (api/http.py) and rendered by
    `tools/cluster_top.py`; `tools/debug_bundle.py --cluster` archives
    the raw per-node scrapes next to the merged timeline.
  * `StaticCluster` — adapts a plain URL list to the duck type
    `EventCollector` polls (the chaos harness hands it a LiveCluster
    whose servers restart; static fleets are generation 1 forever).

Everything is best-effort per node: a dead node contributes
`alive: false`, never an exception — the whole point is reading a
cluster mid-incident.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional, Tuple, Union

from consul_tpu.api.client import ApiError, Client

SCRAPE_TIMEOUT = 2.5


class StaticNode:
    """URL-only member with the LiveServer surface EventCollector and
    the scrapers poll (alive is assumed; a refused scrape reports it)."""

    def __init__(self, name: str, url: str):
        self.name = name
        self.http = url.rstrip("/")
        self.generation = 1
        self.paused = False

    def alive(self) -> bool:
        return True


class StaticCluster:
    """A fixed fleet of StaticNodes from URLs or a name->url map."""

    def __init__(self, nodes: Union[List[str], Dict[str, str]]):
        if isinstance(nodes, dict):
            self.servers = [StaticNode(n, u)
                            for n, u in sorted(nodes.items())]
        else:
            self.servers = [StaticNode(f"node{i}", u)
                            for i, u in enumerate(nodes)]


class EventCollector:
    """Polls every node's /v1/agent/events feed on a cursor, tags rows
    with (node, generation), survives node deaths and seq resets
    across restarts, and merges everything — plus the nemesis's own
    injection journal — into one timeline ordered by wall timestamp."""

    def __init__(self, cluster, period: float = 0.4,
                 dc: Optional[str] = None):
        self.cluster = cluster
        self.period = period
        # the datacenter tag (ISSUE 15): a WAN harness runs one
        # collector per DC and merges — every row carries its DC so
        # the federated timeline can tell dc2's wakeup from dc1's
        self.dc = dc
        self.rows: List[dict] = []
        self._cursors: Dict[str, int] = {}
        self._gens: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop,
                                        name="event-collector",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self.poll_once()        # final sweep after the cluster settles

    def _loop(self) -> None:
        while not self._stop.wait(self.period):
            self.poll_once()

    def poll_once(self) -> None:
        for s in self.cluster.servers:
            if not s.alive() or s.paused:
                continue
            gen = s.generation
            if self._gens.get(s.name) != gen:
                # fresh process ⇒ fresh recorder ⇒ seq restarts at 0
                self._gens[s.name] = gen
                self._cursors[s.name] = 0
            try:
                events, idx = Client(
                    s.http, timeout=1.5).agent_events(
                    since=self._cursors.get(s.name, 0))
            except (ApiError, OSError):
                continue
            if not events:
                continue
            with self._lock:
                self._cursors[s.name] = max(
                    self._cursors.get(s.name, 0), idx)
                for e in events:
                    row = {
                        "node": s.name, "gen": gen, "seq": e["Seq"],
                        "ts": e["Ts"], "name": e["Name"],
                        "severity": e["Severity"],
                        "labels": e["Labels"]}
                    if self.dc is not None:
                        row["dc"] = self.dc
                    self.rows.append(row)

    # ------------------------------------------------------------- readers

    def election_wins(self) -> List[Tuple[int, str]]:
        """(term, node) for every raft.election.won row — the feed for
        ElectionSafetyChecker.note()."""
        out = []
        with self._lock:
            for r in self.rows:
                if r["name"] == "raft.election.won":
                    labels = r["labels"] or {}
                    try:
                        out.append((int(labels.get("term")),
                                    str(labels.get("node"))))
                    except (TypeError, ValueError):
                        continue
        return out

    def count(self, name: str) -> int:
        with self._lock:
            return sum(1 for r in self.rows if r["name"] == name)

    def merged_jsonl(self, nemesis_rows: List[dict]) -> str:
        """One cluster timeline: every node's feed + the nemesis's own
        injection journal (node='nemesis'), ordered by timestamp."""
        rows = []
        with self._lock:
            rows.extend(self.rows)
        for r in nemesis_rows:
            rows.append({"node": "nemesis", "gen": 0, "seq": r["seq"],
                         "ts": r["ts"], "name": r["name"],
                         "severity": r["severity"],
                         "labels": r["labels"]})
        return "\n".join(
            json.dumps({"ts": round(r["ts"], 3), "node": r["node"],
                        "name": r["name"], "labels": r["labels"]},
                       sort_keys=True)
            for r in merge_timelines(rows))


def merge_timelines(rows: List[dict]) -> List[dict]:
    """Order cross-node event rows into one timeline: wall timestamp
    first, then (node, generation, seq) so a restarted node's reset
    seq space cannot interleave backwards within one instant."""
    return sorted(rows, key=lambda r: (r["ts"], r["node"],
                                       r.get("gen", 0), r["seq"]))


# ---------------------------------------------------------------------------
# one-shot scraping: the /v1/internal/ui/cluster-metrics backing
# ---------------------------------------------------------------------------


def _metric_maps(dump: dict) -> Tuple[dict, dict]:
    """(gauges, samples) keyed by (name, sorted-label-tuple)."""
    gauges = {}
    for g in (dump or {}).get("Gauges", []):
        lk = tuple(sorted((g.get("Labels") or {}).items()))
        gauges[(g["Name"], lk)] = g["Value"]
    samples = {}
    for s in (dump or {}).get("Samples", []):
        lk = tuple(sorted((s.get("Labels") or {}).items()))
        samples[(s["Name"], lk)] = s
    return gauges, samples


def visibility_stages(dump: dict) -> Dict[str, dict]:
    """{stage: {p50_ms, p99_ms, count}} from a node's metrics dump —
    the consul.kv.visibility summary, per stage label."""
    _, samples = _metric_maps(dump)
    out = {}
    for (name, lk), s in samples.items():
        if name != "consul.kv.visibility":
            continue
        stage = dict(lk).get("stage")
        if stage:
            out[stage] = {
                "p50_ms": round(s.get("P50", 0.0) * 1000.0, 3),
                "p99_ms": round(s.get("P99", 0.0) * 1000.0, 3),
                "count": s.get("Count", 0)}
    return out


def xds_stages(dump: dict) -> Dict[str, dict]:
    """{stage: {p50_ms, p99_ms, count}} from a node's metrics dump —
    the consul.xds.visibility summary (rebuild|push), merged across
    proxy-kind label sets per stage (max quantile, summed count)."""
    _, samples = _metric_maps(dump)
    out: Dict[str, dict] = {}
    for (name, lk), s in samples.items():
        if name != "consul.xds.visibility":
            continue
        stage = dict(lk).get("stage")
        if not stage:
            continue
        cur = out.setdefault(stage, {"p50_ms": 0.0, "p99_ms": 0.0,
                                     "count": 0})
        cur["p50_ms"] = max(cur["p50_ms"],
                            round(s.get("P50", 0.0) * 1000.0, 3))
        cur["p99_ms"] = max(cur["p99_ms"],
                            round(s.get("P99", 0.0) * 1000.0, 3))
        cur["count"] += s.get("Count", 0)
    return out


def xds_view(nodes: Union[List[str], Dict[str, str]]) -> dict:
    """The merged mesh-control-plane view behind /v1/internal/ui/xds
    (ISSUE 16): every CONFIGURED node's own per-proxy table
    (?local=1 — the fixed fleet map, never a caller-supplied URL)
    plus its consul.xds.visibility stage quantiles.  Dead nodes
    degrade to an error row, the cluster-metrics stance."""
    if isinstance(nodes, dict):
        items = sorted(nodes.items())
    else:
        items = [(None, u) for u in nodes]
    view: dict = {"nodes": {}, "proxies": []}
    seen: Dict[str, int] = {}
    for label, url in items:
        c = Client(url, timeout=SCRAPE_TIMEOUT)
        row: dict = {"url": url.rstrip("/"), "alive": False,
                     "proxies": [], "xds_visibility": {},
                     "shapes": {}}
        name = label
        try:
            local = c.internal_xds(local=True)
            row["alive"] = True
            row["proxies"] = local.get("proxies", [])
            # shared-shape registry (ISSUE 19): how many DISTINCT
            # materializations this node's proxy population reduces to
            row["shapes"] = local.get("shapes", {})
            name = label or local.get("node") or row["url"]
            dump = c._call("GET", "/v1/agent/metrics")[0]
            row["xds_visibility"] = xds_stages(dump)
        except (ApiError, OSError) as e:
            row["error"] = str(e)
            name = label or row["url"]
        if name in seen:
            seen[name] += 1
            name = f"{name}#{seen[name]}"
        else:
            seen[name] = 1
        view["nodes"][name] = row
        for p in row["proxies"]:
            view["proxies"].append(dict(p, node=name))
    view["proxies"].sort(key=lambda p: (p["node"], p["proxy_id"]))
    view["shapes"] = {
        "distinct": sum((n.get("shapes") or {}).get("shapes", 0)
                        for n in view["nodes"].values()),
        "pinned": sum((n.get("shapes") or {}).get("pinned", 0)
                      for n in view["nodes"].values())}
    view["generated_at"] = round(time.time(), 3)
    return view


def replication_lag(dump: dict) -> Dict[str, dict]:
    """{peer: {entries, ms}} from a leader's metrics dump."""
    gauges, _ = _metric_maps(dump)
    out: Dict[str, dict] = {}
    for (name, lk), v in gauges.items():
        peer = dict(lk).get("peer")
        if peer is None:
            continue
        if name == "consul.raft.replication.lag":
            out.setdefault(peer, {})["entries"] = v
        elif name == "consul.raft.replication.lag_ms":
            out.setdefault(peer, {})["ms"] = v
    return out


def scrape_node(url: str, events_since: int = 0,
                events_limit: int = 50,
                timeout: float = SCRAPE_TIMEOUT) -> dict:
    """Best-effort scrape of one node's observability surfaces.
    Always returns a row; `alive` says whether anything answered.

    Partial failures do NOT vanish (ISSUE 15 satellite): every surface
    that refused lands in `degraded` with its error, `error` carries
    the first failure, and `consul.introspect.scrape_failed{node}`
    counts the scrape — a node whose metrics endpoint wedged
    mid-incident must show up as a degraded row, never as a silently
    thinner view."""
    from consul_tpu import telemetry
    c = Client(url, timeout=timeout)
    row: dict = {"url": url.rstrip("/"), "alive": False,
                 "name": None, "dc": None, "metrics": None,
                 "profile": None, "events": [],
                 "events_cursor": events_since,
                 "raft": None, "error": None, "degraded": []}
    try:
        cfg = (c.agent_self() or {}).get("Config", {})
        row["name"] = cfg.get("NodeName")
        row["dc"] = cfg.get("Datacenter")
        row["alive"] = True
    except (ApiError, OSError) as e:
        row["error"] = str(e)
        row["degraded"].append({"surface": "self", "error": str(e)})
        telemetry.incr_counter(("introspect", "scrape_failed"),
                               labels={"node": row["name"]
                                       or row["url"]})
        return row
    for field, fetch in (
            ("metrics", lambda: c._call(
                "GET", "/v1/agent/metrics")[0]),
            ("profile", lambda: c.agent_profile()),
            ("raft", lambda: c._call(
                "GET", "/v1/operator/raft/configuration")[0])):
        try:
            row[field] = fetch()
        except (ApiError, OSError) as e:
            # partial scrapes still merge — but loudly
            row["degraded"].append({"surface": field, "error": str(e)})
    # cross-DC replication status (ISSUE 18): present only on nodes
    # running a secondary-DC replication set — absence is NORMAL (a
    # primary-DC node), so a 404/None never degrades the scrape
    try:
        rep, _, _ = c._call("GET", "/v1/internal/ui/replication")
        if rep and rep.get("replicators"):
            row["replication"] = rep
        elif rep and rep.get("write_rate") is not None:
            row["replication"] = rep
    except (ApiError, OSError):
        pass
    try:
        events, cursor = c.agent_events(since=events_since,
                                        limit=events_limit)
        row["events"] = events
        row["events_cursor"] = cursor
    except (ApiError, OSError) as e:
        row["degraded"].append({"surface": "events", "error": str(e)})
    if row["degraded"]:
        row["error"] = row["degraded"][0]["error"]
        telemetry.incr_counter(("introspect", "scrape_failed"),
                               labels={"node": row["name"]
                                       or row["url"]})
    return row


def _self_leader(raft_cfg: Optional[dict],
                 name: Optional[str]) -> bool:
    """Does this node's OWN raft configuration mark itself leader —
    the self-claim election safety audits (chaos_live.leader())."""
    for srv in (raft_cfg or {}).get("Servers", []):
        if srv.get("Leader") and srv.get("ID") == name:
            return True
    return False


def scrape_cluster(nodes: Union[List[str], Dict[str, str]],
                   events_since: int = 0,
                   events_limit: int = 50) -> List[Tuple[str, dict]]:
    """One scrape pass over the fleet -> [(unique name, row)].  Names
    prefer the caller's label, then the node's self-reported NodeName,
    then the URL — deduplicated so two nodes claiming one name (a
    misconfigured fleet, or a URL listed twice) cannot silently
    collapse into a single entry."""
    if isinstance(nodes, dict):
        items = sorted(nodes.items())
    else:
        items = [(None, u) for u in nodes]
    rows: List[Tuple[str, dict]] = []
    seen: Dict[str, int] = {}
    for label, url in items:
        row = scrape_node(url, events_since=events_since,
                          events_limit=events_limit)
        name = label or row["name"] or row["url"]
        if name in seen:
            seen[name] += 1
            name = f"{name}#{seen[name]}"
        else:
            seen[name] = 1
        rows.append((name, row))
    return rows


def cluster_view(nodes: Union[List[str], Dict[str, str]],
                 events_since: int = 0,
                 events_limit: int = 50) -> dict:
    """Scrape every node and merge — see view_from_scrapes."""
    return view_from_scrapes(scrape_cluster(
        nodes, events_since=events_since, events_limit=events_limit))


def view_from_scrapes(rows: List[Tuple[str, dict]]) -> dict:
    """Merge pre-fetched scrape rows: leader + per-node index table,
    the leader's per-peer replication lag, per-stage visibility
    quantiles, and a generation-unaware merged event tail (one-shot
    scrapes have no restart history; the long-lived EventCollector is
    the generation-aware feed).  Split from cluster_view so callers
    that also archive the raw rows (debug_bundle --cluster) scrape the
    fleet ONCE — mid-incident, every dead node costs a scrape timeout."""
    view: dict = {"nodes": {}, "leader": None,
                  "replication_lag": {}, "visibility": {},
                  "events": []}
    all_events = []
    for name, row in rows:
        gauges, _ = _metric_maps(row["metrics"])
        node_view = {
            "url": row["url"], "alive": row["alive"],
            "dc": row.get("dc"),
            "leader": _self_leader(row["raft"], row["name"]),
            "index": gauges.get(("consul.catalog.index", ())),
            "tick": gauges.get(("consul.sim.tick", ())),
            "blocking_queries": gauges.get(
                ("consul.rpc.queries_blocking", ())),
            "visibility": visibility_stages(row["metrics"]),
            "events_cursor": row["events_cursor"],
        }
        if row["error"]:
            node_view["error"] = row["error"]
        if row.get("degraded"):
            # the surfaces that refused: rendered as a DEGRADED row by
            # cluster_top, never dropped from the table
            node_view["degraded"] = [d["surface"]
                                     for d in row["degraded"]]
        view["nodes"][name] = node_view
        if node_view["leader"]:
            view["leader"] = name
            view["replication_lag"] = replication_lag(row["metrics"])
            view["visibility"] = node_view["visibility"]
        for e in row["events"]:
            all_events.append({"node": name, "gen": 1, "seq": e["Seq"],
                               "ts": e["Ts"], "name": e["Name"],
                               "severity": e["Severity"],
                               "labels": e["Labels"]})
    view["events"] = merge_timelines(all_events)
    if view["leader"] is None and view["nodes"]:
        # no self-claimed leader scraped: still surface SOME visibility
        # table (max-count node) so the view degrades, not blanks
        best = max(view["nodes"].values(),
                   key=lambda n: sum(s.get("count", 0)
                                     for s in n["visibility"].values()))
        view["visibility"] = best["visibility"]
    view["generated_at"] = round(time.time(), 3)
    return view


# ---------------------------------------------------------------------------
# federation v2 (ISSUE 15): the multi-DC merge behind
# /v1/internal/ui/federation, cluster_top --wan, debug_bundle --wan
# ---------------------------------------------------------------------------


def parse_dc_spec(spec: str) -> Dict[str, List[str]]:
    """"dc1=url|url,dc2=url" -> {dc: [urls]} — the CLI/--federation-http
    wire form (| separates URLs because , already separates DCs and
    URLs carry ':' and '=')."""
    out: Dict[str, List[str]] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        dc, _, urls = part.partition("=")
        if not dc or not urls:
            raise ValueError(f"malformed DC spec part {part!r} "
                             f"(want dc=url|url|...)")
        out.setdefault(dc, []).extend(
            u for u in urls.split("|") if u)
    return out


def scrape_federation(dc_nodes: Dict[str, Union[List[str],
                                                Dict[str, str]]],
                      events_limit: int = 50
                      ) -> Dict[str, List[Tuple[str, dict]]]:
    """One scrape pass over every DC's fleet -> {dc: scrape rows}.
    Split from federation_view for the same reason view_from_scrapes
    exists: debug_bundle --wan archives the raw per-node rows AND the
    merged view from ONE pass (a dead WAN link mid-incident costs one
    timeout per node, not two)."""
    return {dc: scrape_cluster(dc_nodes[dc], events_limit=events_limit)
            for dc in sorted(dc_nodes)}


def federation_from_scrapes(
        dc_scrapes: Dict[str, List[Tuple[str, dict]]]) -> dict:
    """Merge pre-fetched per-DC scrape rows into the federated view:
    one row per DC (leader, alive/degraded node sets, the leader's
    worst replication lag, the wakeup visibility quantiles), the full
    per-DC node tables, and ONE dc-tagged cross-DC event timeline.
    Degraded scrapes stay in the table (ISSUE 15 satellite) — a DC
    whose nodes half-answer renders as degraded rows, not absences."""
    view: dict = {"dcs": {}, "events": []}
    all_events: List[dict] = []
    for dc, scraped in sorted(dc_scrapes.items()):
        dcv = view_from_scrapes(scraped)
        for e in dcv.pop("events"):
            e["dc"] = dc
            all_events.append(e)
        lag = dcv.get("replication_lag") or {}
        wakeup = (dcv.get("visibility") or {}).get("wakeup") or {}
        # cross-DC replication divergence/lag (ISSUE 18): the leader's
        # replication set is the one whose rounds advance, so report
        # the node with the most rounds; the dynamic write_rate rides
        # the same per-node surface
        rep_best: list = []
        write_rate = None
        for _name, r in scraped:
            rep = r.get("replication") or {}
            rows = rep.get("replicators") or []
            if sum(s.get("Rounds", 0) for s in rows) > \
                    sum(s.get("Rounds", 0) for s in rep_best):
                rep_best = rows
            if rep.get("write_rate") is not None:
                write_rate = rep["write_rate"]
        replication = {
            "max_lag_s": round(max(
                (s.get("LagSeconds", 0.0) or 0.0
                 for s in rep_best), default=0.0), 3),
            "diverged": sorted(s["ReplicationType"] for s in rep_best
                               if s.get("Diverged")),
            "types": sorted(s["ReplicationType"] for s in rep_best),
        } if rep_best else None
        view["dcs"][dc] = {
            "leader": dcv["leader"],
            "nodes": dcv["nodes"],
            "replication_lag": lag,
            "visibility": dcv["visibility"],
            "alive": sum(1 for n in dcv["nodes"].values()
                         if n["alive"]),
            "degraded": sorted(
                n for n, v in dcv["nodes"].items()
                if v.get("degraded") or not v["alive"]),
            "lag_ms_max": max((r.get("ms", 0.0)
                               for r in lag.values()), default=0.0),
            "wakeup_p50_ms": wakeup.get("p50_ms"),
            "wakeup_p99_ms": wakeup.get("p99_ms"),
            "replication": replication,
            "write_rate": write_rate,
        }
    view["events"] = merge_timelines(all_events)
    view["generated_at"] = round(time.time(), 3)
    return view


def federation_view(dc_nodes: Dict[str, Union[List[str],
                                              Dict[str, str]]],
                    events_limit: int = 50) -> dict:
    """Scrape every DC and merge — see federation_from_scrapes."""
    return federation_from_scrapes(
        scrape_federation(dc_nodes, events_limit=events_limit))
