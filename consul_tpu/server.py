"""Server core: Raft-replicated state store behind Consul-shaped RPCs.

The reference's server (agent/consul/server.go:322 NewServer) owns the
raft engine, the FSM, the state store, and the RPC endpoints; writes
funnel through raftApply (rpc.go:730) and non-leaders forward to the
leader (rpc.go:549 ForwardRPC).  Same structure here:

    Server = StateStore (replica) + ServerFSM + RaftNode
    writes: Server.<mutation>() → leader lookup → raft.apply → quorum
            commit → every replica's FSM mutates its store
    reads:  local store (stale) or leader-verified (default/consistent,
            via a raft barrier — the reference's consistentRead uses
            VerifyLeader, rpc.go:~930)

Leader duties (the monitorLeadership/leaderLoop analogue,
agent/consul/leader.go:64,165) run inside tick(): session-TTL expiry is
*proposed* by the leader and applied by every replica, so timers stay a
leader concern while state changes replicate — exactly the reference's
split (session_ttl.go:45).

Servers discover each other through a process-local registry dict for
in-process clusters (SURVEY.md §4 tier 2) and, across process
boundaries, through the socket RPC layer (consul_tpu/rpc): serve_rpc()
binds a listener carrying raft frames and forwarded applies, and
raft_apply falls back to a remote "apply" call when the leader is not
in-process (ForwardRPC over the conn pool — agent/consul/rpc.go:549,
agent/pool/pool.go:542).
"""

from __future__ import annotations

import random
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from consul_tpu.catalog.store import StateStore
from consul_tpu.consensus.fsm import ServerFSM
from consul_tpu.consensus.raft import (
    NotLeaderError, RaftConfig, RaftNode, Transport,
)


class NoLeaderError(Exception):
    """No leader available within the retry budget (structs.ErrNoLeader)."""


_APPLY_TRANSIT_MARGIN = 0.25


def _apply_wait_budget(args: dict, default: float = 5.0,
                       cap: float = 10.0) -> float:
    """Commit-wait for a forwarded apply, derived from the CALLER's
    remaining RPC budget (shipped as `budget` by the forward
    coalescer, which grants up to 10 s).  A transit margin is reserved
    off the shipped budget so the DEFINITIVE response (success or the
    leader's own timeout error) still reaches the caller before its
    client.call deadline — waiting the full budget would hand a
    near-deadline commit to a caller that already gave up, exactly the
    ambiguity this path exists to narrow.  Falls back to the historic
    5 s for callers that don't ship a budget; never below 50 ms or
    above the coalescer's own cap."""
    import math
    try:
        b = float(args["budget"])
        if not math.isfinite(b):     # json accepts NaN/Infinity
            raise ValueError(b)
        b -= _APPLY_TRANSIT_MARGIN
    except (KeyError, TypeError, ValueError):
        b = default
    return min(cap, max(0.05, b))


class Server:
    def __init__(self, node_id: str, peers: List[str], transport: Transport,
                 registry: Dict[str, "Server"],
                 raft_config: Optional[RaftConfig] = None, seed: int = 0,
                 data_dir: Optional[str] = None, storage_io=None):
        self.node_id = node_id
        self.transport = transport
        self.store = StateStore()
        self.fsm = ServerFSM(self.store)
        self.registry = registry
        # data_dir → durable raft log + vote + snapshots (the
        # raft-boltdb + FileSnapshotStore role, server.go:728): a
        # kill -9 of the whole fleet recovers to the last commit.
        # `storage_io` is the storage.py seam instance the WAL writes
        # through — the live nemesis threads a chaos.FaultyStorage in
        # here (tools/server_proc.py --storage-faults) so a real server
        # PROCESS can suffer torn-disk power loss.
        durable = None
        if data_dir:
            from consul_tpu.consensus.logstore import DurableLog
            import os
            durable = DurableLog(os.path.join(data_dir, "raft"),
                                 io=storage_io)
        self.raft = RaftNode(
            node_id, peers, transport,
            apply_fn=self.fsm.apply,
            snapshot_fn=self.store.snapshot,
            restore_fn=self.store.load_snapshot,
            config=raft_config, seed=seed, store=durable)
        if hasattr(transport, "register"):
            transport.register(self.raft)
        registry[node_id] = self
        self._ttl_reap_inflight: set = set()
        self._listener = None
        self._rpc_client = None
        # forward coalescer (group commit for quorum writes): applies
        # that must travel to a remote leader queue here and drain as
        # one apply_batch RPC per round trip
        self._fwd_cv = threading.Condition()
        self._fwd_q: list = []
        self._fwd_thread = None
        self._fwd_running = False
        self._fwd_closed = False
        self.tls = None
        self._bootstrap_token = None
        # auto-config: auth-method name that validates intro JWTs
        # (None/empty = disabled) and the config fields pushed to
        # bootstrapping clients (auto_config_endpoint.go)
        self.auto_config_method: Optional[str] = None
        self.auto_config_settings: Dict[str, Any] = {}
        from consul_tpu.autopilot import Autopilot
        self.autopilot = Autopilot(self)
        # apply-path admission control (ISSUE 13): bounded-queue +
        # deadline admission STRICTLY BEFORE the raft append, so a
        # rejection is an unambiguous NACK — the write was never
        # proposed (consul_tpu/ratelimit.py ApplyGate).  Set to None
        # (or .enabled = False) to disable; leader-internal housekeeping
        # (_leader_propose: session reaping, member reconcile) bypasses
        # the gate by design — shedding the reconciler would trade
        # overload for unbounded catalog drift.
        from consul_tpu.ratelimit import ApplyGate
        self.apply_gate: Optional[ApplyGate] = ApplyGate()

    # --------------------------------------------------------------- rpc net

    def serve_rpc(self, host: str = "127.0.0.1", port: int = 0,
                  tls=None, bootstrap_token: str = None):
        """Bind the socket RPC listener (raft frames + forwarded applies)
        and advertise our address in the transport's address book.
        Returns (host, port).

        `tls` is a tlsutil.Configurator: the listener upgrades incoming
        connections (requiring client certs under verify_incoming), the
        transport + forwarding client present this server's cert, and
        auto_encrypt_sign RPCs mint agent certs off the same CA."""
        from consul_tpu.rpc import RpcClient, RpcListener
        self.tls = tls
        ssl_in = ssl_out = sni = None
        if tls is not None:
            cert, key = tls.sign_cert(self.node_id, server=True)
            ssl_in = tls.incoming_context(cert, key)
            ssl_out = tls.outgoing_context(cert, key)
            sni = tls.server_sni() if tls.verify_server_hostname else None
        self._listener = RpcListener(self.raft.deliver, self._handle_rpc,
                                     host=host, port=port,
                                     ssl_context=ssl_in)
        self._listener.start()
        self._bootstrap_listener = None
        self._bootstrap_token = bootstrap_token
        if tls is not None and tls.verify_incoming \
                and bootstrap_token:
            # secure by default: no bootstrap token configured means no
            # unauthenticated cert-minting surface at all
            # the reference's insecure RPC server (server.go:240-247):
            # ONE method, no client cert required — so a fresh agent can
            # obtain its first cert at all
            def _bootstrap_only(method, args):
                # the insecure listener's whole surface: first-cert
                # issuance + JWT-authorized config push (server.go:
                # 240-247 registers exactly AutoEncrypt.Sign +
                # AutoConfig.InitialConfiguration)
                if method not in ("auto_encrypt_sign", "auto_config"):
                    raise ValueError("bootstrap listener serves "
                                     "auto_encrypt_sign/auto_config "
                                     "only")
                return self._handle_rpc(method, args)

            boot_ctx = tls.bootstrap_context(cert, key)
            self._bootstrap_listener = RpcListener(
                lambda msg: None, _bootstrap_only, host=host,
                ssl_context=boot_ctx)
            self._bootstrap_listener.start()
        self._rpc_client = RpcClient(ssl_context=ssl_out,
                                     server_hostname=sni)
        if ssl_out is not None and hasattr(self.transport, "set_tls"):
            self.transport.set_tls(ssl_out, sni)
        if hasattr(self.transport, "addresses"):
            self.transport.addresses[self.node_id] = self._listener.addr
        return self._listener.addr

    def close_rpc(self) -> None:
        if hasattr(self.transport, "addresses"):
            self.transport.addresses.pop(self.node_id, None)
        if getattr(self, "_bootstrap_listener", None) is not None:
            self._bootstrap_listener.stop()
            self._bootstrap_listener = None
        if self._listener is not None:
            self._listener.stop()
            self._listener = None
        with self._fwd_cv:
            self._fwd_running = False
            # a write racing stop() must not resurrect the forwarder
            # (it would spin forever with nothing left to join it)
            self._fwd_closed = True
            self._fwd_cv.notify_all()
        if self._fwd_thread is not None:
            self._fwd_thread.join(timeout=2.0)
            self._fwd_thread = None
        if self._rpc_client is not None:
            self._rpc_client.close()
            self._rpc_client = None

    # ---------------------------------------------------- forward coalescer

    _FWD_MAX_BATCH = 128
    # Consul's rpcHoldTimeout (agent/consul/config.go RPCHoldTimeout,
    # 7s): during a leader election, forwarded RPCs are HELD and
    # retried rather than failed into the election window
    _RPC_HOLD_TIMEOUT = 7.0

    def _hold_for_leader(self, budget_s: float) -> bool:
        """rpcHoldTimeout behavior for a forwarded apply that lands
        mid-election: hold (bounded by the caller's remaining budget
        and the 7 s cap) until leadership settles.  Returns True when
        THIS node emerged as leader (serve the apply); False when a
        leader settled elsewhere (bounce with the fresh hint — the
        caller re-forwards, re-forwarding from here could loop) or the
        cluster stayed leaderless past the hold."""
        deadline = time.time() + max(0.0, min(budget_s,
                                              self._RPC_HOLD_TIMEOUT))
        backoff = 0.005
        while True:
            if self.raft.is_leader():
                return True
            lid = self.raft.leader_id
            if lid is not None and lid != self.node_id:
                return False
            if time.time() >= deadline:
                return False
            time.sleep(backoff * (0.5 + random.random()))
            backoff = min(backoff * 2.0, 0.05)

    def _forward_apply(self, op: str, args: dict, timeout: float):
        """Queue one apply for the remote leader and wait.  A single
        forwarder thread drains the queue, sending everything queued as
        ONE apply_batch RPC — concurrent writers on this server cost
        one forwarded round trip and one raft append round between
        them (group commit), instead of a socket RPC each."""
        from consul_tpu import trace
        from consul_tpu.rpc import RpcError
        item = {"op": op, "args": args, "event": threading.Event(),
                "result": None, "error": None,
                "trace": trace.current_trace(),
                "deadline": time.time() + timeout}
        with self._fwd_cv:
            if self._fwd_closed:
                raise NoLeaderError("server RPC is closed")
            if not self._fwd_running:
                self._fwd_running = True
                self._fwd_thread = threading.Thread(
                    target=self._forward_loop, daemon=True,
                    name=f"fwd-{self.node_id}")
                self._fwd_thread.start()
            self._fwd_q.append(item)
            self._fwd_cv.notify()
        # the forwarded leg of the write, follower-side (ForwardRPC):
        # one span per caller covering queue + socket round trip
        with trace.span("rpc.forward", trace_id=item["trace"] or "",
                        op=op, node=self.node_id):
            done = item["event"].wait(timeout)
        if not done:
            raise TimeoutError(f"forwarded apply {op} timed out")
        if item["error"] is not None:
            err = item["error"]
            raise err if isinstance(err, Exception) else RpcError(err)
        return item["result"]

    def _forward_loop(self) -> None:
        from consul_tpu import telemetry
        from consul_tpu.rpc import RpcError
        while True:
            with self._fwd_cv:
                while not self._fwd_q and self._fwd_running:
                    self._fwd_cv.wait(0.5)
                if not self._fwd_running and not self._fwd_q:
                    return
                items = self._fwd_q[:self._FWD_MAX_BATCH]
                del self._fwd_q[:self._FWD_MAX_BATCH]
            # an item whose caller already timed out (and was told the
            # write FAILED) must not be transmitted on its behalf —
            # that would widen the failed-but-later-applied ambiguity
            # window beyond the caller's own budget
            now = time.time()
            stale = [it for it in items if it["deadline"] <= now]
            for it in stale:
                it["error"] = TimeoutError("forward abandoned: caller "
                                           "deadline passed")
                it["event"].set()
            items = [it for it in items if it["deadline"] > now]
            if not items:
                continue
            # RPC budget: the longest remaining caller deadline (a
            # near-expired caller must not sink the whole batch; its
            # own event.wait still returns on ITS deadline, and the
            # ambiguity window is bounded by the in-batch spread)
            budget = min(10.0, max(0.05, max(it["deadline"]
                                             for it in items) - now))
            # leader resolved at drain time: a change between enqueue
            # and send surfaces as an error and the caller's
            # raft_apply retry loop re-resolves
            addr = self._remote_addr(self.leader_id or "")
            client = self._rpc_client
            if addr is None or client is None:
                # ErrNoLeader mid-election: hold and retry with
                # jittered backoff inside each caller's remaining
                # budget (rpcHoldTimeout) instead of failing the batch
                # into the election window.  Callers whose budget ran
                # out (or a closing server) fail now.
                now = time.time()
                with self._fwd_cv:
                    closing = self._fwd_closed or not self._fwd_running
                err = NoLeaderError("no leader address to forward to")
                keep = []
                for it in items:
                    if closing or it["deadline"] - now <= 0.05 \
                            or client is None:
                        it["error"] = err
                        it["event"].set()
                    else:
                        keep.append(it)
                if keep:
                    time.sleep(0.02 * (0.5 + random.random()))
                    with self._fwd_cv:
                        self._fwd_q[:0] = keep
                continue
            telemetry.incr_counter(("rpc", "forward", "rounds"))
            telemetry.incr_counter(("rpc", "forward", "items"),
                                   len(items))
            try:
                if len(items) == 1:
                    it = items[0]
                    # ship the remaining RPC budget with the call: the
                    # leader waits for commit up to the CALLER's
                    # deadline, not a fixed server-side constant —
                    # narrowing the window where a caller is told
                    # "timed out" for a write that later applies
                    it["result"] = client.call(
                        addr, "apply",
                        {"op": it["op"], "args": it["args"],
                         "trace": it["trace"], "budget": budget},
                        timeout=budget)
                    it["event"].set()
                    continue
                out = client.call(
                    addr, "apply_batch",
                    {"items": [{"op": it["op"], "args": it["args"],
                                "trace": it["trace"]}
                               for it in items],
                     "budget": budget},
                    timeout=budget)
                results = (out or {}).get("results") or []
                errors = (out or {}).get("errors") or []
                for i, it in enumerate(items):
                    it["result"] = results[i] if i < len(results) \
                        else None
                    e = errors[i] if i < len(errors) else None
                    it["error"] = RpcError(e) if e else None
                    it["event"].set()
            except Exception as e:
                for it in items:
                    if not it["event"].is_set():
                        it["error"] = e
                        it["event"].set()

    def _admit_apply(self, n_items: int, budget_s: float) -> None:
        """Apply-path admission (ratelimit.ApplyGate): NACK — raise
        ApplyRejectedError — when the pending apply queue is at its
        bound or the caller's remaining budget cannot cover a commit
        wait.  Called strictly BEFORE raft.apply_many so a rejection
        proves non-commitment."""
        gate = self.apply_gate
        if gate is None or not gate.enabled:
            return
        gate.admit(self.raft.pending_count(), n_items, budget_s)

    def _handle_rpc(self, method: str, args: dict):
        """Server-side forwarded calls (the RPC endpoints the mux routes
        to, agent/consul/rpc.go:130).  'apply' rejects at a non-leader —
        the caller targeted us as leader; re-forwarding could loop.

        New methods must also enter rpc/net.py _KNOWN_METHODS (the
        per-method metric label allowlist) — test_rpc enforces the
        pairing."""
        from consul_tpu import trace
        if method == "apply":
            t_in = time.time()
            if not self.raft.is_leader() \
                    and not self._hold_for_leader(_apply_wait_budget(args)):
                raise NotLeaderError(self.raft.leader_id)
            # wait for commit as long as the CALLER still has RPC
            # budget (the coalescer ships its remaining deadline in
            # `budget`, granted up to 10 s) — a fixed 5.0 s here
            # reported "apply timed out" to callers that still had
            # budget, widening the failed-but-later-applied ambiguity
            # window (ADVICE r5).  Clamped: a missing/garbage budget
            # falls back to the old constant, never waits > 10 s.
            # Whatever the election hold consumed comes OFF the wait:
            # hold + commit-wait together must fit the caller's budget
            # or the definitive response lands after it hung up.
            wait_s = max(0.05,
                         _apply_wait_budget(args) - (time.time() - t_in))
            # admission BEFORE the append: a NACK here proves the
            # write never entered the log (ratelimit.ApplyGate)
            self._admit_apply(1, wait_s)
            with trace.span("leader.apply", trace_id=args.get("trace"),
                            op=args.get("op"), node=self.node_id):
                t_commit = time.perf_counter()
                pend = self.raft.apply_many(
                    [{"op": args["op"],
                      "args": args.get("args") or {}}],
                    trace_ids=[args.get("trace")])[0]
                if not pend.event.wait(wait_s):
                    raise TimeoutError("apply timed out")
                if self.apply_gate is not None:
                    self.apply_gate.observe_commit(
                        time.perf_counter() - t_commit)
            if pend.error is not None:
                raise pend.error
            return pend.result
        if method == "apply_batch":
            # group commit for forwarded writes: one raft append round
            # for the whole batch, per-item results/errors (the
            # reference batches at the msgpack chunking layer;
            # coalescing concurrent forwards is the same lever)
            t_in = time.time()
            if not self.raft.is_leader() \
                    and not self._hold_for_leader(_apply_wait_budget(args)):
                raise NotLeaderError(self.raft.leader_id)
            # batch admission: admit or shed the batch as a unit —
            # the coalescer already grouped these callers, and a
            # partial admit would hand half of them a NACK whose
            # reason ("queue_full") the other half just caused
            self._admit_apply(
                len(args["items"]),
                max(0.05, _apply_wait_budget(args)
                    - (time.time() - t_in)))
            t_wall, t0 = time.time(), time.perf_counter()
            pends = self.raft.apply_many(
                [{"op": it["op"], "args": it.get("args") or {}}
                 for it in args["items"]],
                trace_ids=[it.get("trace") for it in args["items"]])
            # group-commit wait bounded by the batch's shipped RPC
            # budget (= the longest remaining caller deadline) MINUS
            # whatever the election hold consumed, floored like the
            # "apply" branch so a budget-eating hold still leaves the
            # appended batch a sliver to commit rather than reporting
            # instant timeouts for entries already in the log
            deadline = time.time() + max(
                0.05, _apply_wait_budget(args) - (time.time() - t_in))
            results, errors = [], []
            for pend in pends:
                if not pend.event.wait(max(0.0,
                                           deadline - time.time())):
                    results.append(None)
                    errors.append("apply timed out")
                elif pend.error is not None:
                    results.append(None)
                    errors.append(f"{type(pend.error).__name__}: "
                                  f"{pend.error}")
                else:
                    results.append(pend.result)
                    errors.append(None)
            # one leader.apply span per batched item, each under ITS
            # caller's trace id (the shared wait is the group commit)
            dur = time.perf_counter() - t0
            if self.apply_gate is not None and any(
                    e is None for e in errors):
                # feed the deadline EMA only from commits that landed
                self.apply_gate.observe_commit(dur)
            for it in args["items"]:
                trace.record("leader.apply", it.get("trace"), t_wall,
                             dur, op=it.get("op"), node=self.node_id,
                             batched=len(args["items"]))
            return {"results": results, "errors": errors}
        if method == "barrier":
            if not self.raft.is_leader():
                raise NotLeaderError(self.raft.leader_id)
            pend = self.raft.barrier()
            if not pend.event.wait(5.0) or pend.error is not None:
                raise TimeoutError("barrier failed")
            return {"index": self.store.index}
        if method == "stats":
            return self.stats()
        if method == "auto_encrypt_sign":
            # agent bootstrap cert issuance (auto_encrypt_endpoint.go
            # Sign — the reference gates this with an ACL token; a cert
            # minted without ANY credential would turn network
            # reachability into full RPC write access)
            if self.tls is None:
                raise ValueError("TLS not configured")
            token = args.get("token", "")
            if not self._bootstrap_token \
                    or token != self._bootstrap_token:
                raise PermissionError("auto-encrypt: invalid token")
            cert, key = self.tls.sign_cert(args.get("name", "agent"))
            return {"cert": cert, "key": key, "ca": self.tls.ca_pem}
        if method == "auto_config":
            # JWT-authorized client bootstrap (AutoConfig.
            # InitialConfiguration, agent/consul/auto_config_endpoint.go):
            # the intro JWT validates against the configured auth
            # method, binding rules mint the agent's ACL token (the
            # write replicates through raft), and the response carries
            # runtime-config fields + TLS material
            from consul_tpu.acl.authmethod import AuthError, login
            if not self.auto_config_method:
                raise PermissionError("auto-config not enabled")
            try:
                accessor, secret, policies = login(
                    self, self.auto_config_method,
                    args.get("jwt", ""))
            except AuthError as e:
                raise PermissionError(f"auto-config: {e}") from None
            node = args.get("node_name", "agent")
            out = {
                "accessor": accessor,
                "token": secret,
                "policies": policies,
                "config": dict(self.auto_config_settings,
                               node_name=node),
            }
            if self.tls is not None:
                cert, key = self.tls.sign_cert(node)
                out["cert"], out["key"] = cert, key
                out["ca"] = self.tls.ca_pem
            return out
        raise ValueError(f"unknown rpc method {method}")

    def _remote_addr(self, node_id: str):
        if self._rpc_client is None:
            return None
        addrs = getattr(self.transport, "addresses", None)
        if not addrs:
            return None
        return addrs.get(node_id)

    # ------------------------------------------------------------------ tick

    def tick(self, now: Optional[float] = None) -> None:
        now = now if now is not None else time.time()
        self.raft.tick(now)
        if self.raft.is_leader():
            self._leader_duties(now)

    def attach_oracle(self, oracle, reconcile_interval: float = 1.0,
                      reap_timeout: float = 72 * 3600.0) -> None:
        """Wire the gossip oracle so THIS raft leader runs serf→catalog
        reconciliation (the reference's leaderLoop: reconcileMember
        leader.go:1187, handleFailedMember :1332, reap :1390) — every
        catalog mutation proposes through raft, so followers converge.
        `reap_timeout`: failed members deregister after this long
        (serf reconnect_timeout, 72h default)."""
        self._oracle = oracle
        self._reconcile_interval = reconcile_interval
        self._reap_timeout = reap_timeout
        self._last_reconcile = 0.0
        self._failed_since = {}

    _oracle = None
    _reconcile_inflight = False
    _reconcile_interval = 1.0
    _last_reconcile = 0.0

    def _leader_duties(self, now: float) -> None:
        # autopilot: server health + dead-server cleanup (autopilot.go:67)
        self.autopilot.run(now)
        # session TTL sweep: propose destroys, don't block the tick thread
        for sid in self.store.peek_expired_sessions(now):
            if sid in self._ttl_reap_inflight:
                continue
            try:
                self.raft.apply({"op": "session_destroy",
                                 "args": {"sid": sid, "now": now}})
                self._ttl_reap_inflight.add(sid)
            except NotLeaderError:
                break
        self._ttl_reap_inflight &= set(
            s["id"] for s in self.store.session_list())
        # serf→catalog reconcile + session-check invalidation, interval-
        # gated and OFF the tick thread — leader-only + raft-proposed.
        # Runs on a worker thread: members() may sync the device (first
        # call compiles for seconds), the session scan is
        # O(sessions x checks), and a stalled tick thread stops
        # heartbeats → leadership churn (lib/routine.Manager role).
        if now - self._last_reconcile >= self._reconcile_interval \
                and not self._reconcile_inflight:
            self._last_reconcile = now
            self._reconcile_inflight = True

            def work(now=now):
                from consul_tpu import telemetry
                t0 = time.perf_counter()
                try:
                    self._invalidate_sessions_on_checks(now)
                    if self._oracle is not None:
                        self._reconcile_members(now)
                finally:
                    self._reconcile_inflight = False
                    # consul.leader.reconcile: the serf→catalog sweep
                    # duration (leader.go:196's leaderLoop timers)
                    telemetry.measure_since(("leader", "reconcile"), t0)

            threading.Thread(target=work, daemon=True).start()

    def _invalidate_sessions_on_checks(self, now: float) -> None:
        for sess in self.store.session_list():
            sid = sess["id"]
            if sid in self._ttl_reap_inflight:
                continue  # destroy already proposed, not yet applied
            node_checks = {c["check_id"]: c["status"]
                           for c in self.store.node_checks(sess["node"])}
            for cid in sess.get("checks") or []:
                if node_checks.get(cid) == "critical":
                    try:
                        # pin `now` at the proposer: replicas computing
                        # lock-delay expiry from their own clocks would
                        # diverge (store.py determinism invariant)
                        result = self._leader_propose(
                            "session_destroy", sid=sid, now=now)
                        if result is not None:
                            # only confirmed commits enter the dedup set
                            # — a proposal lost to deposition would pin
                            # the sid forever (destroy is idempotent, so
                            # a timed-out retry next round is safe)
                            self._ttl_reap_inflight.add(sid)
                    except NotLeaderError:
                        return
                    break

    def _leader_propose(self, op: str, timeout: float = 2.0, **args):
        """Propose on THIS node only — a deposed leader's worker must
        abort, never forward its stale snapshot to the new leader
        (raft_apply would forward)."""
        pend = self.raft.apply({"op": op, "args": args})
        pend.event.wait(timeout)
        return pend.result

    def _reconcile_members(self, now: float) -> None:
        """handleAliveMember/handleFailedMember/handleReapMember
        (leader.go:1234-1432) driven from oracle membership, with every
        write a raft proposal."""
        catalog = {n["node"] for n in self.store.nodes()}
        try:
            members = self._oracle.members()
        except Exception:
            return
        member_names = {m["name"] for m in members}
        # drop stale failed-timers for members no longer tracked: a
        # deregistered-then-rejoining node must get a fresh reap window
        for stale in set(self._failed_since) - (member_names & catalog):
            self._failed_since.pop(stale, None)
        for m in members:
            if not self.raft.is_leader():
                return  # deposed mid-loop: stop writing
            name = m["name"]
            if name not in catalog:
                continue
            checks = {c["check_id"]: c
                      for c in self.store.node_checks(name)}
            sh = checks.get("serfHealth")
            try:
                if m["status"] == "failed":
                    since = self._failed_since.setdefault(name, now)
                    if now - since >= self._reap_timeout:
                        # reap: the member stayed failed past
                        # reconnect_timeout — deregister entirely
                        self._leader_propose("deregister_node", node=name)
                        self._failed_since.pop(name, None)
                    elif sh is None or sh["status"] != "critical":
                        self._leader_propose(
                            "register_check", node=name,
                            check_id="serfHealth",
                            name="Serf Health Status",
                            status="critical",
                            output="Agent not live or unreachable")
                elif m["status"] == "left":
                    self._failed_since.pop(name, None)
                    self._leader_propose("deregister_node", node=name)
                else:
                    self._failed_since.pop(name, None)
                    if sh is not None and sh["status"] != "passing":
                        self._leader_propose(
                            "register_check", node=name,
                            check_id="serfHealth",
                            name="Serf Health Status",
                            status="passing",
                            output="Agent alive and reachable")
            except (NotLeaderError, NoLeaderError):
                return

    # ------------------------------------------------------------ raft apply

    def is_leader(self) -> bool:
        return self.raft.is_leader()

    @property
    def leader_id(self) -> Optional[str]:
        return self.raft.leader_id if not self.raft.is_leader() \
            else self.node_id

    def raft_apply(self, op: str, timeout: float = 5.0, **args) -> Any:
        """Propose on the leader (forwarding like ForwardRPC, rpc.go:549)
        and wait for FSM apply.  Retries once across leader changes.

        An ApplyRejectedError — the leader's admission NACK — escapes
        IMMEDIATELY, never retried: the NACK is load shedding, and a
        retry loop would re-offer the exact load the gate just shed
        (clients back off with their own policy instead)."""
        from consul_tpu.ratelimit import ApplyRejectedError
        from consul_tpu.rpc import RpcError
        deadline = time.time() + timeout
        last_err: Optional[Exception] = None
        # jittered exponential backoff across leader-change retries
        # (the reference's retry loop under rpcHoldTimeout): flat
        # 10 ms polling hammered the deposed leader during elections
        backoff = 0.005

        def _pause():
            nonlocal backoff
            time.sleep(min(backoff * (0.5 + random.random()),
                           max(0.0, deadline - time.time())))
            backoff = min(backoff * 2.0, 0.05)

        while time.time() < deadline:
            leader = self.leader_id
            target = self if self.raft.is_leader() else \
                self.registry.get(leader or "")
            if target is None:
                # leader not in-process: forward over the socket RPC
                # through the coalescer (concurrent applies batch into
                # one apply_batch round), bounded by the caller's
                # remaining budget
                if self._remote_addr(leader or "") is not None:
                    try:
                        out = self._forward_apply(
                            op, args,
                            timeout=max(0.05, deadline - time.time()))
                        if out is not None:
                            self._bind_visibility(out)
                            return out
                        # a None result means the remote apply raced a
                        # deposition — retry within the deadline rather
                        # than hand callers a non-dict
                        last_err = RpcError("empty apply result")
                    except (RpcError, TimeoutError,
                            NoLeaderError) as e:
                        # a forwarded admission NACK arrives as an
                        # RpcError string — reconstruct it so the NACK
                        # stays a definite failure on this side too
                        rej = ApplyRejectedError.from_rpc(str(e))
                        if rej is not None:
                            raise rej from None
                        last_err = e
                _pause()
                continue
            try:
                # in-process leader: same admission the RPC handlers
                # run, before the append (the NACK escapes — see
                # docstring)
                target._admit_apply(
                    1, max(0.05, deadline - time.time()))
                pend = target.raft.apply({"op": op, "args": args})
            except NotLeaderError as e:
                last_err = e
                _pause()
                continue
            if pend.event.wait(max(0.0, deadline - time.time())):
                if pend.error is not None:
                    last_err = pend.error
                    continue
                self._bind_visibility(pend.result)
                return pend.result
            last_err = TimeoutError(f"raft apply {op} timed out")
            break
        raise NoLeaderError(str(last_err))

    def _bind_visibility(self, result) -> None:
        """Proposer-side commit-to-visibility correlation: the apply
        result carried the store index this write landed at — bind the
        request's trace id to it (late upsert; the FSM-side
        `visibility.applying` scope already stamped it on the node
        that ran the apply, this covers the FORWARDING node's own
        replica, whose apply arrives by replication without a trace)."""
        if isinstance(result, dict) and "index" in result:
            from consul_tpu import trace
            self.store.visibility.bind_trace(result["index"],
                                             trace.current_trace())

    # ------------------------------------------------------- read plane
    # The follower-read surface consul_tpu/readplane.py duck-types:
    # a bare StateStore has none of these and is treated as 0-stale.

    def read_staleness(self) -> float:
        """Seconds this replica's readable state may trail an acked
        write (0.0 on the leader) — the ?max_stale enforcement bound."""
        return self.raft.staleness()

    def known_leader(self) -> bool:
        return self.raft.known_leader

    def last_contact_ms(self) -> float:
        """Milliseconds since last leader contact (0 on the leader) —
        the X-Consul-LastContact header value."""
        s = self.raft.last_contact_s()
        return 0.0 if s == float("inf") else s * 1000.0

    def consistent_index(self, timeout: float = 5.0) -> int:
        """Leader barrier — readers wanting ?consistent semantics call this
        first (VerifyLeader / consistentRead)."""
        from consul_tpu.rpc import RpcError
        target = self if self.raft.is_leader() else \
            self.registry.get(self.raft.leader_id or "")
        if target is None:
            addr = self._remote_addr(self.raft.leader_id or "")
            if addr is not None:
                try:
                    return self._rpc_client.call(
                        addr, "barrier", {}, timeout=timeout)["index"]
                except (RpcError, TimeoutError) as e:
                    raise NoLeaderError(str(e))
            raise NoLeaderError("no leader for consistent read")
        pend = target.raft.barrier()
        if not pend.event.wait(timeout) or pend.error is not None:
            raise NoLeaderError("barrier failed")
        return target.store.index

    # --------------------------------------------------- replicated mutations
    # Same signatures as StateStore so the HTTP layer can take either
    # (duck-typed "write surface"); ids are generated here, proposer-side.

    def kv_set(self, key, value, flags=0, cas=None, acquire=None,
               release=None):
        r = self.raft_apply("kv_set", key=key,
                            value=value.decode("latin-1")
                            if isinstance(value, bytes) else value,
                            flags=flags, cas=cas, acquire=acquire,
                            release=release)
        return r["ok"], r["index"]

    def kv_delete(self, key, recurse=False, cas=None):
        r = self.raft_apply("kv_delete", key=key, recurse=recurse, cas=cas)
        return r["ok"], r["index"]

    def txn(self, ops):
        safe_ops = [dict(op, value=op["value"].decode("latin-1"))
                    if isinstance(op.get("value"), bytes) else dict(op)
                    for op in ops]
        r = self.raft_apply("txn", ops=safe_ops)
        results = [x if not isinstance(x, dict) else
                   dict(x, value=x["value"].encode("latin-1")
                        if isinstance(x.get("value"), str) else
                        x.get("value"))
                   for x in r["results"]]
        return r["ok"], results, r["index"]

    def register_node(self, node, address, meta=None, node_id=None):
        return self.raft_apply(
            "register_node", node=node, address=address, meta=meta,
            node_id=node_id or str(uuid.uuid4()))["index"]

    def register_service(self, node, service_id, name, port=0, tags=None,
                         meta=None, address="", kind="", proxy=None):
        return self.raft_apply(
            "register_service", node=node, service_id=service_id, name=name,
            port=port, tags=tags, meta=meta, address=address,
            kind=kind, proxy=proxy)["index"]

    def register_check(self, node, check_id, name, status="critical",
                       service_id="", output=""):
        return self.raft_apply(
            "register_check", node=node, check_id=check_id, name=name,
            status=status, service_id=service_id, output=output)["index"]

    def update_check(self, node, check_id, status, output=""):
        r = self.raft_apply("update_check", node=node, check_id=check_id,
                            status=status, output=output)
        if "error" in r:
            raise KeyError(r["error"])
        return r["index"]

    def deregister_node(self, node):
        return self.raft_apply("deregister_node", node=node)["index"]

    def deregister_service(self, node, service_id):
        return self.raft_apply("deregister_service", node=node,
                               service_id=service_id)["index"]

    def deregister_check(self, node, check_id):
        return self.raft_apply("deregister_check", node=node,
                               check_id=check_id)["index"]

    def session_create(self, node, ttl=0.0, behavior="release",
                       lock_delay=15.0, checks=None, sid=None):
        r = self.raft_apply("session_create", sid=sid or str(uuid.uuid4()),
                            node=node, ttl=ttl, behavior=behavior,
                            lock_delay=lock_delay, checks=checks,
                            now=time.time())
        if "error" in r:
            raise KeyError(r["error"])
        return r["id"], r["index"]

    def session_renew(self, sid):
        return self.raft_apply("session_renew", sid=sid,
                               now=time.time())["ok"]

    def session_destroy(self, sid):
        return self.raft_apply("session_destroy", sid=sid,
                               now=time.time())["index"]

    def acl_policy_set(self, pid, name, rules, description=""):
        r = self.raft_apply("acl_policy_set", pid=pid, name=name,
                            rules=rules, description=description)
        if "error" in r:
            raise ValueError(r["error"])
        return r["index"]

    def acl_policy_delete(self, pid):
        return self.raft_apply("acl_policy_delete", pid=pid)["index"]

    def acl_token_set(self, accessor, secret, policies=None, description="",
                      token_type="client", local=False,
                      service_identities=None, node_identities=None):
        return self.raft_apply(
            "acl_token_set", accessor=accessor, secret=secret,
            policies=policies, description=description,
            token_type=token_type, local=local,
            service_identities=service_identities,
            node_identities=node_identities)["index"]

    def acl_token_delete(self, accessor):
        return self.raft_apply("acl_token_delete", accessor=accessor)["index"]

    def acl_bootstrap(self, accessor, secret):
        r = self.raft_apply("acl_bootstrap", accessor=accessor, secret=secret)
        return r["ok"], r["index"]

    def query_set(self, qid, query):
        r = self.raft_apply("query_set", qid=qid, query=query)
        if "error" in r:
            raise ValueError(r["error"])
        return r["index"]

    def query_delete(self, qid):
        return self.raft_apply("query_delete", qid=qid)["index"]

    def intention_set(self, iid, source, destination, action,
                      description="", meta=None):
        r = self.raft_apply("intention_set", iid=iid, source=source,
                            destination=destination, action=action,
                            description=description, meta=meta)
        if "error" in r:
            raise ValueError(r["error"])
        return r["index"]

    def intention_delete(self, iid):
        return self.raft_apply("intention_delete", iid=iid)["index"]

    def config_entry_set(self, kind, name, body):
        r = self.raft_apply("config_entry_set", kind=kind, name=name,
                            body=body)
        if "error" in r:
            raise ValueError(r["error"])
        return r["index"]

    def config_entry_delete(self, kind, name):
        return self.raft_apply("config_entry_delete", kind=kind,
                               name=name)["index"]

    def coordinate_batch_update(self, updates):
        return self.raft_apply("coordinate_batch_update",
                               updates=updates)["index"]

    # ------------------------------------------------------------- read side
    # Stale reads hit the local replica directly; the HTTP layer decides.

    def __getattr__(self, name):
        # read-only store surface (kv_get, service_nodes, wait_for, ...);
        # guard against recursion during __init__ before `store` exists
        if name == "store":
            raise AttributeError(name)
        return getattr(self.store, name)

    def stats(self) -> dict:
        s = self.raft.stats()
        s["node_id"] = self.node_id
        s["store_index"] = self.store.index
        return s


class ServerCluster:
    """In-process multi-server fixture + wall-clock driver (the reference's
    test tier 2 made a first-class runtime object)."""

    def __init__(self, n: int = 3, raft_config: Optional[RaftConfig] = None,
                 transport: Optional[Transport] = None, seed: int = 0):
        from consul_tpu.consensus.raft import InMemTransport
        self.transport = transport or InMemTransport(seed=seed)
        self.registry: Dict[str, Server] = {}
        ids = [f"server{i}" for i in range(n)]
        self.servers = [Server(i, ids, self.transport, self.registry,
                               raft_config=raft_config, seed=seed)
                        for i in ids]
        self._running = False
        self._thread: Optional[threading.Thread] = None

    # virtual-clock stepping (tests)
    def step(self, seconds: float, dt: float = 0.01,
             start: Optional[float] = None) -> float:
        now = start if start is not None else getattr(self, "_vnow", 0.0)
        end = now + seconds
        while now < end:
            now += dt
            for s in self.servers:
                s.tick(now)
        self._vnow = now
        return now

    def wait_leader(self, max_s: float = 5.0) -> Server:
        for _ in range(int(max_s / 0.1)):
            self.step(0.1)
            leaders = [s for s in self.servers if s.is_leader()]
            if len(leaders) == 1:
                return leaders[0]
        raise RuntimeError("no leader elected")

    # wall-clock driving (live agents)
    def start(self, tick_seconds: float = 0.01) -> None:
        self._running = True

        def loop():
            while self._running:
                for s in self.servers:
                    s.tick(time.time())
                time.sleep(tick_seconds)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        if self._thread:
            self._thread.join(timeout=5.0)

    def leader(self) -> Optional[Server]:
        leaders = [s for s in self.servers if s.is_leader()]
        return leaders[0] if len(leaders) == 1 else None
