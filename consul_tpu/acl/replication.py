"""Cross-DC ACL replication: primary → secondary token/policy sync.

The reference replicates ACL state from the primary datacenter with
rate-limited, index-based round loops (agent/consul/replication.go
Replicator; acl_replication.go diffACLPolicies/diffACLTokens; started
from the leader loop, leader.go:873-896).  Same structure here: each
round lists the primary's policies and tokens, diffs against the local
secondary store by modify_index, and applies upserts + deletes.  Local
tokens (`local: true`) never replicate (the reference's local-token
carve-out).

Divergence CHECKING (ISSUE 18): each replicator also carries a
content-hash divergence checker — `snapshot()` canonicalizes the
replicated payload class on either store, `check_divergence()`
compares the two hashes, and the outcome feeds the
`consul.replication.{lag,diverged}{type}` SLIs plus the
`replication.{diverged,converged}` flight transitions.  Under a WAN
partition the primary list fails, lag grows from the last proven-sync
stamp, and the secondary is marked diverged; after heal one clean
round converges it back.  The live chaos family
(`chaos_live.live_wan_partition`) asserts exactly that arc.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from typing import Callable, Optional, Tuple

from consul_tpu import telemetry


class Replicator:
    """Shared rate-limited round loop (replication.go Replicator):
    subclasses implement run_once() -> (upserts, deletes).  Round
    outcomes feed the status surface GET /v1/acl/replication serves
    (acl_endpoint.go ACLReplicationStatus)."""

    # the reference reports which payload class replicates
    replication_type = "tokens"

    def __init__(self, primary_store, secondary_store,
                 interval: float = 30.0, source_dc: str = "dc1",
                 gate: Optional[Callable[[], bool]] = None):
        self.primary = primary_store
        self.secondary = secondary_store
        self.interval = interval
        self.source_dc = source_dc
        # leadership gate: the reference starts replication routines
        # from the leader loop (leader.go) — only the secondary DC's
        # LEADER replicates, so a follower's loop idles until it wins
        self.gate = gate
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.last_round: Tuple[int, int] = (0, 0)  # (upserts, deletes)
        # status (acl_replication.go updateACLReplicationStatus)
        self.last_success: Optional[float] = None
        self.last_error: Optional[float] = None
        self.last_error_message: Optional[str] = None
        self.replicated_index = 0
        self.rounds = 0
        # divergence surface: a successful round PROVES sync (the diff
        # applied everything), so lag counts up from the last clean
        # round; a failed round (partitioned primary) means sync can
        # no longer be proven → diverged until the next clean round
        self.diverged = False
        self.lag_s = 0.0
        self.last_divergence_check: Optional[float] = None
        self.content_hash_local: Optional[str] = None
        self.content_hash_primary: Optional[str] = None
        self._synced_at: Optional[float] = None

    def run_once(self) -> Tuple[int, int]:  # pragma: no cover
        raise NotImplementedError

    def run_round(self) -> Tuple[int, int]:
        """run_once plus status bookkeeping; the loop and the tests
        both drive rounds through here."""
        try:
            out = self.run_once()
        except Exception as e:
            self.last_error = time.time()
            self.last_error_message = f"{type(e).__name__}: {e}"
            self._note_divergence(diverged=True)
            raise
        self.rounds += 1
        self.last_success = time.time()
        self.replicated_index = getattr(self.primary, "index", 0)
        self._synced_at = time.time()
        self._note_divergence(diverged=False)
        return out

    # ----------------------------------------------------- divergence checker

    def snapshot(self, store) -> list:  # pragma: no cover
        """The canonical replicated payload on `store` — what the two
        sides must agree on for this replication type.  Subclasses
        strip store-local fields (index columns) the same way their
        diff does."""
        raise NotImplementedError

    def content_hash(self, store) -> str:
        """Order-independent content hash of the replicated payload."""
        payload = json.dumps(self.snapshot(store), sort_keys=True,
                             default=str)
        return hashlib.sha256(payload.encode()).hexdigest()[:16]

    def check_divergence(self) -> dict:
        """Compare both sides' content hashes WITHOUT applying a diff.
        Primary unreachable (partition) counts as diverged: sync can
        no longer be proven.  Feeds the SLIs + flight transitions."""
        self.content_hash_local = self.content_hash(self.secondary)
        try:
            self.content_hash_primary = self.content_hash(self.primary)
            diverged = self.content_hash_primary \
                != self.content_hash_local
            reason = "content" if diverged else None
        except Exception as e:
            self.content_hash_primary = None
            diverged = True
            reason = f"unreachable: {type(e).__name__}"
        if not diverged:
            self._synced_at = time.time()
        self._note_divergence(diverged=diverged)
        self.last_divergence_check = time.time()
        return {"diverged": diverged, "reason": reason,
                "local_hash": self.content_hash_local,
                "primary_hash": self.content_hash_primary,
                "lag_s": self.lag_s}

    def _note_divergence(self, diverged: bool) -> None:
        """Update lag + diverged state, publish the SLIs, and journal
        the TRANSITIONS (not every round — a long partition is one
        diverged event, not one per retry)."""
        now = time.time()
        if self._synced_at is None:
            self._synced_at = now
        self.lag_s = 0.0 if not diverged \
            else max(0.0, now - self._synced_at)
        was = self.diverged
        self.diverged = diverged
        labels = {"type": self.replication_type}
        telemetry.set_gauge(("replication", "lag"), self.lag_s,
                            labels=labels)
        telemetry.set_gauge(("replication", "diverged"),
                            1.0 if diverged else 0.0, labels=labels)
        if was != diverged:
            from consul_tpu import flight
            flight.emit(
                "replication.diverged" if diverged
                else "replication.converged",
                labels={"type": self.replication_type,
                        "source_dc": self.source_dc})

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive() \
            and not self._stop.is_set()

    def status(self) -> dict:
        """ACLReplicationStatus shape (agent/structs/acl.go)."""

        def stamp(t):
            return time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                 time.gmtime(t)) if t else None

        return {
            "Enabled": True,
            "Running": self.running,
            "SourceDatacenter": self.source_dc,
            "ReplicationType": self.replication_type,
            "ReplicatedIndex": self.replicated_index,
            "ReplicatedTokenIndex": self.replicated_index,
            "LastSuccess": stamp(self.last_success),
            "LastError": stamp(self.last_error),
            "LastErrorMessage": self.last_error_message,
            "Diverged": self.diverged,
            "LagSeconds": round(self.lag_s, 3),
            "LastDivergenceCheck": stamp(self.last_divergence_check),
            "ContentHash": self.content_hash_local,
            "Rounds": self.rounds,
        }

    def start(self) -> None:
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                if self.gate is not None and not self.gate():
                    # not the leader: idle without touching status —
                    # the leader's loop owns the round bookkeeping
                    self._stop.wait(self.interval)
                    continue
                try:
                    self.run_round()
                except Exception:
                    pass  # rate-limited retry next round (replication.go)
                self._stop.wait(self.interval)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            if not self._thread.is_alive():
                self._thread = None


class AclReplicator(Replicator):

    # ------------------------------------------------------------ one round

    def run_once(self) -> Tuple[int, int]:
        """One replication round; returns (upserts, deletes)."""
        ups = dels = 0
        # policies first so token->policy links resolve (reference order:
        # policies, roles, tokens — leader.go:873-896)
        # content comparison, NOT modify_index: the two stores have
        # independent raft index spaces, so cross-store index compares
        # would re-upsert identical data every round forever
        prim_pols = {p["id"]: p for p in self.primary.acl_policy_list()}
        sec_pols = {p["id"]: p for p in self.secondary.acl_policy_list()}
        # deletes BEFORE upserts: a delete+recreate reusing a policy name
        # would otherwise hit the secondary's name-uniqueness check and
        # wedge every subsequent round (reference delete-first diff order)
        for pid in set(sec_pols) - set(prim_pols):
            self.secondary.acl_policy_delete(pid)
            dels += 1
        for pid, pol in prim_pols.items():
            mine = sec_pols.get(pid)
            if mine is None or mine["rules"] != pol["rules"] \
                    or mine["name"] != pol["name"] \
                    or mine.get("description") != pol.get("description"):
                self.secondary.acl_policy_set(
                    pid, pol["name"], pol["rules"],
                    pol.get("description", ""))
                ups += 1

        prim_toks = {t["accessor"]: t for t in self.primary.acl_token_list()
                     if not t.get("local")}
        sec_toks = {t["accessor"]: t for t in self.secondary.acl_token_list()
                    if not t.get("local")}
        for acc in set(sec_toks) - set(prim_toks):
            self.secondary.acl_token_delete(acc)
            dels += 1
        for acc, tok in prim_toks.items():
            mine = sec_toks.get(acc)
            if mine is None or mine["secret"] != tok["secret"] \
                    or mine["policies"] != tok["policies"] \
                    or mine.get("type") != tok.get("type") \
                    or mine.get("description") != tok.get("description") \
                    or (mine.get("service_identities") or []) != \
                    (tok.get("service_identities") or []) \
                    or (mine.get("node_identities") or []) != \
                    (tok.get("node_identities") or []):
                self.secondary.acl_token_set(
                    acc, tok["secret"], tok.get("policies") or [],
                    tok.get("description", ""),
                    token_type=tok.get("type", "client"), local=False,
                    service_identities=tok.get("service_identities"),
                    node_identities=tok.get("node_identities"))
                ups += 1
        self.last_round = (ups, dels)
        return ups, dels

    def snapshot(self, store) -> list:
        pols = [{"id": p["id"], "name": p["name"],
                 "rules": p["rules"],
                 "description": p.get("description", "")}
                for p in store.acl_policy_list()]
        toks = [{"accessor": t["accessor"], "secret": t["secret"],
                 "policies": t["policies"],
                 "type": t.get("type"),
                 "description": t.get("description", ""),
                 "service_identities":
                     t.get("service_identities") or [],
                 "node_identities": t.get("node_identities") or []}
                for t in store.acl_token_list() if not t.get("local")]
        return [sorted(pols, key=lambda p: p["id"]),
                sorted(toks, key=lambda t: t["accessor"])]


class IntentionReplicator(Replicator):
    """Primary → secondary connect-intention sync: the mesh's
    allow/deny graph written in the primary DC must converge to every
    secondary (the reference replicates intentions as config entries,
    agent/consul/config_replication.go; here they are first-class
    store rows keyed by id)."""

    replication_type = "intentions"

    @staticmethod
    def _strip(i: dict) -> dict:
        return {"id": i["id"], "source": i["source"],
                "destination": i["destination"],
                "action": i["action"],
                "description": i.get("description", ""),
                "meta": i.get("meta") or {}}

    def run_once(self):
        ups = dels = 0
        prim = {i["id"]: self._strip(i)
                for i in self.primary.intention_list()}
        sec = {i["id"]: self._strip(i)
               for i in self.secondary.intention_list()}
        # deletes first: a delete+recreate of the same (src, dst) pair
        # under a new id would otherwise trip the store's duplicate-
        # pair check and wedge every later round
        for iid in set(sec) - set(prim):
            self.secondary.intention_delete(iid)
            dels += 1
        for iid, body in prim.items():
            if sec.get(iid) != body:
                self.secondary.intention_set(
                    iid, body["source"], body["destination"],
                    body["action"], body.get("description", ""),
                    body.get("meta") or {})
                ups += 1
        self.last_round = (ups, dels)
        return ups, dels

    def snapshot(self, store) -> list:
        return sorted((self._strip(i) for i in store.intention_list()),
                      key=lambda i: i["id"])


class ConfigEntryReplicator(Replicator):
    """Primary → secondary config-entry sync
    (agent/consul/config_replication.go): mesh routing config
    (resolvers/routers/splitters/gateway bindings/proxy-defaults)
    written in the primary DC must converge to every secondary, same
    content-diff round shape as the other replicators."""

    replication_type = "config-entries"

    def run_once(self):
        ups = dels = 0

        def strip(e):
            return {k: v for k, v in e.items()
                    if k not in ("create_index", "modify_index")}

        prim = {(e["kind"], e["name"]): strip(e)
                for e in self.primary.config_entry_list()}
        sec = {(e["kind"], e["name"]): strip(e)
               for e in self.secondary.config_entry_list()}
        for (kind, name) in set(sec) - set(prim):
            self.secondary.config_entry_delete(kind, name)
            dels += 1
        for (kind, name), body in prim.items():
            if sec.get((kind, name)) != body:
                self.secondary.config_entry_set(
                    kind, name, {k: v for k, v in body.items()
                                 if k not in ("kind", "name")})
                ups += 1
        self.last_round = (ups, dels)
        return ups, dels

    def snapshot(self, store) -> list:
        def strip(e):
            return {k: v for k, v in e.items()
                    if k not in ("create_index", "modify_index")}
        return sorted((strip(e) for e in store.config_entry_list()),
                      key=lambda e: (e["kind"], e["name"]))


class FederationStateReplicator(Replicator):
    """Primary → secondary federation-state sync
    (agent/consul/federation_state_replication.go): each round lists the
    primary's per-DC gateway states and upserts/deletes by content, the
    same shape as ACL replication."""

    replication_type = "federation-states"

    def run_once(self):
        ups = dels = 0
        prim = {f["datacenter"]: f
                for f in self.primary.federation_state_list()}
        sec = {f["datacenter"]: f
               for f in self.secondary.federation_state_list()}
        for dc in set(sec) - set(prim):
            self.secondary.federation_state_delete(dc)
            dels += 1
        for dc, st in prim.items():
            mine = sec.get(dc)
            if mine is None \
                    or mine["mesh_gateways"] != st["mesh_gateways"] \
                    or mine.get("updated") != st.get("updated"):
                self.secondary.federation_state_set(
                    dc, st["mesh_gateways"], st.get("updated", ""))
                ups += 1
        self.last_round = (ups, dels)
        return ups, dels

    def snapshot(self, store) -> list:
        return sorted(
            ({"datacenter": f["datacenter"],
              "mesh_gateways": f["mesh_gateways"],
              "updated": f.get("updated", "")}
             for f in store.federation_state_list()),
            key=lambda f: f["datacenter"])


class RemoteDcStore:
    """Read-only store adapter over the PRIMARY datacenter's HTTP
    surface: list calls hit `GET /v1/internal/replication/<what>` with
    `?dc=<primary>` on the LOCAL front, which WAN-forwards through the
    mesh gateways (api/http.py `_DC_FORWARDABLE`) — so severing the
    gateway link severs replication, exactly the failure the
    divergence checker must observe.  Short timeouts keep a partition
    from wedging a replication round for the client default 30 s."""

    def __init__(self, client, dc: str, timeout: float = 3.0):
        self.client = client
        self.dc = dc
        self.timeout = timeout
        self.index = 0

    def _rows(self, what: str) -> list:
        data, _idx, _raw = self.client._call(
            "GET", f"/v1/internal/replication/{what}",
            params={"dc": self.dc}, timeout=self.timeout)
        self.index = int((data or {}).get("index", 0))
        return (data or {}).get("rows", [])

    def acl_policy_list(self):
        return self._rows("policies")

    def acl_token_list(self):
        return self._rows("tokens")

    def intention_list(self):
        return self._rows("intentions")

    def config_entry_list(self):
        return self._rows("config-entries")

    def federation_state_list(self):
        return self._rows("federation-states")


def build_replicators(primary_store, secondary, source_dc: str,
                      interval: float = 5.0,
                      gate: Optional[Callable[[], bool]] = None,
                      include_federation: bool = False) -> list:
    """The secondary-DC replication set the leader loop runs
    (leader.go:873-896 starts ACL + config + federation-state
    replication routines together).  Federation states are OFF by
    default: deployments that advertise DC-local gateway addresses
    (each DC dials the remote through its own WAN link, as LiveWan
    does) must not have the primary's self-view clobber the
    secondary's routes — the primary holds no row for itself, so a
    full-diff round would DELETE the secondary's route back to it."""
    reps = [
        AclReplicator(primary_store, secondary, interval=interval,
                      source_dc=source_dc, gate=gate),
        IntentionReplicator(primary_store, secondary, interval=interval,
                            source_dc=source_dc, gate=gate),
        ConfigEntryReplicator(primary_store, secondary,
                              interval=interval, source_dc=source_dc,
                              gate=gate),
    ]
    if include_federation:
        reps.append(FederationStateReplicator(
            primary_store, secondary, interval=interval,
            source_dc=source_dc, gate=gate))
    return reps
