"""Cross-DC ACL replication: primary → secondary token/policy sync.

The reference replicates ACL state from the primary datacenter with
rate-limited, index-based round loops (agent/consul/replication.go
Replicator; acl_replication.go diffACLPolicies/diffACLTokens; started
from the leader loop, leader.go:873-896).  Same structure here: each
round lists the primary's policies and tokens, diffs against the local
secondary store by modify_index, and applies upserts + deletes.  Local
tokens (`local: true`) never replicate (the reference's local-token
carve-out).
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Tuple


class Replicator:
    """Shared rate-limited round loop (replication.go Replicator):
    subclasses implement run_once() -> (upserts, deletes).  Round
    outcomes feed the status surface GET /v1/acl/replication serves
    (acl_endpoint.go ACLReplicationStatus)."""

    # the reference reports which payload class replicates
    replication_type = "tokens"

    def __init__(self, primary_store, secondary_store,
                 interval: float = 30.0, source_dc: str = "dc1"):
        self.primary = primary_store
        self.secondary = secondary_store
        self.interval = interval
        self.source_dc = source_dc
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.last_round: Tuple[int, int] = (0, 0)  # (upserts, deletes)
        # status (acl_replication.go updateACLReplicationStatus)
        self.last_success: Optional[float] = None
        self.last_error: Optional[float] = None
        self.last_error_message: Optional[str] = None
        self.replicated_index = 0
        self.rounds = 0

    def run_once(self) -> Tuple[int, int]:  # pragma: no cover
        raise NotImplementedError

    def run_round(self) -> Tuple[int, int]:
        """run_once plus status bookkeeping; the loop and the tests
        both drive rounds through here."""
        try:
            out = self.run_once()
        except Exception as e:
            self.last_error = time.time()
            self.last_error_message = f"{type(e).__name__}: {e}"
            raise
        self.rounds += 1
        self.last_success = time.time()
        self.replicated_index = getattr(self.primary, "index", 0)
        return out

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive() \
            and not self._stop.is_set()

    def status(self) -> dict:
        """ACLReplicationStatus shape (agent/structs/acl.go)."""

        def stamp(t):
            return time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                 time.gmtime(t)) if t else None

        return {
            "Enabled": True,
            "Running": self.running,
            "SourceDatacenter": self.source_dc,
            "ReplicationType": self.replication_type,
            "ReplicatedIndex": self.replicated_index,
            "ReplicatedTokenIndex": self.replicated_index,
            "LastSuccess": stamp(self.last_success),
            "LastError": stamp(self.last_error),
            "LastErrorMessage": self.last_error_message,
        }

    def start(self) -> None:
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    self.run_round()
                except Exception:
                    pass  # rate-limited retry next round (replication.go)
                self._stop.wait(self.interval)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            if not self._thread.is_alive():
                self._thread = None


class AclReplicator(Replicator):

    # ------------------------------------------------------------ one round

    def run_once(self) -> Tuple[int, int]:
        """One replication round; returns (upserts, deletes)."""
        ups = dels = 0
        # policies first so token->policy links resolve (reference order:
        # policies, roles, tokens — leader.go:873-896)
        # content comparison, NOT modify_index: the two stores have
        # independent raft index spaces, so cross-store index compares
        # would re-upsert identical data every round forever
        prim_pols = {p["id"]: p for p in self.primary.acl_policy_list()}
        sec_pols = {p["id"]: p for p in self.secondary.acl_policy_list()}
        # deletes BEFORE upserts: a delete+recreate reusing a policy name
        # would otherwise hit the secondary's name-uniqueness check and
        # wedge every subsequent round (reference delete-first diff order)
        for pid in set(sec_pols) - set(prim_pols):
            self.secondary.acl_policy_delete(pid)
            dels += 1
        for pid, pol in prim_pols.items():
            mine = sec_pols.get(pid)
            if mine is None or mine["rules"] != pol["rules"] \
                    or mine["name"] != pol["name"] \
                    or mine.get("description") != pol.get("description"):
                self.secondary.acl_policy_set(
                    pid, pol["name"], pol["rules"],
                    pol.get("description", ""))
                ups += 1

        prim_toks = {t["accessor"]: t for t in self.primary.acl_token_list()
                     if not t.get("local")}
        sec_toks = {t["accessor"]: t for t in self.secondary.acl_token_list()
                    if not t.get("local")}
        for acc in set(sec_toks) - set(prim_toks):
            self.secondary.acl_token_delete(acc)
            dels += 1
        for acc, tok in prim_toks.items():
            mine = sec_toks.get(acc)
            if mine is None or mine["secret"] != tok["secret"] \
                    or mine["policies"] != tok["policies"] \
                    or mine.get("type") != tok.get("type") \
                    or mine.get("description") != tok.get("description") \
                    or (mine.get("service_identities") or []) != \
                    (tok.get("service_identities") or []) \
                    or (mine.get("node_identities") or []) != \
                    (tok.get("node_identities") or []):
                self.secondary.acl_token_set(
                    acc, tok["secret"], tok.get("policies") or [],
                    tok.get("description", ""),
                    token_type=tok.get("type", "client"), local=False,
                    service_identities=tok.get("service_identities"),
                    node_identities=tok.get("node_identities"))
                ups += 1
        self.last_round = (ups, dels)
        return ups, dels



class ConfigEntryReplicator(Replicator):
    """Primary → secondary config-entry sync
    (agent/consul/config_replication.go): mesh routing config
    (resolvers/routers/splitters/gateway bindings/proxy-defaults)
    written in the primary DC must converge to every secondary, same
    content-diff round shape as the other replicators."""

    replication_type = "config-entries"

    def run_once(self):
        ups = dels = 0

        def strip(e):
            return {k: v for k, v in e.items()
                    if k not in ("create_index", "modify_index")}

        prim = {(e["kind"], e["name"]): strip(e)
                for e in self.primary.config_entry_list()}
        sec = {(e["kind"], e["name"]): strip(e)
               for e in self.secondary.config_entry_list()}
        for (kind, name) in set(sec) - set(prim):
            self.secondary.config_entry_delete(kind, name)
            dels += 1
        for (kind, name), body in prim.items():
            if sec.get((kind, name)) != body:
                self.secondary.config_entry_set(
                    kind, name, {k: v for k, v in body.items()
                                 if k not in ("kind", "name")})
                ups += 1
        self.last_round = (ups, dels)
        return ups, dels


class FederationStateReplicator(Replicator):
    """Primary → secondary federation-state sync
    (agent/consul/federation_state_replication.go): each round lists the
    primary's per-DC gateway states and upserts/deletes by content, the
    same shape as ACL replication."""

    replication_type = "federation-states"

    def run_once(self):
        ups = dels = 0
        prim = {f["datacenter"]: f
                for f in self.primary.federation_state_list()}
        sec = {f["datacenter"]: f
               for f in self.secondary.federation_state_list()}
        for dc in set(sec) - set(prim):
            self.secondary.federation_state_delete(dc)
            dels += 1
        for dc, st in prim.items():
            mine = sec.get(dc)
            if mine is None \
                    or mine["mesh_gateways"] != st["mesh_gateways"] \
                    or mine.get("updated") != st.get("updated"):
                self.secondary.federation_state_set(
                    dc, st["mesh_gateways"], st.get("updated", ""))
                ups += 1
        self.last_round = (ups, dels)
        return ups, dels
