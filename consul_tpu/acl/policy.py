"""ACL policy language: parse + merge.

The reference's policies are HCL documents of resource rules
(acl/policy.go; syntax docs website/content/docs/security/acl/acl-rules.mdx):

    key_prefix "foo/" { policy = "write" }
    service "web"     { policy = "read" }
    operator = "read"

This module parses the same surface from either the HCL subset above or a
JSON object ({"key_prefix": {"foo/": {"policy": "write"}}, ...}), producing
a flat rule list the Authorizer consumes.  Exact-match resources (`key`,
`service`, `node`, `session`, `event`, `query`, `agent`) and their
`_prefix` variants mirror acl/policy.go's PolicyRules fields; the scalar
resources `operator`, `keyring`, `acl`, `mesh` take a bare policy string.
"""

from __future__ import annotations

import json
import re
from typing import Dict, List, NamedTuple

# permission lattice: deny always wins; list < read < write
DENY, LIST, READ, WRITE = "deny", "list", "read", "write"
_RANK = {DENY: 0, LIST: 1, READ: 2, WRITE: 3}

PREFIX_RESOURCES = ("key", "service", "node", "session", "event", "query",
                    "agent")
SCALAR_RESOURCES = ("operator", "keyring", "acl", "mesh")

_BLOCK_RE = re.compile(
    r'(\w+)\s+"([^"]*)"\s*{\s*policy\s*=\s*"(\w+)"(?:\s+intentions\s*=\s*'
    r'"(\w+)")?\s*}')
_SCALAR_RE = re.compile(r'^\s*(\w+)\s*=\s*"(\w+)"\s*$', re.M)


class Rule(NamedTuple):
    resource: str      # "key", "service", ... or scalar name
    name: str          # segment name; "" for scalars
    exact: bool        # exact match vs prefix match
    policy: str        # deny | list | read | write
    intentions: str    # service rules only: deny | read | write | ""


class PolicyError(ValueError):
    pass


def parse(text_or_obj) -> List[Rule]:
    """Parse an HCL-subset string or a JSON-shaped dict into rules."""
    if isinstance(text_or_obj, dict):
        return _parse_obj(text_or_obj)
    text = text_or_obj.strip()
    if text.startswith("{"):
        return _parse_obj(json.loads(text))
    return _parse_hcl(text)


def _check_policy(resource: str, policy: str) -> None:
    if policy not in _RANK:
        raise PolicyError(f"invalid policy {policy!r} for {resource!r}")
    if policy == LIST and resource != "key":
        raise PolicyError(f"policy \"list\" is only valid for key rules")


def _parse_hcl(text: str) -> List[Rule]:
    rules: List[Rule] = []
    stripped = text
    for m in _BLOCK_RE.finditer(text):
        kind, name, policy, intentions = m.groups()
        base = kind[:-7] if kind.endswith("_prefix") else kind
        if base not in PREFIX_RESOURCES:
            raise PolicyError(f"unknown resource {kind!r}")
        _check_policy(base, policy)
        rules.append(Rule(base, name, exact=not kind.endswith("_prefix"),
                          policy=policy, intentions=intentions or ""))
        stripped = stripped.replace(m.group(0), "", 1)
    for m in _SCALAR_RE.finditer(stripped):
        kind, policy = m.groups()
        if kind not in SCALAR_RESOURCES:
            raise PolicyError(f"unknown resource {kind!r}")
        _check_policy(kind, policy)
        rules.append(Rule(kind, "", exact=True, policy=policy, intentions=""))
    leftover = _SCALAR_RE.sub("", stripped).strip()
    if leftover:
        raise PolicyError(f"unparsed policy text: {leftover[:80]!r}")
    return rules


def _parse_obj(obj: Dict) -> List[Rule]:
    rules: List[Rule] = []
    for kind, body in obj.items():
        base = kind[:-7] if kind.endswith("_prefix") else kind
        if base in PREFIX_RESOURCES and isinstance(body, dict):
            for name, spec in body.items():
                policy = spec["policy"] if isinstance(spec, dict) else spec
                _check_policy(base, policy)
                rules.append(Rule(
                    base, name, exact=not kind.endswith("_prefix"),
                    policy=policy,
                    intentions=(spec.get("intentions", "")
                                if isinstance(spec, dict) else "")))
        elif kind in SCALAR_RESOURCES and isinstance(body, str):
            _check_policy(kind, body)
            rules.append(Rule(kind, "", exact=True, policy=body,
                              intentions=""))
        else:
            raise PolicyError(f"unknown resource {kind!r}")
    return rules


def rank(policy: str) -> int:
    return _RANK[policy]
