from consul_tpu.acl.authorizer import (
    Authorizer, ManagementAuthorizer, allow_all, deny_all,
)
from consul_tpu.acl.policy import PolicyError, Rule, parse
from consul_tpu.acl.resolver import ACLResolver, ResolveError

__all__ = ["Authorizer", "ManagementAuthorizer", "allow_all", "deny_all",
           "PolicyError", "Rule", "parse", "ACLResolver", "ResolveError"]
