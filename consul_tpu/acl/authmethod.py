"""Auth methods: trusted-identity login → ACL token minting.

The reference's auth-method stack (agent/consul/authmethod/, login at
acl_endpoint.go Login/Logout): an auth method validates a bearer
credential (Kubernetes SA JWT, OIDC/JWT), binding rules select which
identities map to which ACL roles/policies, and a successful login mints
a short-lived token deleted again by logout.

Implemented method type: "jwt" with HS256 (HMAC, stdlib) and RS256
(RSA-PKCS1v15/SHA-256 via cryptography) validation — no JOSE
dependency.  Config: {"secret": ...} for HS256 and/or
{"jwt_validation_pubkeys": [PEM, ...]} for RS256 (the reference's
locally-configured JWT mode, agent/consul/authmethod/jwtauth), plus
{"bound_audiences": [...], "claim_mappings": {claim: var}}.
Binding-rule selectors are `key==value` conjunctions over the mapped
claims; bind_name supports ${var} interpolation like the reference's
HIL templates.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import re
import time
from typing import Dict, List, Optional, Tuple


class AuthError(Exception):
    pass


def _b64url_decode(s: str) -> bytes:
    pad = "=" * (-len(s) % 4)
    return base64.urlsafe_b64decode(s + pad)


def b64url_encode(b: bytes) -> str:
    return base64.urlsafe_b64encode(b).rstrip(b"=").decode()


def make_jwt(claims: dict, secret: str) -> str:
    """Test/ops helper: mint an HS256 JWT."""
    header = b64url_encode(json.dumps({"alg": "HS256",
                                       "typ": "JWT"}).encode())
    payload = b64url_encode(json.dumps(claims).encode())
    signing = f"{header}.{payload}".encode()
    sig = b64url_encode(hmac.new(secret.encode(), signing,
                                 hashlib.sha256).digest())
    return f"{header}.{payload}.{sig}"


def make_jwt_rs256(claims: dict, private_key_pem: str) -> str:
    """Test/ops helper: mint an RS256 JWT from a PEM private key."""
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import padding
    key = serialization.load_pem_private_key(private_key_pem.encode(),
                                             password=None)
    header = b64url_encode(json.dumps({"alg": "RS256",
                                       "typ": "JWT"}).encode())
    payload = b64url_encode(json.dumps(claims).encode())
    signing = f"{header}.{payload}".encode()
    sig = key.sign(signing, padding.PKCS1v15(), hashes.SHA256())
    return f"{header}.{payload}.{b64url_encode(sig)}"


def _verify_rs256(signing: bytes, sig: bytes,
                  pubkeys: List[str]) -> bool:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import padding
    for pem in pubkeys:
        try:
            pub = serialization.load_pem_public_key(pem.encode())
            pub.verify(sig, signing, padding.PKCS1v15(),
                       hashes.SHA256())
            return True
        except (InvalidSignature, ValueError):
            continue
    return False


def validate_jwt(token: str, secret: str,
                 bound_audiences: Optional[List[str]] = None,
                 pubkeys: Optional[List[str]] = None) -> dict:
    """JWT validation → claims dict (authmethod/validator role).

    The accepted algorithm follows the CONFIGURED trust material, never
    the attacker-controlled header: a secret admits HS256, pubkeys
    admit RS256 (jwtauth's locally-configured validation)."""
    parts = token.split(".")
    if len(parts) != 3:
        raise AuthError("malformed JWT")
    header_raw, payload_raw, sig_raw = parts
    try:
        header = json.loads(_b64url_decode(header_raw))
        claims = json.loads(_b64url_decode(payload_raw))
        sig = _b64url_decode(sig_raw)
    except (ValueError, json.JSONDecodeError):
        raise AuthError("malformed JWT")
    # attacker-shaped tokens must fail AUTH, not 500: enforce dict
    # payloads and numeric exp before touching them
    if not isinstance(header, dict) or not isinstance(claims, dict):
        raise AuthError("malformed JWT")
    alg = header.get("alg")
    signing = f"{header_raw}.{payload_raw}".encode()
    if alg == "HS256" and secret:
        want = hmac.new(secret.encode(), signing,
                        hashlib.sha256).digest()
        if not hmac.compare_digest(sig, want):
            raise AuthError("invalid signature")
    elif alg == "RS256" and pubkeys:
        if not _verify_rs256(signing, sig, pubkeys):
            raise AuthError("invalid signature")
    else:
        raise AuthError(f"unsupported alg {alg!r} for configured "
                        f"trust material")
    exp = claims.get("exp")
    if exp is not None:
        try:
            expired = time.time() > float(exp)
        except (TypeError, ValueError):
            raise AuthError("malformed exp claim")
        if expired:
            raise AuthError("token expired")
    if bound_audiences:
        aud = claims.get("aud")
        auds = aud if isinstance(aud, list) else [aud]
        if not any(a in bound_audiences for a in auds):
            raise AuthError("audience not allowed")
    return claims


def map_claims(claims: dict, mappings: Dict[str, str]) -> Dict[str, str]:
    """claim → selector variable projection (claim_mappings)."""
    out = {}
    for claim, var in (mappings or {}).items():
        if claim in claims:
            out[var] = str(claims[claim])
    return out


def selector_matches(selector: str, variables: Dict[str, str]) -> bool:
    """`a==b and c==d` conjunctions over mapped variables (the
    reference's bexpr selectors, minimal subset).  Empty = match all."""
    if not selector.strip():
        return True
    for clause in selector.split(" and "):
        m = re.fullmatch(r"\s*([\w.]+)\s*==\s*\"?([^\"]*)\"?\s*",
                         clause)
        if m is None:
            return False
        if variables.get(m.group(1)) != m.group(2):
            return False
    return True


def interpolate(template: str, variables: Dict[str, str]) -> str:
    """${var} interpolation in bind_name (HIL-lite).  A missing variable
    raises — substituting "" would mint tokens bound to nonexistent
    policy names (the reference fails login on unavailable vars)."""

    def sub(m):
        var = m.group(1)
        if var not in variables:
            raise AuthError(f"bind name variable ${{{var}}} not mapped "
                            f"from the login identity")
        return variables[var]

    return re.sub(r"\$\{([\w.]+)\}", sub, template)


def login(store, method_name: str, bearer: str) -> Tuple[str, str, list]:
    """Validate the bearer against the method, evaluate binding rules,
    mint a token: returns (accessor, secret, policies).
    (ACL.Login — acl_endpoint.go)."""
    import uuid
    method = store.auth_method_get(method_name)
    if method is None:
        raise AuthError(f"unknown auth method {method_name!r}")
    cfg = method.get("config") or {}
    if method.get("type") != "jwt":
        raise AuthError(f"unsupported method type {method.get('type')!r}")
    claims = validate_jwt(bearer, cfg.get("secret", ""),
                          cfg.get("bound_audiences"),
                          pubkeys=cfg.get("jwt_validation_pubkeys"))
    variables = map_claims(claims, cfg.get("claim_mappings"))
    policies: List[str] = []
    for rule in store.binding_rule_list(method_name):
        if not selector_matches(rule.get("selector", ""), variables):
            continue
        if rule.get("bind_type", "policy") == "policy":
            name = interpolate(rule.get("bind_name", ""), variables)
            if name:
                policies.append(name)
    if not policies:
        raise AuthError("no binding rules matched the login identity")
    accessor, secret = str(uuid.uuid4()), str(uuid.uuid4())
    store.acl_token_set(accessor, secret, policies,
                        description=f"token created via login: "
                                    f"{method_name}",
                        token_type="login", local=True)
    return accessor, secret, policies
