"""Auth methods: trusted-identity login → ACL token minting.

The reference's auth-method stack (agent/consul/authmethod/, login at
acl_endpoint.go Login/Logout): an auth method validates a bearer
credential (Kubernetes SA JWT, OIDC/JWT), binding rules select which
identities map to which ACL roles/policies, and a successful login mints
a short-lived token deleted again by logout.

Implemented method type: "jwt" with HS256 (HMAC, stdlib) and RS256
(RSA-PKCS1v15/SHA-256 via cryptography) validation — no JOSE
dependency.  Config: {"secret": ...} for HS256 and/or
{"jwt_validation_pubkeys": [PEM, ...]} for RS256 (the reference's
locally-configured JWT mode, agent/consul/authmethod/jwtauth), plus
{"bound_audiences": [...], "claim_mappings": {claim: var}}.
Binding-rule selectors are `key==value` conjunctions over the mapped
claims; bind_name supports ${var} interpolation like the reference's
HIL templates.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import re
import time
from typing import Dict, List, Optional, Tuple


class AuthError(Exception):
    pass


def _b64url_decode(s: str) -> bytes:
    pad = "=" * (-len(s) % 4)
    return base64.urlsafe_b64decode(s + pad)


def b64url_encode(b: bytes) -> str:
    return base64.urlsafe_b64encode(b).rstrip(b"=").decode()


def make_jwt(claims: dict, secret: str) -> str:
    """Test/ops helper: mint an HS256 JWT."""
    header = b64url_encode(json.dumps({"alg": "HS256",
                                       "typ": "JWT"}).encode())
    payload = b64url_encode(json.dumps(claims).encode())
    signing = f"{header}.{payload}".encode()
    sig = b64url_encode(hmac.new(secret.encode(), signing,
                                 hashlib.sha256).digest())
    return f"{header}.{payload}.{sig}"


def make_jwt_rs256(claims: dict, private_key_pem: str) -> str:
    """Test/ops helper: mint an RS256 JWT from a PEM private key."""
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import padding
    key = serialization.load_pem_private_key(private_key_pem.encode(),
                                             password=None)
    header = b64url_encode(json.dumps({"alg": "RS256",
                                       "typ": "JWT"}).encode())
    payload = b64url_encode(json.dumps(claims).encode())
    signing = f"{header}.{payload}".encode()
    sig = key.sign(signing, padding.PKCS1v15(), hashes.SHA256())
    return f"{header}.{payload}.{b64url_encode(sig)}"


def jwk_to_pem(jwk: dict) -> Optional[str]:
    """One RSA JWK → PEM public key (RFC 7517/7518 n/e members).  The
    reference validates against JWKS documents through go-sso
    (internal/go-sso/oidcauth/oidcjwt.go); this is the same math with
    cryptography primitives."""
    if jwk.get("kty") != "RSA":
        return None
    try:
        from cryptography.hazmat.primitives import serialization
        from cryptography.hazmat.primitives.asymmetric import rsa
        n = int.from_bytes(_b64url_decode(jwk["n"]), "big")
        e = int.from_bytes(_b64url_decode(jwk["e"]), "big")
        pub = rsa.RSAPublicNumbers(e, n).public_key()
        return pub.public_bytes(
            serialization.Encoding.PEM,
            serialization.PublicFormat.SubjectPublicKeyInfo).decode()
    except (KeyError, ValueError):
        return None


def pem_to_jwk(public_key_pem: str, kid: str) -> dict:
    """Test/ops helper: PEM public key → RSA JWK with `kid` (what an
    IdP's jwks_uri would serve)."""
    from cryptography.hazmat.primitives import serialization
    pub = serialization.load_pem_public_key(public_key_pem.encode())
    nums = pub.public_numbers()

    def be(i: int) -> str:
        return b64url_encode(i.to_bytes((i.bit_length() + 7) // 8,
                                        "big"))

    return {"kty": "RSA", "use": "sig", "alg": "RS256", "kid": kid,
            "n": be(nums.n), "e": be(nums.e)}


# login-hot-path caches: JWKS documents convert to PEMs once per
# document identity (file mtime / content hash), and PEMs load into
# key objects once (bounded; cleared wholesale when full)
_jwks_pem_cache: Dict[tuple, List[str]] = {}


def jwks_pubkeys(cfg: dict, kid: Optional[str]) -> List[str]:
    """PEM keys from the method's JWKS trust material.  A token
    carrying a `kid` matches ONLY that kid — an unknown kid FAILS
    rather than brute-forcing every key (go-sso's keyset lookup
    semantics); kid-less tokens try all keys.  Key ROTATION is the IdP
    publishing a new kid and the operator updating the document
    (jwks_url fetching needs egress, which this rig blocks; the
    document itself rides config as `jwks_document` (dict or JSON
    string) or `jwks_file` (path))."""
    doc = cfg.get("jwks_document")
    cache_key = None
    if isinstance(doc, str):
        try:
            doc = json.loads(doc)
        except ValueError:
            raise AuthError("malformed jwks_document")
    if doc is None and cfg.get("jwks_file"):
        path = cfg["jwks_file"]
        try:
            import os
            mtime = os.stat(path).st_mtime_ns
            cache_key = ("file", path, mtime, kid)
            hit = _jwks_pem_cache.get(cache_key)
            if hit is not None:
                return hit
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            raise AuthError(f"jwks_file unreadable: {e}")
    if not isinstance(doc, dict):
        return []
    if cache_key is None:
        cache_key = ("doc", json.dumps(doc, sort_keys=True), kid)
        hit = _jwks_pem_cache.get(cache_key)
        if hit is not None:
            return hit
    keys = doc.get("keys") or []
    if kid is not None:
        keys = [k for k in keys if k.get("kid") == kid]
    pems = [pem for pem in (jwk_to_pem(k) for k in keys)
            if pem is not None]
    if len(_jwks_pem_cache) > 256:
        _jwks_pem_cache.clear()
    _jwks_pem_cache[cache_key] = pems
    return pems


_pem_key_cache: Dict[str, object] = {}


def _verify_rs256(signing: bytes, sig: bytes,
                  pubkeys: List[str]) -> bool:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import padding
    for pem in pubkeys:
        try:
            pub = _pem_key_cache.get(pem)
            if pub is None:
                pub = serialization.load_pem_public_key(pem.encode())
                if len(_pem_key_cache) > 256:
                    _pem_key_cache.clear()
                _pem_key_cache[pem] = pub
            pub.verify(sig, signing, padding.PKCS1v15(),
                       hashes.SHA256())
            return True
        except (InvalidSignature, ValueError):
            continue
    return False


def validate_jwt(token: str, secret: str,
                 bound_audiences: Optional[List[str]] = None,
                 pubkeys: Optional[List[str]] = None,
                 jwks_cfg: Optional[dict] = None,
                 bound_issuer: str = "") -> dict:
    """JWT validation → claims dict (authmethod/validator role).

    The accepted algorithm follows the CONFIGURED trust material, never
    the attacker-controlled header: a secret admits HS256, pubkeys or a
    JWKS document admit RS256 (jwtauth's locally-configured validation
    + go-sso's JWKS mode; the token's `kid` selects the JWKS key, so
    rotation is just publishing the new kid)."""
    parts = token.split(".")
    if len(parts) != 3:
        raise AuthError("malformed JWT")
    header_raw, payload_raw, sig_raw = parts
    try:
        header = json.loads(_b64url_decode(header_raw))
        claims = json.loads(_b64url_decode(payload_raw))
        sig = _b64url_decode(sig_raw)
    except (ValueError, json.JSONDecodeError):
        raise AuthError("malformed JWT")
    # attacker-shaped tokens must fail AUTH, not 500: enforce dict
    # payloads and numeric exp before touching them
    if not isinstance(header, dict) or not isinstance(claims, dict):
        raise AuthError("malformed JWT")
    alg = header.get("alg")
    signing = f"{header_raw}.{payload_raw}".encode()
    rsa_keys = list(pubkeys or [])
    if jwks_cfg is not None:
        rsa_keys += jwks_pubkeys(jwks_cfg, header.get("kid"))
    if alg == "HS256" and secret:
        want = hmac.new(secret.encode(), signing,
                        hashlib.sha256).digest()
        if not hmac.compare_digest(sig, want):
            raise AuthError("invalid signature")
    elif alg == "RS256" and rsa_keys:
        if not _verify_rs256(signing, sig, rsa_keys):
            raise AuthError("invalid signature")
    else:
        raise AuthError(f"unsupported alg {alg!r} for configured "
                        f"trust material")
    exp = claims.get("exp")
    if exp is not None:
        try:
            expired = time.time() > float(exp)
        except (TypeError, ValueError):
            raise AuthError("malformed exp claim")
        if expired:
            raise AuthError("token expired")
    if bound_issuer and claims.get("iss") != bound_issuer:
        raise AuthError("issuer not allowed")
    if bound_audiences:
        aud = claims.get("aud")
        auds = aud if isinstance(aud, list) else [aud]
        if not any(a in bound_audiences for a in auds):
            raise AuthError("audience not allowed")
    return claims


def map_claims(claims: dict, mappings: Dict[str, str]) -> Dict[str, str]:
    """claim → selector variable projection (claim_mappings)."""
    out = {}
    for claim, var in (mappings or {}).items():
        if claim in claims:
            out[var] = str(claims[claim])
    return out


def selector_matches(selector: str, variables: Dict[str, str]) -> bool:
    """`a==b and c==d` conjunctions over mapped variables (the
    reference's bexpr selectors, minimal subset).  Empty = match all."""
    if not selector.strip():
        return True
    for clause in selector.split(" and "):
        m = re.fullmatch(r"\s*([\w.]+)\s*==\s*\"?([^\"]*)\"?\s*",
                         clause)
        if m is None:
            return False
        if variables.get(m.group(1)) != m.group(2):
            return False
    return True


def interpolate(template: str, variables: Dict[str, str]) -> str:
    """${var} interpolation in bind_name (HIL-lite).  A missing variable
    raises — substituting "" would mint tokens bound to nonexistent
    policy names (the reference fails login on unavailable vars)."""

    def sub(m):
        var = m.group(1)
        if var not in variables:
            raise AuthError(f"bind name variable ${{{var}}} not mapped "
                            f"from the login identity")
        return variables[var]

    return re.sub(r"\$\{([\w.]+)\}", sub, template)


def login(store, method_name: str, bearer: str,
          _code_flow: bool = False,
          _expected_nonce: str = "") -> Tuple[str, str, list]:
    """Validate the bearer against the method, evaluate binding rules,
    mint a token: returns (accessor, secret, policies).
    (ACL.Login — acl_endpoint.go).

    Method types: "jwt" (HS256 secret / RS256 PEM keys / RS256 JWKS
    document) logs in directly; "oidc" is ONLY reachable through the
    code flow (/v1/acl/oidc/auth-url + /callback, which call with
    _code_flow=True) — the reference's ACL.Login rejects oidc methods
    the same way, or the single-use-state/redirect/nonce controls
    would be a decorative side door.  `_expected_nonce` binds the ID
    token's nonce claim to the auth-url request's ClientNonce
    (go-sso's code-injection defense)."""
    import uuid
    method = store.auth_method_get(method_name)
    if method is None:
        raise AuthError(f"unknown auth method {method_name!r}")
    cfg = method.get("config") or {}
    mtype = method.get("type")
    allowed = ("jwt", "oidc") if _code_flow else ("jwt",)
    if mtype not in allowed:
        raise AuthError(f"auth method type {mtype!r} cannot login "
                        f"via this endpoint")
    claims = validate_jwt(bearer, cfg.get("secret", ""),
                          cfg.get("bound_audiences"),
                          pubkeys=cfg.get("jwt_validation_pubkeys"),
                          jwks_cfg=cfg,
                          bound_issuer=cfg.get("bound_issuer", ""))
    if _expected_nonce and \
            claims.get("nonce") != _expected_nonce:
        raise AuthError("ID token nonce does not match the login "
                        "request")
    variables = map_claims(claims, cfg.get("claim_mappings"))
    policies: List[str] = []
    for rule in store.binding_rule_list(method_name):
        if not selector_matches(rule.get("selector", ""), variables):
            continue
        if rule.get("bind_type", "policy") == "policy":
            name = interpolate(rule.get("bind_name", ""), variables)
            if name:
                policies.append(name)
    if not policies:
        raise AuthError("no binding rules matched the login identity")
    accessor, secret = str(uuid.uuid4()), str(uuid.uuid4())
    store.acl_token_set(accessor, secret, policies,
                        description=f"token created via login: "
                                    f"{method_name}",
                        token_type="login", local=True)
    return accessor, secret, policies
