"""ACLResolver: secret → Authorizer with caching and down-policy.

Mirrors agent/consul/acl.go:239 (ACLResolver): tokens resolve to their
policies, policies compile to an Authorizer, results cache with a TTL, and
when the authority (servers/primary DC) is unreachable the `down_policy`
decides: deny, allow, extend-cache (serve stale entries indefinitely) or
async-cache.  Unknown tokens fall back to the anonymous token / default
policy.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional, Tuple

from consul_tpu.acl import policy as policy_mod
from consul_tpu.acl.authorizer import (
    Authorizer, ManagementAuthorizer, allow_all, deny_all,
)

ANONYMOUS_ACCESSOR = "00000000-0000-0000-0000-000000000002"

# Synthetic-policy templates for token identities, matching the
# reference byte-for-semantics (agent/structs/acl_oss.go
# aclPolicyTemplateServiceIdentity / aclPolicyTemplateNodeIdentity):
# a service identity may register the service and its sidecar and read
# the rest of the catalog (which also grants intention read via the
# service-read mapping); a node identity may register its node and
# read services for anti-entropy diffing.
_SERVICE_IDENTITY_RULES = (
    'service "{0}" {{ policy = "write" }}\n'
    'service "{0}-sidecar-proxy" {{ policy = "write" }}\n'
    'service_prefix "" {{ policy = "read" }}\n'
    'node_prefix "" {{ policy = "read" }}\n')
_NODE_IDENTITY_RULES = (
    'node "{0}" {{ policy = "write" }}\n'
    'service_prefix "" {{ policy = "read" }}\n')


def synthetic_identity_rules(token: dict, dc: str) -> str:
    """Policy text synthesized from a token's service/node identities,
    scoped to `dc` (ServiceIdentity.Datacenters filters; a
    NodeIdentity is valid only in its own datacenter —
    agent/structs/acl.go:144,199)."""
    parts = []
    for si in token.get("service_identities") or []:
        dcs = si.get("datacenters") or []
        if dcs and dc not in dcs:
            continue
        parts.append(_SERVICE_IDENTITY_RULES.format(si["service_name"]))
    for ni in token.get("node_identities") or []:
        if ni.get("datacenter") and ni["datacenter"] != dc:
            continue
        parts.append(_NODE_IDENTITY_RULES.format(ni["node_name"]))
    return "".join(parts)


class ResolveError(Exception):
    """Authority unreachable (the reference's RPC error path)."""


class ACLResolver:
    def __init__(self, store, enabled: bool = True,
                 default_policy: str = "allow",
                 down_policy: str = "extend-cache",
                 ttl: float = 30.0,
                 fetch: Optional[Callable[[str], Optional[dict]]] = None,
                 dc: str = "dc1"):
        """`store` is any object with acl_token_get_by_secret /
        acl_policy_get; `fetch` overrides token lookup (e.g. an RPC to the
        primary DC) and may raise ResolveError.  `dc` scopes identity
        synthetic policies (datacenter-limited identities grant nothing
        outside their datacenters)."""
        self.store = store
        self.enabled = enabled
        self.default_policy = default_policy
        self.down_policy = down_policy
        self.ttl = ttl
        self.dc = dc
        self._fetch = fetch or self._local_fetch
        self._cache: Dict[str, Tuple[float, Authorizer]] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------------ core

    def _local_fetch(self, secret: str) -> Optional[dict]:
        return self.store.acl_token_get_by_secret(secret)

    def _default_authorizer(self) -> Authorizer:
        return allow_all() if self.default_policy == "allow" else deny_all()

    def _compile(self, token: dict) -> Authorizer:
        """Token dict → Authorizer from its policies."""
        if token.get("type") == "management":
            return ManagementAuthorizer()
        rules = []
        synthetic = synthetic_identity_rules(token, self.dc)
        if synthetic:
            try:
                rules.extend(policy_mod.parse(synthetic))
            except policy_mod.PolicyError:
                # a malformed identity name that slipped past creation
                # validation must fail closed (grant nothing), not 500
                # every request from this token
                pass
        for pid in token.get("policies", []):
            pol = self.store.acl_policy_get(pid) or \
                self.store.acl_policy_get_by_name(pid)
            if pol:
                try:
                    rules.extend(policy_mod.parse(pol["rules"]))
                except policy_mod.PolicyError:
                    # a corrupt stored policy (e.g. restored from a
                    # foreign snapshot) must not 500 every request
                    # from its tokens; it just grants nothing
                    continue
        return Authorizer(
            rules, default_policy="deny"
            if self.default_policy != "allow" else "write")

    _MGMT = None     # shared allow-all: resolve() runs per request on
    #                  the KV hot path; allocating one per call costs

    def resolve(self, secret: Optional[str]) -> Authorizer:
        if not self.enabled:
            # ACLs off: nothing is enforced, including ACL endpoints
            if ACLResolver._MGMT is None:
                ACLResolver._MGMT = ManagementAuthorizer()
            return ACLResolver._MGMT
        if not secret:
            # tokenless requests run as the anonymous token when one
            # exists (the reference resolves ANONYMOUS_ACCESSOR so
            # operators can grant e.g. DNS read to anonymous), else the
            # bare default policy
            anon = self.store.acl_token_get(ANONYMOUS_ACCESSOR)
            if anon and (anon.get("policies")
                         or anon.get("service_identities")
                         or anon.get("node_identities")):
                return self._compile(anon)
            return self._default_authorizer()
        now = time.time()
        with self._lock:
            hit = self._cache.get(secret)
            if hit and now < hit[0]:
                return hit[1]
        try:
            token = self._fetch(secret)
        except ResolveError:
            return self._on_down(secret, hit)
        if token is None:
            authz = self._default_authorizer()
        else:
            authz = self._compile(token)
        with self._lock:
            self._cache[secret] = (now + self.ttl, authz)
        return authz

    def _on_down(self, secret: str,
                 hit: Optional[Tuple[float, Authorizer]]) -> Authorizer:
        if self.down_policy == "allow":
            return allow_all()
        if self.down_policy in ("extend-cache", "async-cache") and hit:
            with self._lock:  # serve stale, keep it warm
                self._cache[secret] = (time.time() + self.ttl, hit[1])
            return hit[1]
        return deny_all()

    def invalidate(self, secret: Optional[str] = None) -> None:
        with self._lock:
            if secret is None:
                self._cache.clear()
            else:
                self._cache.pop(secret, None)
