"""Authorizer: rule evaluation with Consul's precedence semantics.

Mirrors the reference's acl.Authorizer interface (acl/authorizer.go:54)
and policyAuthorizer resolution (acl/policy_authorizer.go): an exact-match
rule beats any prefix rule; among prefix rules the longest match wins;
multiple policies on one token merge with deny > write > read > list at
equal specificity.  A management token resolves to ManagementAuthorizer
(allow-all incl. ACL ops); the anonymous/default fallback is built from
the agent's default_policy.
"""

from __future__ import annotations

from typing import Iterable, List, Optional

from consul_tpu.acl.policy import DENY, LIST, READ, WRITE, Rule, rank


class Authorizer:
    """Evaluates a merged rule set.  All checks return bool (allowed)."""

    def __init__(self, rules: Iterable[Rule], default_policy: str = DENY):
        self._rules: List[Rule] = list(rules)
        self._default = default_policy

    # ------------------------------------------------------------ resolution

    def _resolve(self, resource: str, name: str) -> Optional[str]:
        """Effective policy for one resource instance, or None → default.

        Exact rules trump prefixes; longest prefix wins; ties merge with
        deny-wins then widest-grant (the reference sorts rules so an exact
        deny can't be shadowed — acl/policy_authorizer.go radix insert).
        """
        exact = [r for r in self._rules
                 if r.resource == resource and r.exact and r.name == name]
        if exact:
            return self._merge(exact)
        prefixes = [r for r in self._rules
                    if r.resource == resource and not r.exact
                    and name.startswith(r.name)]
        if not prefixes:
            return None
        longest = max(len(r.name) for r in prefixes)
        return self._merge([r for r in prefixes if len(r.name) == longest])

    @staticmethod
    def _merge(rules: List[Rule]) -> str:
        if any(r.policy == DENY for r in rules):
            return DENY
        return max((r.policy for r in rules), key=rank)

    def _allow(self, resource: str, name: str, need: str) -> bool:
        policy = self._resolve(resource, name)
        if policy is None:
            # ACL management never falls back to a permissive default:
            # the reference's AllowAll authorizer still denies ACLRead/
            # ACLWrite (acl/authorizer.go AllowAll vs ManageAll) — only an
            # explicit `acl = "..."` rule or a management token grants it
            policy = DENY if resource == "acl" else self._default
        if policy == DENY:
            return False
        return rank(policy) >= rank(need)

    # ------------------------------------------------------------- KV

    def key_read(self, key: str) -> bool:
        return self._allow("key", key, READ)

    def key_list(self, key: str) -> bool:
        return self._allow("key", key, LIST)

    def key_write(self, key: str) -> bool:
        return self._allow("key", key, WRITE)

    def key_write_prefix(self, prefix: str) -> bool:
        """Recursive delete needs write on the whole subtree: no rule under
        the prefix may deny write (KeyWritePrefix, acl/policy_authorizer.go)."""
        if not self._allow("key", prefix, WRITE):
            return False
        for r in self._rules:
            if r.resource == "key" and r.name.startswith(prefix) \
                    and rank(r.policy) < rank(WRITE):
                return False
        return True

    # -------------------------------------------------------------- catalog

    def service_read(self, name: str) -> bool:
        return self._allow("service", name, READ)

    def service_write(self, name: str) -> bool:
        return self._allow("service", name, WRITE)

    def node_read(self, name: str) -> bool:
        return self._allow("node", name, READ)

    def _read_all(self, resource: str) -> bool:
        """True iff EVERY possible name of `resource` resolves to >=
        read (the reference's ServiceReadAll/NodeReadAll,
        acl/authorizer.go).  The resolution function is piecewise
        constant with breakpoints at rule names, so probing each rule's
        name, a point just inside each prefix region, and the
        no-rule-matches default region covers the whole domain — a
        broad prefix grant with one explicit deny correctly fails."""
        probes = {"\x00__default_region__"}
        for r in self._rules:
            if r.resource != resource:
                continue
            probes.add(r.name)
            if not r.exact:
                probes.add(r.name + "\x00")
        return all(self._allow(resource, n, READ) for n in probes)

    def service_read_all(self) -> bool:
        return self._read_all("service")

    def node_read_all(self) -> bool:
        return self._read_all("node")

    def node_write(self, name: str) -> bool:
        return self._allow("node", name, WRITE)

    def session_read(self, node: str) -> bool:
        return self._allow("session", node, READ)

    def session_write(self, node: str) -> bool:
        return self._allow("session", node, WRITE)

    def event_read(self, name: str) -> bool:
        return self._allow("event", name, READ)

    def event_write(self, name: str) -> bool:
        return self._allow("event", name, WRITE)

    def query_read(self, name: str) -> bool:
        return self._allow("query", name, READ)

    def query_write(self, name: str) -> bool:
        return self._allow("query", name, WRITE)

    def agent_read(self, node: str) -> bool:
        return self._allow("agent", node, READ)

    def agent_write(self, node: str) -> bool:
        return self._allow("agent", node, WRITE)

    # intentions ride the service rules (intention_read/write need the
    # destination service's `intentions` grant, defaulting to the service
    # policy — acl/policy.go ServiceRule.Intentions)

    def intention_read(self, service: str) -> bool:
        g = self._intention_grant(service)
        return g is not None and rank(g) >= rank(READ) if g != DENY else False

    def intention_write(self, service: str) -> bool:
        g = self._intention_grant(service)
        return g is not None and g != DENY and rank(g) >= rank(WRITE)

    def _intention_grant(self, service: str) -> Optional[str]:
        matches = [r for r in self._rules if r.resource == "service"
                   and ((r.exact and r.name == service)
                        or (not r.exact and service.startswith(r.name)))]
        with_intent = [r for r in matches if r.intentions]
        if with_intent:
            # same precedence as _resolve: exact beats prefix, longest
            # prefix wins; merge only rules at the winning specificity
            exact = [r for r in with_intent if r.exact]
            if exact:
                pick = exact
            else:
                longest = max(len(r.name) for r in with_intent)
                pick = [r for r in with_intent if len(r.name) == longest]
            return self._merge([Rule(r.resource, r.name, r.exact,
                                     r.intentions, "") for r in pick])
        svc = self._resolve("service", service)
        if svc is None:
            # no service rule matches at all: intentions follow the
            # token's default policy (ACLs off / default allow ⇒ full
            # intention access — you can manage intentions without ACLs)
            return WRITE if self._default == WRITE else DENY
        # a service RULE matched (acl/policy_authorizer.go:208-218):
        # service read OR write derives intention READ only — intention
        # WRITE always needs an explicit intentions = "write"
        if svc == DENY or rank(svc) < rank(READ):
            return DENY
        return READ

    # -------------------------------------------------------------- scalars

    def operator_read(self) -> bool:
        return self._allow("operator", "", READ)

    def operator_write(self) -> bool:
        return self._allow("operator", "", WRITE)

    def keyring_read(self) -> bool:
        return self._allow("keyring", "", READ)

    def keyring_write(self) -> bool:
        return self._allow("keyring", "", WRITE)

    def acl_read(self) -> bool:
        return self._allow("acl", "", READ)

    def acl_write(self) -> bool:
        return self._allow("acl", "", WRITE)

    def mesh_read(self) -> bool:
        return self._allow("mesh", "", READ)

    def mesh_write(self) -> bool:
        return self._allow("mesh", "", WRITE)


class ManagementAuthorizer(Authorizer):
    """Allow-all (the reference's ManageAll / global-management policy)."""

    def __init__(self):
        super().__init__([], default_policy=WRITE)

    def _allow(self, resource: str, name: str, need: str) -> bool:
        return True


def allow_all() -> Authorizer:
    """Permissive default (the reference's AllowAll): everything except
    ACL management, which stays deny without an explicit rule or a
    management token."""
    return Authorizer([], default_policy=WRITE)


def deny_all() -> Authorizer:
    return Authorizer([], default_policy=DENY)
