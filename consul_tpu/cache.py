"""Agent cache: request-keyed results with TTL + background refresh.

The reference's agent/cache (cache.go:102 Cache, Get :316, watch.go:28
Notify) fronts RPCs with a cache whose entries either expire on TTL or
are kept fresh by a background blocking-query loop (refresh types —
cache-types/*, e.g. health_services).  Serving `?cached` requests from
this layer is what lets thousands of agents ride one server fleet.

Same structure here: a type registry maps a type name to a fetch
function `fetch(key, min_index, timeout) -> (value, index)` (usually a
closure over the store that runs a blocking query); `get` returns the
cached value immediately and — for refresh types — keeps a background
loop long-polling for changes so the next read is already fresh.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

FetchFn = Callable[[str, int, float], Tuple[Any, int]]


@dataclass
class _Type:
    fetch: FetchFn
    refresh: bool = False
    ttl: float = 60.0             # entry lifetime without reads
    refresh_timeout: float = 300.0


@dataclass
class _Entry:
    value: Any = None
    index: int = 0
    fetched_at: float = 0.0
    expires_at: float = 0.0
    fetching: bool = False
    hit: bool = False              # last get() was a cache hit
    cond: threading.Condition = field(
        default_factory=threading.Condition)
    refresher: Optional[threading.Thread] = None
    stop: bool = False


class Cache:
    def __init__(self):
        self._types: Dict[str, _Type] = {}
        self._entries: Dict[Tuple[str, str], _Entry] = {}
        self._lock = threading.Lock()

    def register_type(self, name: str, fetch: FetchFn,
                      refresh: bool = False, ttl: float = 60.0,
                      refresh_timeout: float = 300.0) -> None:
        """RegisterType (cache.go:181): how to fetch one request type."""
        self._types[name] = _Type(fetch, refresh, ttl, refresh_timeout)

    # ------------------------------------------------------------------ get

    def get(self, type_name: str, key: str,
            max_age: Optional[float] = None) -> Tuple[Any, int, bool]:
        """(value, index, cache_hit).  A miss fetches synchronously; a
        refresh-type entry then stays fresh in the background.  `max_age`
        forces a refetch when the entry is older (Cache-Control
        semantics on ?cached requests)."""
        value, index, hit = self._get(type_name, key, max_age)
        # consul.cache.{hit,miss}{type}: the ?cached serving ratio
        # (agent/cache's hit metrics) — emitted here, outside every
        # entry lock; cardinality bounded by the registered types
        from consul_tpu import telemetry
        telemetry.incr_counter(("cache", "hit" if hit else "miss"),
                               labels={"type": type_name})
        return value, index, hit

    def _get(self, type_name: str, key: str,
             max_age: Optional[float] = None) -> Tuple[Any, int, bool]:
        t = self._types[type_name]
        ekey = (type_name, key)
        with self._lock:
            # expired-entry sweep on access — entries must not accumulate
            # for the process lifetime
            now0 = time.time()
            for k, e in list(self._entries.items()):
                if e.expires_at and now0 > e.expires_at and k != ekey:
                    with e.cond:
                        e.stop = True
                        e.cond.notify_all()
                    del self._entries[k]
            entry = self._entries.get(ekey)
            if entry is None:
                entry = _Entry()
                self._entries[ekey] = entry
        with entry.cond:
            while True:
                now = time.time()
                fresh = entry.fetched_at > 0 and (
                    max_age is None or now - entry.fetched_at <= max_age)
                if fresh:
                    entry.expires_at = now + t.ttl
                    entry.hit = True
                    self._ensure_refresher(t, ekey, entry)
                    return entry.value, entry.index, True
                if not entry.fetching:
                    break
                # another caller is refetching: wait, then RE-EVALUATE
                # freshness (incl. max_age) — returning the pre-refetch
                # value would violate the caller's bound
                entry.cond.wait(1.0)
            entry.fetching = True
        try:
            value, index = t.fetch(key, 0, 0.0)
        except BaseException:
            with entry.cond:
                entry.fetching = False
                entry.cond.notify_all()
            raise
        with entry.cond:
            # store the result and clear `fetching` in ONE critical
            # section: a waiter woken between them would see a stale
            # fetched_at with fetching=False and start its own fetch,
            # breaking single-flight into a thundering herd
            entry.value, entry.index = value, index
            entry.fetched_at = time.time()
            entry.expires_at = entry.fetched_at + t.ttl
            entry.hit = False
            entry.fetching = False
            entry.cond.notify_all()
            self._ensure_refresher(t, ekey, entry)
        return value, index, False

    # ---------------------------------------------------------- background

    def _ensure_refresher(self, t: _Type, ekey, entry: _Entry) -> None:
        if not t.refresh or (entry.refresher is not None
                             and entry.refresher.is_alive()):
            return

        def loop():
            while True:
                with entry.cond:
                    if entry.stop or time.time() > entry.expires_at:
                        entry.refresher = None
                        return
                    idx = entry.index
                try:
                    value, index = t.fetch(ekey[1], idx, t.refresh_timeout)
                except Exception:
                    time.sleep(1.0)       # fetch backoff (cache.go)
                    continue
                with entry.cond:
                    if index > entry.index:
                        entry.value, entry.index = value, index
                    entry.fetched_at = time.time()
                    entry.cond.notify_all()

        entry.refresher = threading.Thread(target=loop, daemon=True)
        entry.refresher.start()

    def notify(self, type_name: str, key: str,
               callback: Callable[[Any, int], None],
               poll: float = 0.05) -> Callable[[], None]:
        """Watch a cached request: `callback(value, index)` on each index
        change (cache/watch.go:28 Notify).  Returns a cancel function."""
        stop = threading.Event()

        def loop():
            last = -1
            while not stop.is_set():
                value, index, _ = self.get(type_name, key)
                if index != last:
                    last = index
                    callback(value, index)
                stop.wait(poll)

        threading.Thread(target=loop, daemon=True).start()
        return stop.set

    def close(self) -> None:
        with self._lock:
            entries = list(self._entries.values())
        for e in entries:
            with e.cond:
                e.stop = True
                e.cond.notify_all()
