"""ServiceManager: central-config resolution + sidecar auto-registration.

The reference merges `service-defaults` / `proxy-defaults` config
entries into every locally-registered service as it registers
(agent/service_manager.go:19, agent/consul/config_endpoint.go
ResolveServiceConfig), serves the resolved view at the blocking
`GET /v1/agent/service/:id` endpoint `consul connect envoy` bootstraps
from (agent/http_register.go:43, agent/agent_endpoint.go AgentService),
and expands a nested `connect.sidecar_service {}` stanza into a fully
defaulted connect-proxy registration with a port allocated from
[sidecar_min_port, sidecar_max_port] (agent/sidecar_service.go:12).

This module is the store-functional core of that layer; the HTTP
routes in api/http.py call into it, and the `resolved_service_config`
cache type (agent/cache-types/resolved_service_config.go) wraps
`resolve_service_config`.
"""

from __future__ import annotations

import copy
from typing import Dict, List, Optional, Tuple

from consul_tpu.discoverychain import service_protocol

# the reference's default sidecar port range (agent/config/default.go
# sidecar_min_port/sidecar_max_port)
SIDECAR_MIN_PORT = 21000
SIDECAR_MAX_PORT = 21255


def sidecar_id_for(parent_id: str) -> str:
    """agent/sidecar_service.go sidecarIDForService."""
    return f"{parent_id}-sidecar-proxy"


def resolve_service_config(store, service: str,
                           upstreams: Tuple[str, ...] = ()) -> dict:
    """Resolved central config for `service` — the merge of
    proxy-defaults (global) under service-defaults (per-service), plus
    per-upstream protocols (ConfigEntry.ResolveServiceConfig,
    agent/consul/config_endpoint.go; structs.ServiceConfigResponse).

    Wire-shape (CamelCase) like the reference response; the opaque
    proxy-defaults Config map passes through verbatim.
    """
    pd = store.config_entry_get("proxy-defaults", "global") or {}
    sd = store.config_entry_get("service-defaults", service) or {}
    cfg = dict(pd.get("config") or {})
    proto = sd.get("protocol") or cfg.get("protocol") or "tcp"
    cfg["protocol"] = str(proto).lower()
    mode = sd.get("mode") or pd.get("mode") or ""
    # transparent_proxy settings ride with whichever entry set them;
    # service-defaults wins (config_entry.go:89,254)
    tproxy = sd.get("transparent_proxy") or pd.get("transparent_proxy") \
        or {}
    out = {
        "ProxyConfig": cfg,
        "Mode": mode,
        "TransparentProxy": dict(tproxy),
        "MeshGateway": dict(sd.get("mesh_gateway")
                            or pd.get("mesh_gateway") or {}),
        "Expose": copy.deepcopy(sd.get("expose") or pd.get("expose")
                                or {}),
        "UpstreamConfigs": {},
    }
    # per-upstream defaults: the upstream's own protocol, overlaid with
    # this service's service-defaults upstream_config overrides
    # (structs.UpstreamConfiguration).  The RAW snake block also rides
    # along (upstream-list independent) so merged_proxy can merge the
    # SAME central data per upstream without re-querying the store —
    # cached reads must not mix a stale proxy view with live upstream
    # config.
    uc = sd.get("upstream_config") or {}
    uc_defaults = {k: v for k, v in (uc.get("defaults") or {}).items()
                   if k != "name"}
    uc_over = {o.get("name", ""): {k: v for k, v in o.items()
                                   if k != "name"}
               for o in uc.get("overrides") or []}
    out["UpstreamConfigRaw"] = {"defaults": uc_defaults,
                                "overrides": uc_over}
    for up in upstreams:
        entry = {"Protocol": service_protocol(store, up)}
        for src in (uc_defaults, uc_over.get(up, {})):
            for k, v in src.items():
                entry[_camel_key(k)] = v
        out["UpstreamConfigs"][up] = entry
    return out


def _camel_key(k: str) -> str:
    return "".join(p.capitalize() or "_" for p in k.split("_"))


def merged_proxy(store, proxy: dict, service_name: str,
                 resolved: Optional[dict] = None) -> dict:
    """A connect-proxy registration's snake_case `proxy` dict with the
    central defaults for its DESTINATION service merged underneath
    (registration wins — service_manager.go mergeServiceConfig).

    Adds/normalizes: config (map), mode, transparent_proxy, expose,
    mesh_gateway.  The store keeps the raw registration; this merged
    view is what proxycfg / xDS / the agent endpoint consume.
    `resolved` short-circuits the central lookup (the
    resolved_service_config cache type feeds it on ?cached reads).
    """
    if resolved is None:
        resolved = resolve_service_config(store, service_name)
    out = dict(proxy)
    cfg = dict(resolved["ProxyConfig"])
    cfg.update(proxy.get("config") or {})
    out["config"] = cfg
    if not out.get("mode"):
        out["mode"] = resolved["Mode"]
    if not out.get("transparent_proxy"):
        out["transparent_proxy"] = resolved["TransparentProxy"]
    if not out.get("expose"):
        out["expose"] = _snake_expose(resolved["Expose"])
    if not out.get("mesh_gateway"):
        out["mesh_gateway"] = resolved["MeshGateway"]
    # per-upstream central defaults/overrides (service-defaults
    # upstream_config, structs.UpstreamConfiguration) merge UNDER each
    # upstream's own opaque config — this is how centrally-set
    # escape hatches (envoy_listener_json/envoy_cluster_json) and
    # limits reach xDS without touching every registration.  Snake
    # keys here (the consumers read snake); the data comes from the
    # SAME resolved view as the proxy-level merge above, so a cached
    # read stays internally consistent.
    raw = resolved.get("UpstreamConfigRaw") or {}
    uc_defaults = raw.get("defaults") or {}
    uc_over = raw.get("overrides") or {}
    if uc_defaults or uc_over:
        merged_ups = []
        for up in out.get("upstreams") or []:
            up = dict(up)               # never mutate the store's row
            central = dict(uc_defaults)
            central.update(uc_over.get(
                up.get("destination_name", ""), {}))
            central.update(up.get("config") or {})   # registration wins
            up["config"] = central
            merged_ups.append(up)
        out["upstreams"] = merged_ups
    return out


def _snake_expose(expose: dict) -> dict:
    """Expose blocks arrive from config entries already snake_case;
    pass through (helper exists so callers are explicit about shape)."""
    return copy.deepcopy(expose) if expose else {}


def expose_paths_by_port(expose: Optional[dict]
                         ) -> Dict[int, Dict[str, int]]:
    """{listener_port: {path: local_path_port}} over the
    fully-specified Expose.Paths entries — THE admission + grouping
    rule, shared by xds.listeners, xds.clusters, and the builtin
    ExposeListener so a half-specified entry (or two paths on one
    port) can never make the three diverge."""
    out: Dict[int, Dict[str, int]] = {}
    for p in (expose or {}).get("paths") or []:
        path = p.get("path", "")
        lport = p.get("listener_port", 0)
        lpp = p.get("local_path_port", 0)
        if path and lport and lpp:
            out.setdefault(lport, {})[path] = lpp
    return out


def allocate_sidecar_port(node_services: List[dict], sid: str = "",
                          min_port: int = SIDECAR_MIN_PORT,
                          max_port: int = SIDECAR_MAX_PORT) -> int:
    """Port for sidecar `sid`: an existing registration under the same
    id KEEPS its port (re-registration must not drift the listener),
    otherwise the first port in the range no service on this node
    claims (sidecarServiceFromNodeService port scan,
    agent/sidecar_service.go:97)."""
    for s in node_services:
        if sid and s.get("id") == sid and \
                min_port <= s.get("port", 0) <= max_port:
            return s["port"]
    used = {s.get("port", 0) for s in node_services}
    for p in range(min_port, max_port + 1):
        if p not in used:
            return p
    raise ValueError(
        f"no free sidecar port in [{min_port}, {max_port}]")


def expand_sidecar(body: dict, node_services: List[dict],
                   min_port: int = SIDECAR_MIN_PORT,
                   max_port: int = SIDECAR_MAX_PORT
                   ) -> Optional[Tuple[str, dict]]:
    """Expand `Connect.SidecarService` of a CamelCase registration body
    into a full connect-proxy registration (sid, body), or None when no
    stanza is present (agent/sidecar_service.go:12
    sidecarServiceFromNodeService).

    Defaults filled: ID/Name from the parent, port allocated from the
    sidecar range, Proxy.DestinationService* -> parent,
    LocalServicePort -> parent port, and the reference's two default
    checks (TCP on the proxy port + alias of the parent) unless the
    stanza carries its own.
    """
    connect = body.get("Connect") or {}
    stanza = connect.get("SidecarService")
    if stanza is None:
        return None
    stanza = dict(stanza)
    parent_id = body.get("ID") or body.get("Name")
    parent_name = body.get("Name", parent_id)
    sid = stanza.get("ID") or sidecar_id_for(parent_id)
    name = stanza.get("Name") or f"{parent_name}-sidecar-proxy"
    port = stanza.get("Port") or allocate_sidecar_port(
        node_services, sid, min_port, max_port)
    proxy = dict(stanza.get("Proxy") or {})
    proxy.setdefault("DestinationServiceName", parent_name)
    proxy.setdefault("DestinationServiceID", parent_id)
    proxy.setdefault("LocalServiceAddress", "127.0.0.1")
    if not proxy.get("LocalServicePort"):
        proxy["LocalServicePort"] = body.get("Port", 0)
    checks = stanza.get("Checks") or stanza.get("Check")
    if not checks:
        checks = [
            {"Name": "Connect Sidecar Listening",
             "CheckID": f"sidecar-listening:{sid}",
             "TCP": f"127.0.0.1:{port}", "Interval": "10s"},
            {"Name": f"Connect Sidecar Aliasing {parent_id}",
             "CheckID": f"sidecar-alias:{sid}",
             "AliasService": parent_id},
        ]
    elif isinstance(checks, dict):
        checks = [checks]
    out = {
        "Kind": "connect-proxy",
        "ID": sid,
        "Name": name,
        "Port": port,
        "Address": stanza.get("Address", body.get("Address", "")),
        "Tags": stanza.get("Tags") or list(body.get("Tags") or []),
        "Meta": stanza.get("Meta") or dict(body.get("Meta") or {}),
        "Proxy": proxy,
        "Checks": checks,
    }
    return sid, out
