"""Socket RPC boundary: raft transport + leader forwarding over TCP.

The reference's process boundary is a single TCP "server" port carrying
msgpack net/rpc, raft, and gRPC behind a first-byte protocol mux
(agent/consul/rpc.go:130 handleConn; conn pool agent/pool/pool.go:542).
Here one listener per server carries two frame types over length-prefixed
JSON — "raft" (fire-and-forget engine messages → RaftNode.deliver) and
"rpc" (request/response: forwarded applies, barriers, stats) — with a
pooled one-connection-per-peer client.
"""

from consul_tpu.rpc.net import (  # noqa: F401
    FaultyTcpTransport, NetFaultSchedule, RpcClient, RpcError, RpcListener,
    TcpTransport, recv_frame, send_frame,
)
