"""Framed-JSON TCP plumbing for the server RPC boundary.

Wire format: 4-byte big-endian length + UTF-8 JSON object.  Two frame
kinds (the first-byte mux of agent/consul/rpc.go:130 collapsed into a
"type" field):

    {"type": "raft", "msg": {...}}                 fire-and-forget
    {"type": "rpc", "id": n, "method": m, "args": {...}}   request
    {"type": "resp", "id": n, "result": ..., "error": ...} response

The raft engine's messages (AppendEntries / RequestVote / Install
Snapshot and acks) are already JSON-safe dicts (bytes ride latin-1 /
base64 in the command layer), so no extra codec is needed.
"""

from __future__ import annotations

import json
import random
import socket
import socketserver
import ssl
import struct
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from consul_tpu import telemetry, trace
from consul_tpu.consensus.raft import Transport
from consul_tpu.utils.net import shutdown_and_close

_MAX_FRAME = 64 << 20  # 64 MiB: snapshots ride InstallSnapshot frames

# the server-side endpoint table (server.py _handle_rpc): RPC metrics
# label by method, and the label value must come from THIS fixed set —
# labeling with the raw client-supplied string would let any peer mint
# unbounded registry entries with random method names
_KNOWN_METHODS = frozenset({"apply", "apply_batch", "barrier", "stats",
                            "auto_encrypt_sign", "auto_config"})


class RpcError(Exception):
    """Remote handler raised; message carries the remote error string."""


def send_frame(sock: socket.socket, obj: dict) -> None:
    data = json.dumps(obj).encode()
    sock.sendall(struct.pack(">I", len(data)) + data)


def recv_frame(sock: socket.socket) -> Optional[dict]:
    hdr = _recv_exact(sock, 4)
    if hdr is None:
        return None
    (ln,) = struct.unpack(">I", hdr)
    if ln > _MAX_FRAME:
        raise ValueError(f"frame too large: {ln}")
    data = _recv_exact(sock, ln)
    if data is None:
        return None
    return json.loads(data)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class RpcListener:
    """Per-server TCP listener: raft frames → deliver_fn, rpc frames →
    handler(method, args) → result (the server-side mux, rpc.go:130)."""

    def __init__(self, deliver_fn: Callable[[dict], None],
                 handler: Callable[[str, dict], dict],
                 host: str = "127.0.0.1", port: int = 0,
                 ssl_context: Optional[ssl.SSLContext] = None):
        outer = self
        self.ssl_context = ssl_context

        class _Handler(socketserver.BaseRequestHandler):
            # a byteless client must not park the handler inside the
            # TLS handshake forever: wrap_socket DETACHES the fd from
            # the accepted socket, so no external shutdown can reach
            # an in-flight handshake — a timeout is the only bound
            HANDSHAKE_TIMEOUT = 10.0

            def handle(self):
                sock = self.request
                if outer.ssl_context is not None:
                    # TLS upgrade per connection (tlsutil incoming);
                    # handshake failures end this connection only
                    try:
                        sock.settimeout(self.HANDSHAKE_TIMEOUT)
                        sock = outer.ssl_context.wrap_socket(
                            sock, server_side=True)
                        sock.settimeout(None)
                    except (ssl.SSLError, OSError):
                        return
                # register so stop() can WAKE this reader: daemon
                # threads parked in recv on established conns outlive
                # server_close and ride reused fd numbers otherwise
                with outer._live_lock:
                    outer._live.add(sock)
                raft_handed = False
                try:
                    while True:
                        frame = recv_frame(sock)
                        if frame is None:
                            return
                        kind = frame.get("type")
                        if kind == "raft":
                            if not raft_handed:
                                # consul.rpc.raft_handoff: counted once
                                # per CONNECTION carrying raft traffic
                                # (rpc.go:130's mux hands the conn off
                                # once), not per frame — per-frame
                                # counting tracked heartbeat volume and
                                # taxed every delivery with registry
                                # work
                                raft_handed = True
                                telemetry.incr_counter(
                                    ("rpc", "raft_handoff"))
                            outer.deliver_fn(frame["msg"])
                        elif kind == "rpc":
                            method = frame.get("method", "")
                            # consul.rpc.request + latency, labeled by
                            # method (rpc.go:815's per-request metric);
                            # unknown/garbage method names collapse to
                            # one "other" label so a hostile peer can't
                            # inflate registry cardinality
                            mlabel = {"method": method
                                      if method in _KNOWN_METHODS
                                      else "other"}
                            telemetry.incr_counter(("rpc", "request"),
                                                   labels=mlabel)
                            t0 = time.perf_counter()
                            tid = frame.get("trace")
                            tok = trace.set_current(tid) if tid else None
                            resp = {"type": "resp", "id": frame.get("id")}
                            try:
                                resp["result"] = outer.handler(
                                    method, frame.get("args") or {})
                            except Exception as e:
                                telemetry.incr_counter(
                                    ("rpc", "request_error"),
                                    labels=mlabel)
                                resp["error"] = f"{type(e).__name__}: {e}"
                            finally:
                                if tok is not None:
                                    trace.reset(tok)
                                telemetry.measure_since(
                                    ("rpc", "request_time"), t0,
                                    labels=mlabel)
                            send_frame(sock, resp)
                except (ConnectionError, ValueError, OSError):
                    return
                finally:
                    with outer._live_lock:
                        outer._live.discard(sock)

        self.deliver_fn = deliver_fn
        self.handler = handler
        self._live: set = set()
        self._live_lock = threading.Lock()
        self.server = socketserver.ThreadingTCPServer((host, port), _Handler,
                                                      bind_and_activate=False)
        self.server.allow_reuse_address = True
        self.server.daemon_threads = True
        self.server.server_bind()
        self.server.server_activate()
        self.addr: Tuple[str, int] = self.server.server_address
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()
        # wake every parked per-connection reader: their daemon
        # threads otherwise idle in (ssl) recv until the peer closes,
        # holding fd slots the kernel will reuse
        with self._live_lock:
            live = list(self._live)
        for sock in live:
            shutdown_and_close(sock)
        if self._thread:
            self._thread.join(timeout=5.0)


class _ConnPool:
    """One pooled connection per address, mutex-serialized requests
    (a miniature agent/pool/pool.go ConnPool), with optional TLS
    upgrade on connect (pool.go TLS wrap)."""

    def __init__(self, timeout: float = 5.0,
                 ssl_context: Optional[ssl.SSLContext] = None,
                 server_hostname: Optional[str] = None):
        self._conns: Dict[Tuple[str, int], socket.socket] = {}
        self._locks: Dict[Tuple[str, int], threading.Lock] = {}
        self._lock = threading.Lock()
        self.timeout = timeout
        self.ssl_context = ssl_context
        self.server_hostname = server_hostname
        # reconnect cooldown per address (see oneway): fire-and-forget
        # sends inside the window drop instead of re-dialing a peer
        # that just refused — the jittered-backoff half of the retry
        # policy, kept OFF the sender's thread (a raft tick thread
        # sleeping inline would stall every peer behind the dead one)
        self._down_until: Dict[Tuple[str, int], float] = {}
        self._last_cooldown: Dict[Tuple[str, int], float] = {}

    def _get_lock(self, addr) -> threading.Lock:
        with self._lock:
            if addr not in self._locks:
                self._locks[addr] = threading.Lock()
            return self._locks[addr]

    def _connect(self, addr) -> socket.socket:
        sock = self._conns.get(addr)
        if sock is not None:
            return sock
        sock = socket.create_connection(addr, timeout=self.timeout)
        sock.settimeout(self.timeout)
        if self.ssl_context is not None:
            sock = self.ssl_context.wrap_socket(
                sock, server_hostname=self.server_hostname or addr[0])
        self._conns[addr] = sock
        return sock

    def _drop(self, addr) -> None:
        sock = self._conns.pop(addr, None)
        if sock is not None:
            shutdown_and_close(sock)

    # bounded reconnect policy (the reference pool's acquire/retry
    # stance): a dead pooled socket is evicted and the send retried a
    # bounded number of times immediately (a severed-but-listening
    # peer reconnects on the spot); on exhaustion the address enters a
    # jittered reconnect COOLDOWN during which further fire-and-forget
    # sends drop without dialing — the backoff lives in the pool's
    # state, never as a sleep on the sender's thread (a raft tick
    # thread sleeping inline would stall every peer behind the dead
    # one, and raft re-sends on its own cadence anyway)
    ONEWAY_ATTEMPTS = 3
    COOLDOWN_BASE_S = 0.1
    COOLDOWN_CAP_S = 1.0

    def oneway(self, addr, obj: dict) -> None:
        """Fire-and-forget (raft frames).  Errors evict the pooled
        socket and retry within the bounded policy above; on
        exhaustion the frame drops, the address cools down, and
        consul.rpc.failed counts it."""
        lock = self._get_lock(addr)
        with lock:
            until = self._down_until.get(addr, 0.0)
            if until > time.monotonic():
                telemetry.incr_counter(("rpc", "failed"),
                                       labels={"kind": "oneway"})
                return
            for attempt in range(self.ONEWAY_ATTEMPTS):
                fresh_dial = addr not in self._conns
                try:
                    send_frame(self._connect(addr), obj)
                    self._down_until.pop(addr, None)
                    self._last_cooldown.pop(addr, None)
                    return
                except OSError:
                    self._drop(addr)       # evict the dead socket
                    if fresh_dial:
                        # a FRESH dial failed: more dials this call
                        # can only re-pay the connect timeout (a
                        # black-holed peer costs the full 5 s per SYN,
                        # not a fast RST) — stop and cool down.  The
                        # retry chain exists for STALE pooled sockets,
                        # whose send failures are immediate.
                        break
            # jittered, capped exponential cooldown: doubles while the
            # peer stays dark, resets on the first successful send
            prev = self._down_until.get(addr)
            base = self.COOLDOWN_BASE_S if prev is None else \
                min(self.COOLDOWN_CAP_S, 2.0 * self._last_cooldown.get(
                    addr, self.COOLDOWN_BASE_S))
            self._last_cooldown[addr] = base
            self._down_until[addr] = time.monotonic() \
                + base * (0.5 + random.random())
        telemetry.incr_counter(("rpc", "failed"), labels={"kind": "oneway"})

    def call(self, addr, obj: dict,
             timeout: Optional[float] = None) -> dict:
        lock = self._get_lock(addr)
        with lock:
            try:
                sock = self._connect(addr)
                if timeout is not None:
                    sock.settimeout(timeout)
                send_frame(sock, obj)
                # correlate on id: a stale response left by an earlier
                # timed-out call must not be handed to this caller
                while True:
                    resp = recv_frame(sock)
                    if resp is None:
                        break
                    if obj.get("id") is None or resp.get("id") == obj["id"]:
                        break
            except OSError as e:
                self._drop(addr)
                telemetry.incr_counter(("rpc", "failed"),
                                       labels={"kind": "call"})
                raise RpcError(f"rpc to {addr} failed: {e}") from e
            finally:
                if timeout is not None:
                    try:
                        sock.settimeout(self.timeout)
                    except (OSError, UnboundLocalError):
                        pass
            if resp is None:
                self._drop(addr)
                telemetry.incr_counter(("rpc", "failed"),
                                       labels={"kind": "call"})
                raise RpcError(f"rpc to {addr}: connection closed")
            return resp

    def close(self) -> None:
        with self._lock:
            for addr in list(self._conns):
                self._drop(addr)


class RpcClient:
    """Request/response calls to a peer's RpcListener."""

    def __init__(self, timeout: float = 5.0,
                 ssl_context: Optional[ssl.SSLContext] = None,
                 server_hostname: Optional[str] = None):
        self._pool = _ConnPool(timeout, ssl_context, server_hostname)
        self._next_id = 0
        self._id_lock = threading.Lock()

    def call(self, addr: Tuple[str, int], method: str, args: dict,
             timeout: Optional[float] = None) -> dict:
        with self._id_lock:
            self._next_id += 1
            rid = self._next_id
        obj = {"type": "rpc", "id": rid, "method": method, "args": args}
        # propagate the caller's trace id on the frame envelope (never
        # inside args — forwarded applies' args become raft commands
        # and must stay byte-identical across replicas)
        tid = trace.current_trace()
        if tid:
            obj["trace"] = tid
        t0 = time.perf_counter()
        try:
            resp = self._pool.call(tuple(addr), obj, timeout=timeout)
        finally:
            telemetry.measure_since(("rpc", "client", "request_time"), t0,
                                    labels={"method": method})
        if resp.get("error"):
            raise RpcError(resp["error"])
        return resp.get("result")

    def close(self) -> None:
        self._pool.close()


class TcpTransport(Transport):
    """Raft Transport over sockets: `addresses` maps node_id → (host, port)
    and is shared by every server in the cluster (the reference's router/
    server-lookup role).  send() is fire-and-forget like the engine
    expects; unknown/unreachable targets drop silently (raft retries)."""

    def __init__(self, addresses: Optional[Dict[str, Tuple[str, int]]] = None,
                 timeout: float = 5.0):
        # identity matters: the caller shares one (initially empty)
        # address book across transports — `or {}` would silently fork it
        self.addresses: Dict[str, Tuple[str, int]] = (
            addresses if addresses is not None else {})
        self._pool = _ConnPool(timeout)

    def set_tls(self, ssl_context: ssl.SSLContext,
                server_hostname: Optional[str] = None) -> None:
        """Upgrade outgoing raft connections to TLS (RaftLayer over the
        TLS'd server port).  Existing plaintext conns are dropped."""
        self._pool.close()
        self._pool.ssl_context = ssl_context
        self._pool.server_hostname = server_hostname

    def send(self, target: str, msg: dict) -> None:
        addr = self.addresses.get(target)
        if addr is None:
            return
        self._pool.oneway(tuple(addr), {"type": "raft", "msg": msg})

    def close(self) -> None:
        self._pool.close()


class NetFaultSchedule:
    """Seeded fault decisions for the live TCP path (the nemesis's
    third layer, chaos.py).  Each outgoing frame asks `decide(target)`
    for an action:

        "pass"            send normally
        "drop"            swallow the frame (raft re-sends)
        "sever"           evict the pooled connection AND drop — the
                          next frame reconnects (connection-reset
                          injection; exercises _ConnPool's bounded
                          retry path)
        ("delay", s)      sleep s before sending (head-of-line delay on
                          the pooled conn — frames behind it queue,
                          like a stalled kernel buffer)

    Targets in `cut` are hard-partitioned (every frame severs).  The
    decision STREAM is deterministic (one seeded RNG consumed in call
    order under a lock); with concurrent senders the interleaving is
    the scheduler's, which is as deterministic as a live socket path
    gets — the virtual-time layers carry the bit-reproducibility
    guarantee."""

    def __init__(self, seed: int = 0, drop_p: float = 0.0,
                 sever_p: float = 0.0, delay_p: float = 0.0,
                 delay_range: Tuple[float, float] = (0.005, 0.05)):
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.drop_p = drop_p
        self.sever_p = sever_p
        self.delay_p = delay_p
        self.delay_range = delay_range
        self.cut: set = set()           # node_ids hard-partitioned

    def partition(self, *targets: str) -> None:
        with self._lock:
            self.cut.update(targets)

    def heal(self, *targets: str) -> None:
        with self._lock:
            if targets:
                self.cut.difference_update(targets)
            else:
                self.cut.clear()

    def calm(self) -> None:
        """End probabilistic faults (partitions persist until heal)."""
        with self._lock:
            self.drop_p = self.sever_p = self.delay_p = 0.0

    def decide(self, target: str):
        with self._lock:
            if target in self.cut:
                return "sever"
            r = self._rng.random()
            if r < self.sever_p:
                return "sever"
            r -= self.sever_p
            if r < self.drop_p:
                return "drop"
            r -= self.drop_p
            if r < self.delay_p:
                lo, hi = self.delay_range
                return ("delay", lo + self._rng.random() * (hi - lo))
            return "pass"


class FaultyTcpTransport(TcpTransport):
    """TcpTransport that routes every outgoing raft frame through a
    NetFaultSchedule — the socket-path injector of the nemesis engine
    (chaos.py drives all three layers through the same scenario API).
    Severing evicts the pooled connection via the pool's own eviction,
    so the next healthy frame exercises the reconnect/backoff path the
    way a real RST would."""

    def __init__(self, faults: NetFaultSchedule,
                 addresses: Optional[Dict[str, Tuple[str, int]]] = None,
                 timeout: float = 5.0):
        super().__init__(addresses, timeout)
        self.faults = faults

    def sever(self, target: str) -> None:
        """Drop the pooled connection to `target` now (one-shot)."""
        addr = self.addresses.get(target)
        if addr is not None:
            with self._pool._lock:
                self._pool._drop(tuple(addr))

    def send(self, target: str, msg: dict) -> None:
        act = self.faults.decide(target)
        if act == "drop":
            return
        if act == "sever":
            self.sever(target)
            return
        if isinstance(act, tuple) and act[0] == "delay":
            # head-of-line delay injection IS the fault being modeled
            # lint: ok=blocking-call (nemesis delay fault on purpose)
            time.sleep(act[1])
        super().send(target, msg)
