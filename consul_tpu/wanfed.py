"""WAN federation through mesh gateways (wanfed).

The reference can route ALL cross-DC traffic — WAN gossip and RPC —
through mesh gateways instead of requiring every server to reach every
remote server directly (agent/consul/wanfed/wanfed.go:39 NewTransport,
gateway_locator.go, config `connect.enable_mesh_gateway_wan_federation`).
Remote DCs are then addressed by their gateways, which are discovered
from replicated federation states.

Host-side equivalent here:

  * `MeshGatewayForwarder` — the gateway's federation data plane: a TCP
    listener that splices every accepted connection to the local DC's
    serving address (the reference's gateway does the same forwarding
    via SNI/ALPN routing; a single local target suffices because one
    handle fronts each DC here).
  * `gateway_address(store, dc)` — the GatewayLocator: pick the target
    DC's gateway from the LOCAL store's replicated federation states.
  * The HTTP layer's ?dc= forwarding consults the locator when
    `wan_fed_via_gateways` is on, so dc1 reaches dc2 with NO direct
    route to dc2's servers — only dc2's gateway is dialed.
"""

from __future__ import annotations

import socket

from consul_tpu.utils.net import shutdown_and_close
import threading
from typing import Optional, Tuple


class MeshGatewayForwarder:
    """Federation data plane of one mesh gateway: accept → connect to
    the local serving address → splice bytes both ways until either
    side closes.

    Subclass hooks (the live nemesis's `chaos_live.LinkProxy` builds
    its toxiproxy-style link interposer on this same machinery):
    `_admit()` gates each accepted connection, `_pre_forward(data)`
    gates/paces each spliced chunk — both default to pass-through.

    Observability (ISSUE 15) is opt-in via `dc`: a gateway that knows
    which datacenter it fronts emits the WAN SLIs — per-splice
    `consul.wanfed.gateway.{active,bytes,dial_ms}{gateway,dc}` and
    `wanfed.splice.{opened,failed}` flight events, with the splice's
    trace id sniffed from the spliced request's X-Consul-Trace-Id
    header (the envelope hop: a cross-DC write's trace must survive
    the gateway, not die at the TCP boundary).  The chaos LinkProxy
    interposer passes no dc and stays silent — a seeded scenario's
    event journal must remain byte-identical across replays, and raft
    heartbeat splices would wash the ring."""

    def __init__(self, target_host: str, target_port: int,
                 host: str = "127.0.0.1", port: int = 0,
                 dc: Optional[str] = None, gw_name: str = "gateway"):
        self.target = (target_host, target_port)
        self.dc = dc                # None = observability off
        self.gw_name = gw_name
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((host, port))
        self._lsock.listen(64)
        self.host, self.port = self._lsock.getsockname()
        self._running = False
        self._stopped = False
        self._accept_thread: Optional[threading.Thread] = None
        # live splice threads, joined on stop so no pump outlives us
        self._pumps: list = []
        # live spliced sockets: stop() must shut these down or a pump
        # parked in recv() on a healthy conn outlives the gateway
        # (thread leak + a splice that keeps moving bytes after
        # "death" — the live nemesis kills gateways mid-transfer)
        self._conns: set = set()
        self._conns_lock = threading.Lock()

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        self._running = True
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    def stop(self) -> None:
        """Idempotent, callable mid-transfer: closes the listener,
        tears down every live splice (waking pumps parked in recv),
        and joins all pump threads — no thread survives stop()."""
        already = self._stopped
        self._stopped = True
        self._running = False
        if not already:
            shutdown_and_close(self._lsock)
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5.0)
            self._accept_thread = None
        self._close_live()
        for t in self._pumps:
            t.join(timeout=2.0)
        self._pumps = [t for t in self._pumps if t.is_alive()]

    def _close_live(self) -> None:
        """Tear down every live splice, waking pumps parked in recv."""
        with self._conns_lock:
            live = list(self._conns)
            self._conns.clear()
        for sock in live:
            shutdown_and_close(sock)

    @property
    def address(self) -> Tuple[str, int]:
        return (self.host, self.port)

    # ------------------------------------------------------------- data path

    # ----------------------------------------------------- subclass hooks

    def _admit(self) -> bool:
        """May this accepted connection splice?  (LinkProxy: False
        while the link is severed.)"""
        return True

    def _pre_forward(self, data: bytes) -> bool:
        """Called per spliced chunk before forwarding; return False to
        kill the splice.  (LinkProxy: sever check + delay fault.)"""
        return True

    # ------------------------------------------------------------ WAN SLIs

    def _gauge_active(self) -> None:
        """consul.wanfed.gateway.active: live splices through this
        gateway (each splice holds two sockets in the live set)."""
        from consul_tpu import telemetry
        with self._conns_lock:
            n = len(self._conns) // 2
        telemetry.set_gauge(("wanfed", "gateway", "active"), float(n),
                            labels={"gateway": self.gw_name,
                                    "dc": self.dc})

    @staticmethod
    def _sniff_trace(data: bytes) -> str:
        """Best-effort X-Consul-Trace-Id from the first spliced chunk
        (cross-DC hops are HTTP; the header rides in the first frame).
        Returns "" when absent/invalid — an unparseable splice still
        journals, just uncorrelated."""
        low = data[:4096].lower()
        i = low.find(b"x-consul-trace-id:")
        if i < 0:
            return ""
        val = data[i + len(b"x-consul-trace-id:"):]
        val = val.split(b"\r\n", 1)[0].split(b"\n", 1)[0].strip()
        try:
            from consul_tpu import trace
            return trace.sanitize_id(val.decode("latin-1")) or ""
        except UnicodeDecodeError:
            return ""

    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _ = self._lsock.accept()
            except OSError:
                return  # listener closed
            if not self._admit():
                conn.close()
                continue
            import time as _time
            t0 = _time.perf_counter()
            try:
                upstream = socket.create_connection(self.target,
                                                    timeout=10.0)
            except OSError as e:
                conn.close()
                if self.dc is not None:
                    from consul_tpu import flight
                    flight.emit("wanfed.splice.failed",
                                labels={"gateway": self.gw_name,
                                        "dc": self.dc,
                                        "error": type(e).__name__},
                                trace_id="")
                continue
            if self.dc is not None:
                from consul_tpu import telemetry
                telemetry.add_sample(
                    ("wanfed", "gateway", "dial_ms"),
                    (_time.perf_counter() - t0) * 1000.0,
                    labels={"gateway": self.gw_name, "dc": self.dc})
            # prune finished pumps first: a long-lived gateway must not
            # accumulate two Thread objects per connection forever
            self._pumps = [t for t in self._pumps if t.is_alive()]
            with self._conns_lock:
                if not self._running:
                    # lost the race with stop(): it already swept
                    # _conns, so these two would leak open forever
                    conn.close()
                    upstream.close()
                    return
                self._conns.update((conn, upstream))
            if self.dc is not None:
                self._gauge_active()
            # the client→upstream pump sniffs the splice envelope (the
            # request headers cross first, carrying the trace id)
            for a, b, sniff in ((conn, upstream, True),
                                (upstream, conn, False)):
                t = threading.Thread(target=self._pump,
                                     args=(a, b, sniff),
                                     daemon=True)
                t.start()
                self._pumps.append(t)

    def _pump(self, src: socket.socket, dst: socket.socket,
              sniff: bool = False) -> None:
        observed = self.dc is not None
        first = sniff and observed
        try:
            while True:
                data = src.recv(65536)
                if not data:
                    break
                if first:
                    # one wanfed.splice.opened per splice, correlated
                    # to the spliced request's own trace id — the
                    # gateway leg of the cross-DC visibility trace
                    first = False
                    from consul_tpu import flight
                    flight.emit("wanfed.splice.opened",
                                labels={"gateway": self.gw_name,
                                        "dc": self.dc},
                                trace_id=self._sniff_trace(data))
                if not self._pre_forward(data):
                    break
                if observed:
                    from consul_tpu import telemetry
                    telemetry.incr_counter(
                        ("wanfed", "gateway", "bytes"), float(len(data)),
                        labels={"gateway": self.gw_name, "dc": self.dc})
                dst.sendall(data)
        except OSError:
            pass
        finally:
            # half-close so the peer's pump drains and exits too; when
            # BOTH directions have half-closed the conns drop from the
            # live set (each side's pump closes its read end)
            for s, how in ((dst, socket.SHUT_WR), (src, socket.SHUT_RD)):
                try:
                    s.shutdown(how)
                except OSError:
                    pass
            with self._conns_lock:
                self._conns.discard(src)
            if observed:
                self._gauge_active()


def gateway_address(store, dc: str) -> Optional[Tuple[str, int]]:
    """GatewayLocator: the first known mesh gateway of `dc` from the
    locally replicated federation states (gateway_locator.go picks from
    fallback + primary gateways; federation states replicate DC→gateway
    lists)."""
    fs = store.federation_state_get(dc)
    if not fs:
        return None
    for gw in fs.get("mesh_gateways", []):
        addr, port = gw.get("address", ""), gw.get("port", 0)
        if addr and port:
            return (addr, port)
    return None
