"""Network segments: LAN gossip sharded into isolated pools.

The reference shards the LAN gossip plane into *network segments* —
each segment is its own serf pool with its own port, clients join
exactly one segment, servers join all of them and bridge
(agent/consul/segment_oss.go, server.go:254-258 segmentLAN, flooding
agent/consul/flood.go:12-27; SURVEY §2.2).  Failure detection and event
dissemination stay segment-local; the servers' catalog is the global
view.

TPU mapping: one device-resident serf pool (GossipOracle) per segment —
the pools are independent SWIM/serf tensor sims, exactly like the
reference's per-segment serf instances.  The SegmentedOracle presents
the combined membership as ONE oracle-shaped surface (members/status/
kill/revive/events/keyring), so the Agent/HTTP layers work unchanged;
`?segment=` filters where the reference filters.

Default segment name is "" (the reference's unnamed default segment
`<default>`); user events fire into every segment because servers
re-broadcast them across segments (flood.go's role for joins; events
ride the servers' WAN/bridge path).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from consul_tpu.config import GossipConfig, SimConfig
from consul_tpu.oracle import GossipOracle

DEFAULT_SEGMENT = ""


class SegmentedOracle:
    """Oracle-shaped facade over one GossipOracle per segment."""

    def __init__(self, segments: Dict[str, Tuple[GossipConfig,
                                                 SimConfig]]):
        if not segments:
            raise ValueError("at least one segment required")
        self.pools: Dict[str, GossipOracle] = {}
        for seg, (gossip, sim) in segments.items():
            prefix = f"{seg}-node" if seg else "node"
            self.pools[seg] = GossipOracle(gossip, sim,
                                           node_prefix=prefix)

    # ------------------------------------------------------------ lifecycle

    def start(self, tick_seconds: float = 0.0) -> None:
        for p in self.pools.values():
            p.start(tick_seconds)

    def stop(self) -> None:
        for p in self.pools.values():
            p.stop()

    def advance(self, n_ticks: int = 1) -> None:
        for p in self.pools.values():
            p.advance(n_ticks)

    # ------------------------------------------------------------- identity

    def segments(self) -> List[str]:
        return sorted(self.pools)

    def _pool_of(self, name: str) -> Tuple[str, GossipOracle]:
        for seg, p in self.pools.items():
            if name in p._ids:
                return seg, p
        raise KeyError(name)

    def node_id(self, name: str) -> int:
        return self._pool_of(name)[1].node_id(name)

    # ----------------------------------------------------------- membership

    def members(self, limit: Optional[int] = None, offset: int = 0,
                segment: Optional[str] = None) -> List[dict]:
        """Combined member list; `segment` restricts to one pool (the
        reference's ?segment= filter / members -segment).  Pagination
        spans pools in sorted-segment order."""
        order = sorted(self.pools)
        if segment is not None:
            if segment not in self.pools:
                raise KeyError(f"unknown segment {segment!r}")
            ns = order.index(segment)
            rows = self.pools[segment].members(limit=limit,
                                               offset=offset)
            return [dict(r, segment=segment, addr_ns=ns) for r in rows]
        out: List[dict] = []
        remaining_offset = max(0, offset)
        budget = limit
        for ns, seg in enumerate(order):
            p = self.pools[seg]
            # provisioned count, not slot count: sparse pools list only
            # members that ever joined, and page math must match
            n = p.provisioned_count
            if remaining_offset >= n:
                remaining_offset -= n
                continue
            rows = p.members(limit=budget, offset=remaining_offset)
            # addr_ns namespaces the synthetic member address: per-pool
            # ids restart at 0, so without it node0 and alpha-node0
            # would collide on the same Addr
            out += [dict(r, segment=seg, addr_ns=ns) for r in rows]
            remaining_offset = 0
            if budget is not None:
                budget -= len(rows)
                if budget <= 0:
                    break
        return out

    def members_summary(self) -> Dict[str, int]:
        total: Dict[str, int] = {"alive": 0, "failed": 0, "left": 0,
                                 "total": 0}
        for p in self.pools.values():
            for k, v in p.members_summary().items():
                total[k] = total.get(k, 0) + v
        return total

    def members_delta(self, max_changes: int = 256) -> dict:
        """Changed members since the last delta checkpoint across every
        segment pool (GossipOracle.members_delta — the gather-free
        incremental read): `changed` rows are (segment, id, status)."""
        out = {"count": 0, "changed": [], "truncated": False}
        for seg in sorted(self.pools):
            d = self.pools[seg].members_delta(max_changes)
            out["count"] += d["count"]
            out["changed"] += [(seg, i, st) for i, st in d["changed"]]
            out["truncated"] = out["truncated"] or d["truncated"]
        return out

    def journal_flaps(self, max_changes: int = 256) -> int:
        """Flight-recorder flap feed across every segment pool
        (GossipOracle.journal_flaps — O(flaps) rows per pool)."""
        return sum(p.journal_flaps(max_changes)
                   for p in self.pools.values())

    def publish_sim_metrics(self, registry=None) -> Dict[str, float]:
        """Per-segment consul.serf.* gauges, labeled {segment=…} (the
        reference reports serf metrics per LAN segment pool), plus
        each pool's flap journal feeding the flight recorder.  Returns
        the LAST pool's raw metrics dict for API parity."""
        from consul_tpu import telemetry
        reg = registry or telemetry.default_registry()
        m: Dict[str, float] = {}
        for seg in sorted(self.pools):
            p = self.pools[seg]
            m = p.sim_metrics()
            for name, v in m.items():
                reg.set_gauge(("serf",) + tuple(name.split(".")), v,
                              labels={"segment": seg or "default"})
            p.journal_flaps()
        return m

    def status(self, name: str) -> str:
        return self._pool_of(name)[1].status(name)

    def believed_down_fraction(self, name: str) -> float:
        return self._pool_of(name)[1].believed_down_fraction(name)

    def kill(self, name: str) -> None:
        self._pool_of(name)[1].kill(name)

    def revive(self, name: str) -> None:
        self._pool_of(name)[1].revive(name)

    def leave(self, name: str) -> None:
        self._pool_of(name)[1].leave(name)

    # ---------------------------------------------------------- coordinates
    # Coordinates are per-segment planes (lib/rtt.go CoordinateSet keyed
    # by segment): cross-segment distances are undefined.

    def coordinate(self, name: str) -> dict:
        seg, p = self._pool_of(name)
        return dict(p.coordinate(name), segment=seg)

    def rtt(self, a: str, b: str) -> float:
        seg_a, pa = self._pool_of(a)
        seg_b, _ = self._pool_of(b)
        if seg_a != seg_b:
            raise KeyError(
                f"nodes {a!r}/{b!r} are in different segments "
                f"({seg_a!r} vs {seg_b!r}): no shared coordinate plane")
        return pa.rtt(a, b)

    def sort_by_rtt(self, origin: str, names: List[str]) -> List[str]:
        """Same-segment names sort by coordinate distance; foreign-
        segment names keep their order at the tail (Intersect returns
        zero distance only for comparable planes)."""
        try:
            seg, pool = self._pool_of(origin)
        except KeyError:
            return list(names)
        local = [n for n in names if n in pool._ids]
        foreign = [n for n in names if n not in pool._ids]
        return pool.sort_by_rtt(origin, local) + foreign

    # --------------------------------------------------------------- events

    def fire_event(self, name: str, payload: bytes, origin: str) -> str:
        """User events reach every segment (servers re-broadcast across
        the pools they bridge)."""
        ids = []
        for seg in sorted(self.pools):
            p = self.pools[seg]
            org = origin if origin in p._ids else \
                p.node_name(0)
            ids.append(p.fire_event(name, payload, origin=org))
        return ids[0] if ids else "0"

    def event_list(self) -> List[dict]:
        # the default segment's ring is authoritative for listing (every
        # event was fired into all pools)
        first = sorted(self.pools)[0]
        return self.pools[first].event_list()

    def event_coverage(self, event_id) -> float:
        vals = [p.event_coverage(event_id) for p in self.pools.values()]
        return min(vals) if vals else 0.0

    # -------------------------------------------------------------- keyring
    # one keyring for the whole cluster (keyring ops broadcast to every
    # segment pool, agent/keyring.go)

    def keyring_list(self) -> dict:
        first = sorted(self.pools)[0]
        out = self.pools[first].keyring_list()
        out["NumNodes"] = self.n_nodes
        return out

    def keyring_install(self, key: str) -> None:
        for p in self.pools.values():
            p.keyring_install(key)

    def keyring_use(self, key: str) -> None:
        for p in self.pools.values():
            p.keyring_use(key)

    def keyring_remove(self, key: str) -> None:
        for p in self.pools.values():
            p.keyring_remove(key)

    # ----------------------------------------------------------------- misc

    @property
    def tick(self) -> int:
        return max(p.tick for p in self.pools.values())

    @property
    def n_nodes(self) -> int:
        return sum(p.n_nodes for p in self.pools.values())
